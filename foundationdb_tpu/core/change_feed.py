"""Change feeds — versioned streaming change capture over packed batches.

Reference: REF:fdbserver/storageserver.actor.cpp (changeFeedStreamQ /
ChangeFeedInfo) + REF:fdbclient/SystemData.cpp changeFeedPrefix — a feed
is a durable, version-ordered stream of every committed mutation inside
a key range, served by the storage servers that own the range.  Upstream
built the subsystem as the backbone of its blob/backup pipeline; here it
is the serving path for derived readers (caches, indexes, replication
fan-out) the ROADMAP north star needs.

The storage server already holds every mutation it applies as a packed
``MutationBatch`` (PROTOCOL_VERSION 712); a feed retains *index slices*
of those batches (``MutationBatch.select`` — the identity slice is
zero-copy), so N subscribers cost one retained reference per version,
never a re-materialized ``Mutation`` list.

Retention model:

- entries newer than the storage durable floor live in memory only and
  ROLL BACK with the MVCC window on recovery (they came from a log
  generation's possibly-unacked suffix — exactly-once delivery depends
  on this);
- every sealed entry at or below the durable floor spills to a
  DiskQueue-backed side queue (one per storage server, frames tagged
  by feed id) BEFORE the TLog pop drops its replay copy, and is re-read
  on demand — the spill-by-reference discipline of the TLog, promoted
  to a durability obligation;
- ``pop`` advances a consumer's durable low-water mark: entries at or
  below it are discarded and the side queue's dead prefix is released.
"""

from __future__ import annotations

import bisect
import dataclasses

from .data import SYSTEM_PREFIX, KeyRange, MutationBatch, Version

__all__ = ["ChangeFeedStreamRequest", "ChangeFeedStreamReply",
           "FeedState", "ChangeFeedStore",
           "WHOLE_DB_BEGIN", "WHOLE_DB_END"]

# Whole-database feeds (ISSUE 8): a feed may cover the ENTIRE user
# keyspace, \xff-exclusively — the backbone of the feed-native backup.
# System writes are excluded at capture (every feed range ends at or
# below \xff and capture clips to it), registration/pop/destroy markers
# route to ALL current owners through the proxy's tags_for_range over
# the live shard map, and DD splits/moves keep routing via the
# fetch_feed_state handoff exactly as for ranged feeds.
WHOLE_DB_BEGIN: bytes = b""
WHOLE_DB_END: bytes = SYSTEM_PREFIX


@dataclasses.dataclass
class ChangeFeedStreamRequest:
    """One long-poll of a feed cursor (ChangeFeedStreamRequest,
    REF:fdbclient/StorageServerInterface.h).  ``begin_version`` is the
    consumer's resume cursor: the reply carries every retained entry at
    versions in [begin_version, end_version)."""
    feed_id: bytes
    begin_version: Version
    byte_limit: int = 0


@dataclasses.dataclass
class ChangeFeedStreamReply:
    """``entries`` is [(version, MutationBatch)] in version order.
    ``end_version`` is the heartbeat: the consumer owns everything below
    it for this shard, even when ``entries`` is empty — that is what
    lets a cursor resume exactly-once after a storage failover.
    ``popped_version`` echoes the feed's durable low-water mark.
    ``ranges`` lists the feed subranges THIS server currently serves
    (its shard ∩ the feed range, minus handed-off pieces): the cursor
    requires its polled replies to jointly cover the whole feed range
    before advancing — a stale shard map after a split would otherwise
    silently miss the moved half (no error ever fires: the old owner
    answers happily for the keys it kept)."""
    entries: list
    end_version: Version
    popped_version: Version
    ranges: list | None = None


class FeedState:
    """One feed's retained window on one storage server."""

    __slots__ = ("feed_id", "range", "register_version", "popped_version",
                 "versions", "batches", "sizes", "start", "mem_bytes",
                 "spilled", "spilled_bytes", "fence", "excluded")

    def __init__(self, feed_id: bytes, begin: bytes, end: bytes,
                 register_version: Version,
                 popped_version: Version = 0) -> None:
        self.feed_id = feed_id
        self.range = KeyRange(begin, end)
        self.register_version = register_version
        self.popped_version = popped_version
        # in-memory retained entries, version-ascending (amortized-trim
        # start index, the _TagStore pattern)
        self.versions: list[Version] = []
        self.batches: list[MutationBatch] = []
        self.sizes: list[int] = []
        self.start = 0
        self.mem_bytes = 0
        # spilled entries: (version, frame_start, frame_end, nbytes),
        # version-ascending, strictly older than every in-memory entry
        self.spilled: list[tuple[Version, int, int, int]] = []
        self.spilled_bytes = 0
        # set when this server's ENTIRE intersection with the feed was
        # relinquished (live move handoff): streams above the fence
        # refuse with wrong_shard_server so consumers re-route to the
        # destination
        self.fence: Version | None = None
        # subranges handed off by PARTIAL drops (a split moving only the
        # suffix), as (drop version, begin, end): the destination is
        # authoritative for them — this server filters them out of
        # capture AND serving, so the consumer's per-shard merge never
        # sees a mutation twice.  Versioned so a rolled-back drop
        # (recovery clamping an unacked flip) un-excludes.
        self.excluded: list[tuple[Version, bytes, bytes]] = []

    def retain(self, version: Version, batch: MutationBatch) -> None:
        self.versions.append(version)
        self.batches.append(batch)
        nb = batch.nbytes
        self.sizes.append(nb)
        self.mem_bytes += nb

    def entry_count(self) -> int:
        return len(self.versions) - self.start + len(self.spilled)

    def pop(self, version: Version) -> None:
        """Advance the low-water mark; discard retained entries <= it."""
        if version <= self.popped_version:
            return
        self.popped_version = version
        i = bisect.bisect_right(self.versions, version)
        if i > self.start:
            self.mem_bytes -= sum(self.sizes[self.start:i])
            self.start = i
        if self.start > 64 and self.start * 2 > len(self.versions):
            del self.versions[:self.start]
            del self.batches[:self.start]
            del self.sizes[:self.start]
            self.start = 0
        keep = [e for e in self.spilled if e[0] > version]
        if len(keep) != len(self.spilled):
            self.spilled_bytes = sum(e[3] for e in keep)
            self.spilled = keep

    def rollback_after(self, version: Version) -> None:
        """Discard in-memory entries newer than ``version`` (storage
        rejoin: the unacked suffix of a dead log generation rolls back
        before any consumer could be handed it).  Spilled entries never
        need rolling back — spill is gated at the durable floor, and a
        replica whose durable floor exceeds the recovery version is
        discarded outright (StorageServer.rejoin)."""
        while len(self.versions) > self.start \
                and self.versions[-1] > version:
            self.versions.pop()
            self.batches.pop()
            self.mem_bytes -= self.sizes.pop()

def _subtract_ranges(pieces: list[tuple[bytes, bytes]],
                     excluded: list[tuple[Version, bytes, bytes]]
                     ) -> list[tuple[bytes, bytes]]:
    """Subtract every excluded (version, begin, end) subrange from the
    piece list — the one home of the interval arithmetic shared by
    clear-clipping and serving-range computation."""
    for _v, b, e in excluded:
        nxt = []
        for cb, ce in pieces:
            if ce <= b or e <= cb:
                nxt.append((cb, ce))
                continue
            if cb < b:
                nxt.append((cb, b))
            if e < ce:
                nxt.append((e, ce))
        pieces = nxt
    return pieces


def _filter_excluded(batch: MutationBatch,
                     excluded: list[tuple[Version, bytes, bytes]]
                     ) -> MutationBatch:
    """Drop/clip ops inside handed-off subranges: SETs on excluded keys
    vanish, CLEARs are clipped around every excluded subrange (the
    destination delivers its own copy for those keys — without the
    clip, a range clear spanning the split point would reach the
    consumer from both shards).  Returns the ORIGINAL object untouched
    when nothing matches."""
    if not excluded:
        return batch
    from .data import MutationBatchBuilder
    builder = MutationBatchBuilder()
    changed = False
    for t, p1, p2 in batch.iter_ops():
        if t == 0:
            if any(b <= p1 < e for _v, b, e in excluded):
                changed = True
                continue
            builder.add(t, p1, p2)
        else:
            pieces = _subtract_ranges([(p1, p2)], excluded)
            if pieces != [(p1, p2)]:
                changed = True
            for cb, ce in pieces:
                builder.add(t, cb, ce)
    if not changed:
        return batch
    return builder.finish()


class ChangeFeedStore:
    """Every feed hosted by one storage server + the shared spill queue.

    ``capture`` is the apply-path hook: synchronous, zero-cost when no
    feed is armed.  Disk-touching surfaces (``read`` of a spilled
    prefix, ``maybe_spill``) are async and run from the storage role's
    read/durability paths.
    """

    def __init__(self, queue=None) -> None:
        self.feeds: dict[bytes, FeedState] = {}
        self.queue = queue          # DiskQueue side file when durable
        # spill frames in offset order: (start, end, feed_id, version);
        # the dead prefix (popped/destroyed feeds) is released via pop_to
        self._frames: list[tuple[int, int, bytes, Version]] = []
        # cached segment decomposition of the armed feed ranges (the
        # capture hook's one-interval-pass index, ROADMAP PR 4 (c)):
        # (key, boundaries, covering-feed lists); rebuilt whenever the
        # eligible feed set or its clipped ranges change
        self._seg_cache: tuple | None = None
        # serializes stream reads against spills: a read's disk awaits
        # must not interleave with maybe_spill moving entries between
        # the memory window and the spilled list, or the read's stale
        # snapshot loses (or doubles) exactly the moved versions
        self._io_lock = None
        self.streams_served = 0
        self.total_captured = 0

    def _lock(self):
        import asyncio
        if self._io_lock is None:   # lazily: the store may be built
            self._io_lock = asyncio.Lock()   # outside a running loop
        return self._io_lock

    # --- lifecycle markers (applied from the tag's mutation stream) ---

    def register(self, feed_id: bytes, begin: bytes, end: bytes,
                 version: Version) -> None:
        """Idempotent: a re-delivered marker (recovery replay) is a no-op.
        The range is clamped \\xff-exclusive — system writes must never
        enter a feed even if a forged/corrupt registration names them
        (the client and proxy already enforce this; defense in depth)."""
        if feed_id in self.feeds:
            return
        end = min(end, SYSTEM_PREFIX)
        if begin >= end:
            return
        self.feeds[feed_id] = FeedState(feed_id, begin, end, version)

    def destroy(self, feed_id: bytes) -> None:
        self.feeds.pop(feed_id, None)

    def pop(self, feed_id: bytes, version: Version) -> None:
        f = self.feeds.get(feed_id)
        if f is not None:
            f.pop(version)

    def fence(self, version: Version, begin: bytes, end: bytes,
              remaining: KeyRange | None = None) -> None:
        """The shard relinquished [begin, end) as of ``version``.

        A feed whose ENTIRE intersection with this server's remaining
        range is gone hard-fences: streams above ``version`` refuse
        with wrong_shard_server and consumers re-route to the
        destination (which received the retained window via
        fetch_feed_state).  A PARTIAL handoff (a split moving only the
        suffix) instead EXCLUDES the moved subrange: this server keeps
        serving the feed for the keys it still owns, while the
        destination is authoritative for the moved keys at every
        version — so the consumer's per-shard merge sees each mutation
        exactly once."""
        for f in self.feeds.values():
            if not (f.range.begin < end and begin < f.range.end):
                continue
            if remaining is not None and not remaining.empty \
                    and remaining.begin < f.range.end \
                    and f.range.begin < remaining.end:
                f.excluded.append((version, begin, end))
            else:
                f.fence = version if f.fence is None \
                    else min(f.fence, version)

    def rollback_after(self, version: Version) -> None:
        for fid in [fid for fid, f in self.feeds.items()
                    if f.register_version > version]:
            del self.feeds[fid]
        for f in self.feeds.values():
            f.rollback_after(version)
            if f.fence is not None and f.fence > version:
                f.fence = None
            if any(v > version for v, _b, _e in f.excluded):
                f.excluded = [x for x in f.excluded if x[0] <= version]

    # --- the capture hook (storage apply path) ---

    def capture(self, version: Version, batch: MutationBatch,
                shard: KeyRange | None = None) -> None:
        """Retain this version's slice of ``batch`` for every armed feed
        whose range it touches.  ``batch`` holds only plain SET/CLEAR
        ops (the apply path feeds the packed fast-path batch directly,
        and builds an effective batch of resolved atomics otherwise).

        ``shard`` clips the capture to this server's owned range: a
        CLEAR spanning a shard boundary inside the feed range arrives
        on EVERY overlapping tag's stream, and without the clip the
        consumer's per-shard merge would deliver it once per shard —
        each server must capture only the piece it answers for (the
        same contract ``serving_ranges`` advertises)."""
        if not self.feeds or not batch:
            return
        # eligibility is per (feed, version): cheap O(feeds) each call
        elig: list[tuple[bytes, bytes, FeedState]] = []
        for f in self.feeds.values():
            if version <= f.register_version or version <= f.popped_version:
                continue
            if f.fence is not None and version > f.fence:
                continue
            rb, re_ = f.range.begin, f.range.end
            if shard is not None:
                rb, re_ = max(rb, shard.begin), min(re_, shard.end)
                if rb >= re_:
                    continue
            elig.append((rb, re_, f))
        if not elig:
            return
        # ONE interval pass over the batch (ROADMAP PR 4 (c)): the
        # eligible feed ranges decompose into disjoint segments (cached
        # across applies while the feed set is stable), each op bisects
        # into its segment(s) once, and the covering feeds collect op
        # INDICES — so a server hosting many overlapping feeds scans the
        # batch once, not once per feed.  Per-feed slice assembly
        # (select + boundary clip) is unchanged.
        bounds, cover = self._segments(elig)
        idxs: list[list[int]] = [[] for _ in elig]
        last = [-1] * len(elig)
        nseg = len(cover)
        for i, (t, p1, p2) in enumerate(batch.iter_ops()):
            if t == 0:
                s = bisect.bisect_right(bounds, p1) - 1
                if 0 <= s < nseg:
                    for fpos in cover[s]:
                        idxs[fpos].append(i)
            else:
                lo = bisect.bisect_right(bounds, p1) - 1
                if lo < 0:
                    lo = 0
                hi = min(bisect.bisect_left(bounds, p2), nseg)
                for s in range(lo, hi):
                    for fpos in cover[s]:
                        if last[fpos] != i:
                            last[fpos] = i
                            idxs[fpos].append(i)
        for (rb, re_, f), fidx in zip(elig, idxs):
            if fidx:
                # one clip pass: excluded pieces plus everything outside
                # [rb, re_) — SETs are already range-filtered, this
                # trims boundary-spanning CLEARs to exactly the piece
                # this server serves
                clip = list(f.excluded)
                if rb > b"":
                    clip.append((0, b"", rb))
                clip.append((0, re_, b"\xff\xff\xff\xff"))
                sub = _filter_excluded(batch.select(fidx), clip)
                if sub:
                    f.retain(version, sub)
                    self.total_captured += len(sub)

    def _segments(self, elig: list) -> tuple[list[bytes], list[list[int]]]:
        """Disjoint elementary segments of the eligible (clipped) feed
        ranges: ``bounds[s]`` starts segment s = [bounds[s],
        bounds[s+1]) and ``cover[s]`` lists the positions in ``elig``
        covering it (the final boundary starts no segment).  Cached on
        the exact (feed identity, clipped range) tuple — stable across
        the thousands of applies between feed lifecycle events."""
        key = tuple((id(f), rb, re_) for rb, re_, f in elig)
        cached = self._seg_cache
        if cached is not None and cached[0] == key:
            return cached[1], cached[2]
        pts = sorted({p for rb, re_, _f in elig for p in (rb, re_)})
        cover: list[list[int]] = [[] for _ in range(max(0, len(pts) - 1))]
        for fpos, (rb, re_, _f) in enumerate(elig):
            for s in range(bisect.bisect_left(pts, rb),
                           bisect.bisect_left(pts, re_)):
                cover[s].append(fpos)
        self._seg_cache = (key, pts, cover)
        return pts, cover

    # --- the stream read ---

    async def read(self, feed_id: bytes, begin_version: Version,
                   byte_limit: int, through_version: Version
                   ) -> tuple[list, Version | None]:
        """Retained entries of ``feed_id`` in [begin_version,
        through_version], oldest first: the spilled prefix re-read from
        the side queue, then the in-memory window.  Returns (entries,
        truncated_at): ``truncated_at`` is the last delivered version
        when the byte limit cut the scan short, else None (exhausted)."""
        async with self._lock():
            return await self._read_locked(feed_id, begin_version,
                                           byte_limit, through_version)

    async def _read_locked(self, feed_id: bytes, begin_version: Version,
                           byte_limit: int, through_version: Version
                           ) -> tuple[list, Version | None]:
        from ..rpc.wire import decode
        f = self.feeds[feed_id]
        excluded = f.excluded
        out: list[tuple[Version, MutationBatch]] = []
        nbytes = 0
        lo = bisect.bisect_left(f.spilled, (begin_version, -1, -1, -1))
        for v, st, en, nb in f.spilled[lo:]:
            if v > through_version:
                return out, None
            # a corrupt spilled frame raises DiskCorrupt from read_frames
            # (ISSUE 12): the stream RPC fails LOUDLY instead of the old
            # behavior of silently skipping the version — a consumer
            # must never be heartbeated past data it was never handed.
            # An empty result only means the frame range was released by
            # a concurrent pop, which IS silently skippable.
            frames = await self.queue.read_frames(st, en)
            if not frames:
                continue        # released concurrently by a pop
            rec = decode(frames[0][0])
            sub = _filter_excluded(MutationBatch(*rec["pk"]), excluded)
            if sub:
                out.append((v, sub))
                nbytes += nb
            if byte_limit and nbytes >= byte_limit:
                return out, v
        i = bisect.bisect_left(f.versions, begin_version, lo=f.start)
        while i < len(f.versions):
            v = f.versions[i]
            if v > through_version:
                break
            sub = _filter_excluded(f.batches[i], excluded)
            if sub:
                out.append((v, sub))
                nbytes += f.sizes[i]
            if byte_limit and nbytes >= byte_limit:
                return out, v
            i += 1
        return out, None

    # --- spill / release (durability-loop hooks) ---

    async def maybe_spill(self, floor: Version,
                          mem_limit: int = 0) -> int:
        """Release the side queue's dead prefix, then spill EVERY sealed
        entry at or below ``floor`` (the storage durable floor) to the
        side queue.  This is a durability obligation, not a memory
        optimization: the durability tick pops the TLog past the floor,
        so an unspilled sub-floor entry's only copy would die with the
        process — a rebooted replica would then heartbeat consumers
        past data it silently lost.  Entries above the floor never
        spill: they may still roll back with the MVCC window (and
        replay from the TLog after a reboot), and a disk queue cannot
        un-append.  ``mem_limit`` > 0 caps the pass for tests (spill
        down to half the cap, oldest first).  Returns bytes spilled.

        Crash/retry discipline: frames are pushed AND fsync'd before a
        single piece of bookkeeping (spilled lists, memory trim)
        mutates, all of which then happens in one synchronous step under
        the io lock — a failed push/commit leaves the store exactly as
        it was (the orphan frames are re-pushed on retry and the stale
        copies skipped at restore by the duplicate-version guard), a
        concurrent stream read can never observe an entry in both the
        memory window and the spilled list, and the post-commit trim is
        by VERSION, not index — a pop applied from the tag stream
        during the push awaits compacts the memory lists safely."""
        async with self._lock():
            await self._release()
            if self.queue is None:
                return 0
            from ..rpc.wire import encode
            total = sum(f.mem_bytes for f in self.feeds.values())
            target = mem_limit // 2 if mem_limit else None
            spilled = 0
            # snapshot (feed, version, size, frame start, frame end) —
            # VALUES, never indices: the lists may compact under a
            # concurrent pop while the pushes await
            pushed: list[tuple[FeedState, Version, int, int, int]] = []
            for f in sorted(self.feeds.values(), key=lambda x: -x.mem_bytes):
                if target is not None and total - spilled <= target:
                    break
                i = f.start
                hi = bisect.bisect_right(f.versions, floor)
                seal = list(zip(f.versions[i:hi], f.batches[i:hi],
                                f.sizes[i:hi]))
                for v, b, nb in seal:
                    if target is not None and total - spilled <= target:
                        break
                    start_off = self.queue.end_offset
                    end_off = await self.queue.push(encode({
                        "f": f.feed_id, "v": v,
                        "pk": (b.types, b.bounds, b.blob)}))
                    pushed.append((f, v, nb, start_off, end_off))
                    spilled += nb
            if not pushed:
                return 0
            # fsync BEFORE any bookkeeping: the TLog pops past the
            # durable floor, so a crash between trim and sync would lose
            # the only copy of acked feed data — and a FAILED sync must
            # leave no record either, or the retry would double-spill
            await self.queue.commit()
            tops: dict[bytes, Version] = {}
            for f, v, nb, st, en in pushed:
                if self.feeds.get(f.feed_id) is not f:
                    continue            # destroyed mid-spill: dead frame
                if v <= f.popped_version:
                    continue            # popped mid-spill: dead frame
                self._frames.append((st, en, f.feed_id, v))
                f.spilled.append((v, st, en, nb))
                f.spilled_bytes += nb
                tops[f.feed_id] = v
            for f in {id(p[0]): p[0] for p in pushed}.values():
                top = tops.get(f.feed_id)
                if top is None:
                    continue
                i = bisect.bisect_right(f.versions, top, lo=f.start)
                if i > f.start:
                    # [start:i) holds exactly the entries just spilled
                    # (or popped mid-spill); the dead prefix below
                    # ``start`` is untouched, so ``start`` stays valid
                    f.mem_bytes -= sum(f.sizes[f.start:i])
                    del f.versions[f.start:i]
                    del f.batches[f.start:i]
                    del f.sizes[f.start:i]
            return spilled

    async def _release(self) -> None:
        """Trim the side queue's dead prefix (popped or destroyed)."""
        if self.queue is None:
            return
        off = None
        while self._frames:
            st, en, fid, v = self._frames[0]
            f = self.feeds.get(fid)
            if f is None or v <= f.popped_version:
                off = en
                self._frames.pop(0)
            else:
                break
        if off is not None:
            await self.queue.pop_to(off)

    def serving_ranges(self, feed_id: bytes,
                       shard: KeyRange) -> list[tuple[bytes, bytes]]:
        """The feed subranges this server answers for: its (narrowed)
        shard ∩ the feed range, minus handed-off exclusions."""
        f = self.feeds[feed_id]
        b = max(shard.begin, f.range.begin)
        e = min(shard.end, f.range.end)
        if b >= e:
            return []
        return _subtract_ranges([(b, e)], f.excluded)

    # --- durable metadata + recovery ---

    def export_meta(self) -> list[dict]:
        """Registration metadata for the engine's meta dict: enough to
        re-arm every feed after a reboot (entries above the durable
        floor replay from the TLog; spilled ones recover from the side
        queue)."""
        return [{"id": f.feed_id, "b": f.range.begin, "e": f.range.end,
                 "rv": f.register_version, "pv": f.popped_version,
                 "ex": [list(x) for x in f.excluded]}
                for f in self.feeds.values()]

    def restore(self, meta: list[dict], frames: list[tuple[bytes, int]],
                front: int) -> None:
        """Reboot path: re-arm feeds from engine meta and re-index the
        side queue's surviving frames (``frames`` is DiskQueue.open's
        payload list; ``front`` the queue's first live offset)."""
        from ..rpc.wire import decode
        for m in meta or []:
            f = FeedState(bytes(m["id"]), bytes(m["b"]), bytes(m["e"]),
                          m["rv"], m["pv"])
            f.excluded = [(v, bytes(b), bytes(e))
                          for v, b, e in m.get("ex") or []]
            self.feeds[bytes(m["id"])] = f
        pos = front
        for payload, end in frames:
            try:
                rec = decode(payload)
            except Exception:  # noqa: BLE001 — torn frame: skip
                pos = end
                continue
            fid, v = bytes(rec["f"]), rec["v"]
            f = self.feeds.get(fid)
            # the monotonic-version guard also drops orphan frames from
            # a spill attempt whose fsync failed before bookkeeping (the
            # retry re-pushed identical content at a later offset)
            if f is not None and v > f.popped_version \
                    and (not f.spilled or v > f.spilled[-1][0]):
                nb = len(rec["pk"][2])
                self._frames.append((pos, end, fid, v))
                f.spilled.append((v, pos, end, nb))
                f.spilled_bytes += nb
            pos = end

    # --- data-distribution handoff (rides fetchKeys) ---

    async def handoff(self, begin: bytes, end: bytes,
                      through_version: Version) -> list[dict]:
        """Export every feed overlapping [begin, end) for a move
        destination: registration + retained entries at or below the
        fetch version, clipped to the moving range.  Entries above it
        arrive at the destination through its own tag pull."""
        out: list[dict] = []
        for f in self.feeds.values():
            if not (f.range.begin < end and begin < f.range.end):
                continue
            entries, _ = await self.read(f.feed_id, f.popped_version + 1,
                                         0, through_version)
            clipped: list[tuple[Version, MutationBatch]] = []
            cb, ce = max(begin, f.range.begin), min(end, f.range.end)
            if cb >= ce:
                continue
            # same clip discipline as capture: CLEARs spanning the
            # handoff boundary must not reach the destination whole, or
            # the kept part would be delivered by both sides
            clip = [(0, ce, b"\xff\xff\xff\xff")]
            if cb > b"":
                clip.append((0, b"", cb))
            for v, batch in entries:
                idxs = [i for i, (t, p1, p2) in enumerate(batch.iter_ops())
                        if (cb <= p1 < ce if t == 0
                            else (p1 < ce and cb < p2))]
                if idxs:
                    sub = _filter_excluded(batch.select(idxs), clip)
                    if sub:
                        clipped.append((v, sub))
            out.append({"id": f.feed_id, "b": f.range.begin,
                        "e": f.range.end, "rv": f.register_version,
                        "pv": f.popped_version, "entries": clipped})
        return out

    def install(self, exported: list[dict]) -> None:
        """Destination side of ``handoff``: arm the feeds and seed their
        retained windows with the source's entries."""
        for m in exported:
            fid = bytes(m["id"])
            f = self.feeds.get(fid)
            if f is None:
                f = self.feeds[fid] = FeedState(
                    fid, bytes(m["b"]), bytes(m["e"]), m["rv"], m["pv"])
            for v, batch in m["entries"]:
                if not f.versions or v > f.versions[-1]:
                    f.retain(v, batch)

    # --- observability ---

    def metrics(self) -> dict:
        return {
            "feeds_active": len(self.feeds),
            # ids, not just a count: the status rollup needs the DISTINCT
            # union across servers (max undercounts disjoint placements,
            # sum double-counts replicas)
            "feed_ids": sorted(self.feeds),
            "feed_entries": sum(f.entry_count()
                                for f in self.feeds.values()),
            "feed_mem_bytes": sum(f.mem_bytes
                                  for f in self.feeds.values()),
            "feed_spilled_bytes": sum(f.spilled_bytes
                                      for f in self.feeds.values()),
            "feed_streams_served": self.streams_served,
            "feed_mutations_captured": self.total_captured,
        }
