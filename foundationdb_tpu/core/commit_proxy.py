"""The commit proxy role — batches client commits through the pipeline.

Reference: REF:fdbserver/CommitProxyServer.actor.cpp::commitBatch — the
five-stage pipeline per batch:
  1. accumulate transactions for COMMIT_BATCH_INTERVAL (or count/byte cap)
  2. GetCommitVersionRequest → sequencer: (prev_version, version)
  3. broadcast ResolveTransactionBatchRequest to EVERY resolver (conflict
     ranges clipped to each resolver's partition); AND the verdicts
  4. tag committed mutations by shard map; substitute versionstamps
  5. push to every TLog; report committed to sequencer; reply to clients
Batches overlap: stage 2 of batch N+1 can start while batch N resolves —
version ordering is preserved by prev_version chaining in the resolver
and TLog, exactly like the reference.
"""

from __future__ import annotations

import asyncio
import struct

from ..ops.batch import COMMITTED, CONFLICT, TOO_OLD, TxnRequest
from ..runtime.errors import (ClientInvalidOperation, ClusterVersionChanged,
                              CommitUnknownResult, NotCommitted,
                              TransactionTooOld)
from ..runtime.knobs import Knobs
from .data import (CommitResult, CommitTransactionRequest, Mutation,
                   MutationType, Version, pack_versionstamp)
from .resolver import ResolveBatchRequest, Resolver, clip_txn_to_range
from .sequencer import Sequencer
from .shard_map import ShardMap


class CommitProxy:
    def __init__(self, knobs: Knobs, sequencer: Sequencer,
                 resolvers: list[Resolver], log_system,
                 shard_map: ShardMap) -> None:
        self.knobs = knobs
        self.sequencer = sequencer
        self.resolvers = resolvers
        self.log_system = log_system
        self.shard_map = shard_map
        self._queue: asyncio.Queue = asyncio.Queue()
        self._batcher_task: asyncio.Task | None = None
        self._inflight: set[asyncio.Task] = set()
        self.total_batches = 0
        self.total_committed = 0
        self.total_conflicts = 0
        from ..runtime.trace import CounterCollection
        self.counters = CounterCollection("ProxyCommit")
        self._metrics_task = None

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._batcher_task = loop.create_task(
            self._batcher_loop(), name="commit-proxy-batcher")
        self._metrics_task = loop.create_task(
            self._metrics_loop(), name="commit-proxy-metrics")

    async def _metrics_loop(self) -> None:
        while True:
            await asyncio.sleep(self.knobs.METRICS_INTERVAL)
            self.counters.log_metrics()

    async def stop(self) -> None:
        tasks = list(self._inflight)
        if self._batcher_task is not None:
            tasks.append(self._batcher_task)
            self._batcher_task = None
        if self._metrics_task is not None:
            tasks.append(self._metrics_task)
            self._metrics_task = None
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        self._inflight.clear()
        # requests still queued or parked in a cancelled batch would await
        # forever; their outcome is genuinely unknown (broken promise)
        from ..runtime.errors import RequestMaybeDelivered
        while not self._queue.empty():
            _, fut = self._queue.get_nowait()
            if not fut.done():
                fut.set_exception(RequestMaybeDelivered())

    # --- client-facing ---

    async def commit(self, req: CommitTransactionRequest) -> CommitResult:
        fut = asyncio.get_running_loop().create_future()
        self._queue.put_nowait((req, fut))
        return await fut

    # --- batching (REF: commitBatcher) ---

    async def _batcher_loop(self) -> None:
        from ..runtime.buggify import buggify
        from ..runtime.rng import deterministic_random
        if buggify("proxy_tiny_batches", fire_p=1.0):
            # pathological batching knob (BUGGIFY knob randomization):
            # near-zero window makes every txn its own batch
            self.knobs = self.knobs.override(COMMIT_BATCH_INTERVAL=1e-5)
        elif buggify("proxy_fat_batches", fire_p=1.0):
            self.knobs = self.knobs.override(
                COMMIT_BATCH_INTERVAL=self.knobs.COMMIT_BATCH_INTERVAL * 20)
        loop = asyncio.get_running_loop()
        last_real_commit = loop.time()
        while True:
            # while clients are active, emit empty batches during gaps so
            # versions keep flowing (storage durability floors, resolver
            # windows, and GRV freshness all ride the version clock —
            # REF: the master's always-advancing version stream)
            if loop.time() - last_real_commit < self.knobs.IDLE_COMMIT_LIMIT:
                try:
                    first = await asyncio.wait_for(
                        self._queue.get(),
                        self.knobs.COMMIT_EMPTY_BATCH_INTERVAL)
                except asyncio.TimeoutError:
                    await self._empty_batch()
                    continue
            else:
                first = await self._queue.get()
            last_real_commit = loop.time()
            batch = [first]
            nbytes = first[0].expected_size()
            deadline = asyncio.get_running_loop().time() + self.knobs.COMMIT_BATCH_INTERVAL
            while (len(batch) < self.knobs.COMMIT_BATCH_COUNT_LIMIT
                   and nbytes < self.knobs.COMMIT_BATCH_BYTE_LIMIT):
                timeout = deadline - asyncio.get_running_loop().time()
                if timeout <= 0:
                    break
                try:
                    item = await asyncio.wait_for(self._queue.get(), timeout)
                except asyncio.TimeoutError:
                    break
                batch.append(item)
                nbytes += item[0].expected_size()
            # overlapped pipelining: run the batch as its own task; version
            # ordering downstream comes from prev_version chaining
            t = asyncio.get_running_loop().create_task(
                self._commit_batch(batch), name="commit-batch")
            self._inflight.add(t)
            t.add_done_callback(self._inflight.discard)

    async def _empty_batch(self) -> None:
        """Advance the version chain with no transactions."""
        prev_version = version = None
        try:
            prev_version, version = await self.sequencer.get_commit_version()
            await asyncio.gather(*(r.resolve(
                ResolveBatchRequest(prev_version, version, []))
                for r in self.resolvers))
            await self.log_system.push(prev_version, version, {})
            self.sequencer.report_committed(version)
        except Exception:
            # an assigned version must never be abandoned (re-resolving or
            # re-pushing an empty batch is harmless)
            if version is not None:
                await self._repair_chain(prev_version, version, False, False)

    # --- the pipeline (REF: commitBatch) ---

    async def _commit_batch(self, batch: list[tuple[CommitTransactionRequest,
                                                    asyncio.Future]]) -> None:
        # Pre-validate anything that could raise during tagging (malformed
        # versionstamp offsets) BEFORE a version is assigned, so a bad
        # request fails alone instead of wedging the version chain.
        valid: list[tuple[CommitTransactionRequest, asyncio.Future]] = []
        for req, fut in batch:
            try:
                for m in req.mutations:
                    self._substitute_versionstamp(m, 0, 0)
                valid.append((req, fut))
            except Exception:
                if not fut.done():
                    fut.set_exception(ClientInvalidOperation())
        if not valid:
            return
        reqs = [r for r, _ in valid]
        futs = [f for _, f in valid]
        prev_version = version = None
        resolved = pushed = push_started = False
        try:
            prev_version, version = await self.sequencer.get_commit_version()
            txns = [TxnRequest(r.read_conflict_ranges, r.write_conflict_ranges,
                               r.read_snapshot) for r in reqs]

            # broadcast to all resolvers, clipped to each partition
            async def ask(res: Resolver):
                clipped = [clip_txn_to_range(t, res.key_range) for t in txns]
                reply = await res.resolve(
                    ResolveBatchRequest(prev_version, version, clipped))
                return reply.verdicts
            all_verdicts = await asyncio.gather(*(ask(r) for r in self.resolvers))
            resolved = True

            # AND the verdicts: TOO_OLD dominates, then CONFLICT
            final = [COMMITTED] * len(reqs)
            for verdicts in all_verdicts:
                for i, v in enumerate(verdicts):
                    final[i] = max(final[i], v)

            # tag mutations of committed txns, in batch order; the log
            # system replicates each tag onto its hosting logs
            tagged: dict[int, list[Mutation]] = {}
            order = 0
            orders: list[int] = [0] * len(reqs)
            for i, (req, verdict) in enumerate(zip(reqs, final)):
                if verdict != COMMITTED:
                    continue
                orders[i] = order
                for m in req.mutations:
                    m = self._substitute_versionstamp(m, version, order)
                    if m.type == MutationType.CLEAR_RANGE:
                        tags = self.shard_map.tags_for_range(m.param1, m.param2)
                    else:
                        tags = self.shard_map.tags_for_key(m.param1)
                    for t in tags:
                        tagged.setdefault(t, []).append(m)
                order += 1

            push_started = True
            await self.log_system.push(prev_version, version, tagged)
            pushed = True
            self.sequencer.report_committed(version)

            self.total_batches += 1
            self.counters.counter("CommitBatchIn").add(1)
            for i, fut in enumerate(futs):
                if fut.done():
                    continue
                if final[i] == COMMITTED:
                    self.total_committed += 1
                    self.counters.counter("TxnCommitOut").add(1)
                    fut.set_result(CommitResult(
                        version, pack_versionstamp(version, orders[i])))
                elif final[i] == TOO_OLD:
                    self.total_conflicts += 1
                    self.counters.counter("TxnConflicts").add(1)
                    fut.set_exception(TransactionTooOld())
                else:
                    self.total_conflicts += 1
                    self.counters.counter("TxnConflicts").add(1)
                    fut.set_exception(NotCommitted())
        except asyncio.CancelledError:
            for fut in futs:
                if not fut.done():
                    fut.set_exception(ClusterVersionChanged())
            raise
        except Exception as e:
            # once any TLog may hold the batch, the outcome is ambiguous:
            # clients must see commit_unknown_result (maybe-committed), not
            # a freely-retryable transport error that would double-apply
            # mutations on retry (REF: NativeAPI tryCommit error mapping)
            client_err = CommitUnknownResult() if push_started else e
            for fut in futs:
                if not fut.done():
                    fut.set_exception(client_err)
            # complete the version chain: downstream roles are waiting on
            # prev_version ordering, and an abandoned version would wedge
            # every later batch cluster-wide
            if version is not None:
                await self._repair_chain(prev_version, version, resolved, pushed)

    async def _repair_chain(self, prev_version: Version, version: Version,
                            resolved: bool, pushed: bool) -> None:
        try:
            if not resolved:
                await asyncio.gather(*(r.resolve(
                    ResolveBatchRequest(prev_version, version, []))
                    for r in self.resolvers))
            if not pushed:
                await self.log_system.push(prev_version, version, {})
            self.sequencer.report_committed(version)
        except Exception:
            pass  # a failed repair means the epoch is dead; recovery's job

    @staticmethod
    def _substitute_versionstamp(m: Mutation, version: Version,
                                 order: int) -> Mutation:
        """Splice the 10-byte commit versionstamp into key/value at the
        trailing 4-byte little-endian offset (API ≥ 520 wire format,
        REF:fdbserver/CommitProxyServer.actor.cpp)."""
        if m.type == MutationType.SET_VERSIONSTAMPED_KEY:
            stamped = CommitProxy._splice(m.param1, version, order)
            return Mutation(MutationType.SET_VALUE, stamped, m.param2)
        if m.type == MutationType.SET_VERSIONSTAMPED_VALUE:
            stamped = CommitProxy._splice(m.param2, version, order)
            return Mutation(MutationType.SET_VALUE, m.param1, stamped)
        return m

    @staticmethod
    def _splice(param: bytes, version: Version, order: int) -> bytes:
        if len(param) < 4:
            raise ValueError("versionstamp param lacks offset suffix")
        pos = struct.unpack("<I", param[-4:])[0]
        raw = param[:-4]
        if pos + 10 > len(raw):
            raise ValueError("versionstamp offset out of range")
        return raw[:pos] + pack_versionstamp(version, order) + raw[pos + 10:]
