"""The commit proxy role — batches client commits through the pipeline.

Reference: REF:fdbserver/CommitProxyServer.actor.cpp::commitBatch — the
five-stage pipeline per batch:
  1. accumulate transactions for COMMIT_BATCH_INTERVAL (or count/byte cap)
  2. GetCommitVersionRequest → sequencer: (prev_version, version)
  3. broadcast ResolveTransactionBatchRequest to EVERY resolver (conflict
     ranges clipped to each resolver's partition); AND the verdicts
  4. tag committed mutations by shard map; substitute versionstamps
  5. push to every TLog; report committed to sequencer; reply to clients
Batches overlap: stage 2 of batch N+1 can start while batch N resolves —
version ordering is preserved by prev_version chaining in the resolver
and TLog, exactly like the reference.
"""

from __future__ import annotations

import asyncio
import struct

from ..ops.batch import COMMITTED, CONFLICT, TOO_OLD, TxnRequest
from ..runtime import span as _span
from ..runtime.errors import (ClientInvalidOperation, ClusterVersionChanged,
                              CommitUnknownResult, NotCommitted,
                              TransactionTooOld)
from ..runtime.knobs import Knobs
from .data import (PRIVATE_TYPES, SYSTEM_PREFIX, CommitResult,
                   CommitTransactionRequest, Mutation, MutationBatch,
                   MutationBatchBuilder, MutationType, Version,
                   pack_versionstamp)
from .resolver import ResolveBatchRequest, Resolver, clip_txn_to_range
from .sequencer import Sequencer
from .shard_map import ShardMap, write_team_drops


def is_state_txn(req: CommitTransactionRequest) -> bool:
    """A transaction that mutates the system keyspace is a "state
    transaction" (REF:fdbserver/CommitProxyServer.actor.cpp
    txnStateTransactions): its mutations must be applied by EVERY commit
    proxy in version order, so it is resolved alone in its batch with
    unclipped conflict ranges on every resolver.

    Verdict-agreement invariant: every resolver must compute the SAME
    verdict for a state transaction, or the proxies' metadata histories
    diverge.  Unclipped ranges alone don't give that — resolvers' write
    HISTORIES are per-partition — so state transactions may take read
    conflicts only within the system keyspace, whose full write history
    every resolver holds (all ``\\xff`` writes arrive via broadcast
    state transactions).  The proxy rejects violators up front."""
    for m in req.mutations:
        if m.type == MutationType.CLEAR_RANGE:
            if m.param2 > SYSTEM_PREFIX:
                return True
        elif m.param1 >= SYSTEM_PREFIX:
            return True
    return False


def check_state_txn_reads(req: CommitTransactionRequest) -> None:
    """Enforce the verdict-agreement invariant (see is_state_txn)."""
    for rb, _re in req.read_conflict_ranges:
        if rb < SYSTEM_PREFIX:
            raise ClientInvalidOperation(
                "system-key transactions may not take read conflicts on "
                "user keys (cross-resolver verdict agreement)")


class CommitProxy:
    def __init__(self, knobs: Knobs, sequencer: Sequencer,
                 resolvers: list[Resolver], log_system,
                 shard_map: ShardMap, backup_tags: dict[str, int] | None = None,
                 locked: bytes | None = None) -> None:
        self.knobs = knobs
        self.sequencer = sequencer
        self.resolvers = resolvers
        self.log_system = log_system
        # continuous-backup mutation tagging (REF:fdbserver/
        # BackupWorker/backup tags): while a backup tag is active, every
        # committed mutation is ALSO pushed under it, so backup agents can
        # pull the full ordered mutation stream.  Versioned like the shard
        # maps — \xff/backup/tag[s/<name>] state transactions flip the
        # armed set at an exact commit version on every proxy.  Several
        # named tags (file backup + DR) stream concurrently.
        self._backup_tags: list[tuple[Version, dict[str, int]]] = \
            [(-1, dict(backup_tags or {}))]
        # database lock (REF: lockedKey in ProxyCommitData): while set,
        # only lock-aware transactions may commit.  Versioned the same way.
        self._locks: list[tuple[Version, bytes | None]] = [(-1, locked)]
        # registered change feeds: feed id -> (begin, end).  Unlike the
        # shard maps / backup tags / locks, no consumer ever needs the
        # registry AT a historical version — markers are computed inside
        # _apply_metadata, which runs strictly in version order — so a
        # plain dict suffices (\xff/changeFeeds state transactions
        # mutate it at their exact commit version on every proxy, and
        # the OWNING proxy injects PRIVATE_FEED_* markers into the
        # owning storage tags' streams)
        self._feeds: dict[bytes, tuple[bytes, bytes]] = {}
        # versioned shard-map history: the map at index i is effective for
        # commit versions >= its change version.  Layout changes arrive as
        # state-transaction entries (the txnStateStore of this proxy) and
        # append snapshots, so pipelined batches always tag with the map
        # as of their OWN version even when a later batch applied a newer
        # layout first.
        self._maps: list[tuple[Version, ShardMap]] = [(-1, shard_map)]
        self.state_applied_version: Version = -1
        # drop markers computed per applied layout-change version.  Kept
        # separately from _apply_state_entries' return value because the
        # entry for version V may be applied by ANOTHER in-flight batch
        # whose reply arrived first — the batch that OWNS version V must
        # still find and push V's markers exactly once.
        self._pending_drops: dict[Version,
                                  list[tuple[int, int, bytes, bytes]]] = {}
        self._queue: asyncio.Queue = asyncio.Queue()
        self._batcher_task: asyncio.Task | None = None
        self._inflight: set[asyncio.Task] = set()
        self.total_batches = 0
        self.total_committed = 0
        self.total_conflicts = 0
        # routed-mesh accounting (ISSUE 16), one slot per resolver
        # partition: how many sends went out, how many were header-only
        # version advances (every txn clipped empty), and how many txns
        # rode the sparse sub-batches.  The imbalance across slots is the
        # signal the CC's heat-driven boundary rebalance consumes.
        self.route_stats = [{"sends": 0, "header_only": 0, "txns_routed": 0}
                            for _ in resolvers]
        # this proxy's fully-acked frontier: the newest version whose
        # push every hosting log acked.  Rides every later push (real
        # and empty) as TLogPushRequest.known_committed, giving
        # downstream consumers a committed floor (feed heartbeats must
        # never expose a possibly-unacked applied tip).
        self._known_committed: Version = 0
        from ..runtime.trace import CounterCollection, Histogram
        from ..runtime.latency_probe import StageStats
        self.counters = CounterCollection("ProxyCommit")
        self.latency_hist = Histogram("ProxyCommit", "BatchLatency")
        # per-stage commit-path breakdown (VERDICT r4 1a): batch_fill /
        # version_wait / resolve / push, read by bench harnesses
        self.stages = StageStats("CommitProxy")
        # CommitDebug span events for sampled txns: queued / batch
        # milestones / reply, keyed by the wire-propagated trace id
        self.spans = _span.SpanSink("CommitProxy")
        self._msource = None
        # fail-stop (see _repair_chain): once set, new commits are refused
        # and the role-liveness ping probes dead, driving an epoch recovery
        self._failed: BaseException | None = None

    @property
    def shard_map(self) -> ShardMap:
        return self._maps[-1][1]

    def map_at(self, version: Version) -> ShardMap:
        for v, m in reversed(self._maps):
            if v <= version:
                return m
        return self._maps[0][1]

    def backup_tags_at(self, version: Version) -> tuple[int, ...]:
        for v, tags in reversed(self._backup_tags):
            if v <= version:
                return tuple(sorted(set(tags.values())))
        return ()

    def locked_at(self, version: Version) -> bytes | None:
        for v, uid in reversed(self._locks):
            if v <= version:
                return uid
        return None

    # --- metadata mutations (REF:fdbserver/ApplyMetadataMutation.cpp) ---

    def _apply_state_entries(self, entries, own_version: Version | None = None
                             ) -> list[tuple[int, int, bytes, bytes]]:
        """Apply committed state entries in version order; returns the
        private markers (shard drops, feed lifecycle) for the entry at
        ``own_version`` (only the proxy that owns that batch pushes them
        to the TLogs — exactly once).  The markers are retrieved from
        _pending_drops rather than the apply call, because a pipelined
        batch at a higher version may have applied our entry before our
        own reply arrived.  Entries arrive sorted by version; the
        piggyback ships mutations packed (MutationBatch) since 713."""
        for v, muts in sorted(entries or [], key=lambda e: e[0]):
            if v <= self.state_applied_version:
                continue
            markers = self._apply_metadata(v, muts)
            if markers:
                self._pending_drops[v] = markers
                if len(self._pending_drops) > 256:
                    # entries owned by other proxies are never popped;
                    # old ones can no longer be claimed by any batch
                    self._pending_drops.pop(min(self._pending_drops))
            self.state_applied_version = v
        if own_version is None:
            return []
        return self._pending_drops.pop(own_version, [])

    def _apply_metadata(self, version: Version, muts
                        ) -> list[tuple[int, int, bytes, bytes]]:
        """Returns (tag, private mutation type, param1, param2) markers
        the owning batch must inject into those tags' streams."""
        from ..rpc.wire import decode
        from ..runtime.trace import TraceEvent
        from .system_data import (BACKUP_PREFIX, BACKUP_TAGS_PREFIX,
                                  CHANGE_FEED_POP_PREFIX, CHANGE_FEED_PREFIX,
                                  LAYOUT_KEY, LOCKED_KEY, backup_tag_key)
        backup_key = BACKUP_PREFIX + b"tag"
        markers: list[tuple[int, int, bytes, bytes]] = []
        for m in muts:
            # -- change-feed lifecycle (create / pop via SET) --
            if m.type == MutationType.SET_VALUE \
                    and m.param1.startswith(CHANGE_FEED_PREFIX):
                fid = m.param1[len(CHANGE_FEED_PREFIX):]
                try:
                    info = decode(m.param2)
                    fb, fe = bytes(info["b"]), bytes(info["e"])
                except Exception as e:  # noqa: BLE001 — bad blob: ignore
                    TraceEvent("ProxyBadFeed", severity=30) \
                        .detail("Error", repr(e)[:100]).log()
                    continue
                # clamp \xff-exclusive (whole-db feeds cover exactly the
                # user keyspace; a forged registration must not make a
                # feed observe system writes)
                fe = min(fe, SYSTEM_PREFIX)
                if fb >= fe:
                    continue
                if fid not in self._feeds:  # re-register is idempotent
                    self._feeds[fid] = (fb, fe)
                    for t in self._maps[-1][1].tags_for_range(fb, fe):
                        markers.append(
                            (t, int(MutationType.PRIVATE_FEED_REGISTER),
                             fid, bytes(m.param2)))
                    TraceEvent("ProxyFeedRegistered") \
                        .detail("Version", version).detail("Feed", fid) \
                        .detail("Begin", fb).detail("End", fe).log()
                continue
            if m.type == MutationType.SET_VALUE \
                    and m.param1.startswith(CHANGE_FEED_POP_PREFIX):
                fid = m.param1[len(CHANGE_FEED_POP_PREFIX):]
                rng = self._feeds.get(fid)
                try:
                    int(decode(m.param2))
                except Exception as e:  # noqa: BLE001 — bad blob: a
                    # forwarded garbage payload would crash every owning
                    # storage server's apply loop
                    TraceEvent("ProxyBadFeedPop", severity=30) \
                        .detail("Error", repr(e)[:100]).log()
                    rng = None
                if rng is not None:
                    for t in self._maps[-1][1].tags_for_range(*rng):
                        markers.append(
                            (t, int(MutationType.PRIVATE_FEED_POP),
                             fid, bytes(m.param2)))
                continue
            # -- mutation-log tag arm/disarm (named slots) --
            name = None
            if m.param1 == backup_key:
                name = ""
            elif m.param1.startswith(BACKUP_TAGS_PREFIX):
                name = m.param1[len(BACKUP_TAGS_PREFIX):].decode(
                    errors="replace")
            if m.type == MutationType.SET_VALUE and name is not None:
                try:
                    tag = int(decode(m.param2))
                except Exception:  # noqa: BLE001 — bad blob: disable
                    tag = None
                cur = dict(self._backup_tags[-1][1])
                if tag is None:
                    cur.pop(name, None)
                else:
                    cur[name] = tag
                self._backup_tags.append((version, cur))
                TraceEvent("ProxyBackupTag").detail("Version", version) \
                    .detail("Name", name).detail("Tag", tag).log()
                continue
            if m.type == MutationType.CLEAR_RANGE:
                cur = {n: t for n, t in self._backup_tags[-1][1].items()
                       if not (m.param1 <= backup_tag_key(n) < m.param2)}
                if cur != self._backup_tags[-1][1]:
                    self._backup_tags.append((version, cur))
                    TraceEvent("ProxyBackupTag").detail("Version", version) \
                        .detail("Armed", sorted(cur)).log()
                # -- change-feed destroy (clear of the registration key) --
                doomed = {fid: rng for fid, rng in self._feeds.items()
                          if m.param1 <= CHANGE_FEED_PREFIX + fid < m.param2}
                for fid, rng in doomed.items():
                    del self._feeds[fid]
                    for t in self._maps[-1][1].tags_for_range(*rng):
                        markers.append(
                            (t, int(MutationType.PRIVATE_FEED_DESTROY),
                             fid, b""))
                    TraceEvent("ProxyFeedDestroyed") \
                        .detail("Version", version).detail("Feed", fid) \
                        .log()
                if m.param1 <= LOCKED_KEY < m.param2:
                    self._locks.append((version, None))
                    self.sequencer.report_lock(version, None)
                    TraceEvent("ProxyDbLock").detail("Version", version) \
                        .detail("Locked", False).log()
            # -- database lock/unlock --
            if m.type == MutationType.SET_VALUE and m.param1 == LOCKED_KEY:
                self._locks.append((version, bytes(m.param2)))
                self.sequencer.report_lock(version, bytes(m.param2))
                TraceEvent("ProxyDbLock").detail("Version", version) \
                    .detail("Locked", True).log()
                continue
            if m.type != MutationType.SET_VALUE or m.param1 != LAYOUT_KEY:
                continue
            try:
                layout = decode(m.param2)
                new = ShardMap([bytes(b) for b in layout["boundaries"]],
                               [list(t) for t in layout["teams"]])
            except Exception as e:  # noqa: BLE001 — a bad blob must not
                TraceEvent("ProxyBadLayout", severity=40) \
                    .detail("Error", repr(e)[:100]).log()   # kill the proxy
                continue
            drop_type = int(MutationType.PRIVATE_DROP_SHARD)
            markers.extend((t, drop_type, b, e) for t, b, e
                           in write_team_drops(self._maps[-1][1], new))
            self._maps.append((version, new))
            TraceEvent("ProxyLayoutApplied").detail("Version", version) \
                .detail("Shards", len(new.shard_tags)) \
                .detail("Drops", len(markers)).log()
        return markers

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._batcher_task = loop.create_task(
            self._batcher_loop(), name="commit-proxy-batcher")

    def metrics_source(self):
        """This role's registration in the per-worker MetricsRegistry
        (ISSUE 15) — replaces the ad-hoc per-role metrics sleep loop.
        Gauges: the proxy's acked frontier + metadata frontier and the
        commit-path queue/in-flight depths (rising queue depth with flat
        KnownCommitted is a wedged version chain at one glance)."""
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("ProxyCommit", counters=self.counters)
            s.histogram(self.latency_hist)
            s.gauge("KnownCommitted", lambda: self._known_committed)
            s.gauge("StateAppliedVersion", lambda: self.state_applied_version)
            s.gauge("QueueDepth", lambda: self._queue.qsize())
            s.gauge("InflightBatches", lambda: len(self._inflight))
            # routed-mesh totals (ISSUE 16); the per-partition split rides
            # each resolver's own SkippedBatches/RoutedBatches gauges
            s.gauge("RoutedHeaderSends", lambda: sum(
                r["header_only"] for r in self.route_stats))
            s.gauge("RoutedTxnsSent", lambda: sum(
                r["txns_routed"] for r in self.route_stats))
            self._msource = s
        return self._msource

    async def stop(self) -> None:
        tasks = list(self._inflight)
        if self._batcher_task is not None:
            tasks.append(self._batcher_task)
            self._batcher_task = None
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        self._inflight.clear()
        # requests still queued or parked in a cancelled batch would await
        # forever; their outcome is genuinely unknown (broken promise)
        from ..runtime.errors import RequestMaybeDelivered
        while not self._queue.empty():
            _, fut, _t, _ctx = self._queue.get_nowait()
            if not fut.done():
                fut.set_exception(RequestMaybeDelivered())

    # --- client-facing ---

    async def metrics(self) -> dict:
        """Role counters for status (span rollup + commit load)."""
        from ..runtime.profiler import stall_metrics
        from ..runtime.span import process_counters
        return {
            "total_batches": self.total_batches,
            "total_committed": self.total_committed,
            "total_conflicts": self.total_conflicts,
            "known_committed": self._known_committed,
            "route_stats": [dict(r) for r in self.route_stats],
            **self.spans.counters(),
            **stall_metrics(),
            **process_counters(),
        }

    async def commit(self, req: CommitTransactionRequest) -> CommitResult:
        if self._failed is not None:
            raise ClusterVersionChanged() from self._failed
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        # capture the wire-propagated span NOW: the batch runs in its own
        # task later, where the request context is long gone
        ctx = _span.current_span()
        if ctx is not None and ctx.sampled:
            self.spans.event("CommitDebug", ctx,
                             "CommitProxyServer.commit.queued")
        else:
            ctx = None
        self._queue.put_nowait((req, fut, loop.time(), ctx))
        return await fut

    # --- batching (REF: commitBatcher) ---

    async def _batcher_loop(self) -> None:
        from ..runtime.buggify import buggify
        from ..runtime.rng import deterministic_random
        if buggify("proxy_tiny_batches", fire_p=1.0):
            # pathological batching knob (BUGGIFY knob randomization):
            # near-zero window makes every txn its own batch
            self.knobs = self.knobs.override(COMMIT_BATCH_INTERVAL=1e-5)
        elif buggify("proxy_fat_batches", fire_p=1.0):
            self.knobs = self.knobs.override(
                COMMIT_BATCH_INTERVAL=self.knobs.COMMIT_BATCH_INTERVAL * 20)
        loop = asyncio.get_running_loop()
        last_real_commit = loop.time()
        while True:
            # while clients are active, emit empty batches during gaps so
            # versions keep flowing (storage durability floors, resolver
            # windows, and GRV freshness all ride the version clock —
            # REF: the master's always-advancing version stream)
            if loop.time() - last_real_commit < self.knobs.IDLE_COMMIT_LIMIT:
                try:
                    first = await asyncio.wait_for(
                        self._queue.get(),
                        self.knobs.COMMIT_EMPTY_BATCH_INTERVAL)
                except asyncio.TimeoutError:
                    await self._empty_batch()
                    continue
            else:
                first = await self._queue.get()
            last_real_commit = loop.time()
            while first is not None:
                # state transactions (system-key writers) resolve ALONE in
                # their batch: every resolver must compute the same verdict
                # from the same (unclipped) view, which a singleton batch
                # guarantees without any cross-resolver agreement protocol
                state_item = None
                if is_state_txn(first[0]):
                    batch, state_item = [], first
                    nbytes = 0
                else:
                    batch = [first]
                    nbytes = first[0].expected_size()
                first = None
                deadline = loop.time() + self.knobs.COMMIT_BATCH_INTERVAL
                while (state_item is None
                       and len(batch) < self.knobs.COMMIT_BATCH_COUNT_LIMIT
                       and nbytes < self.knobs.COMMIT_BATCH_BYTE_LIMIT):
                    try:
                        # drain the backlog WITHOUT yielding: a burst that
                        # outgrew one batch must become consecutive
                        # prev-chained batch tasks created in this same
                        # loop turn, so they all submit to the resolver
                        # before its pipeline pump runs — that back-to-back
                        # submission is the fusion window that keeps >= 2
                        # groups in flight on the live path (ISSUE 16; a
                        # wait_for here yields per txn, which let the pump
                        # drain after every single batch and pinned the
                        # live fused group mean at 1.0)
                        item = self._queue.get_nowait()
                    except asyncio.QueueEmpty:
                        timeout = deadline - loop.time()
                        if timeout <= 0:
                            break
                        try:
                            item = await asyncio.wait_for(self._queue.get(),
                                                          timeout)
                        except asyncio.TimeoutError:
                            break
                    if is_state_txn(item[0]):
                        state_item = item      # flush batch, then this alone
                        break
                    batch.append(item)
                    nbytes += item[0].expected_size()
                # overlapped pipelining: run the batch as its own task;
                # version ordering downstream comes from prev_version
                # chaining
                for b in ([batch] if batch else []) + \
                        ([[state_item]] if state_item else []):
                    t = loop.create_task(
                        self._commit_batch(b), name="commit-batch")
                    self._inflight.add(t)
                    t.add_done_callback(self._inflight.discard)
                # backlog remaining after a full batch: form the next one
                # NOW (same turn), for the same fusion window
                if state_item is None:
                    try:
                        first = self._queue.get_nowait()
                    except asyncio.QueueEmpty:
                        first = None

    async def _empty_batch(self) -> None:
        """Advance the version chain with no transactions."""
        prev_version = version = None
        try:
            prev_version, version = await self.sequencer.get_commit_version()
            replies = await asyncio.gather(*(r.resolve(
                ResolveBatchRequest(prev_version, version, [], None,
                                    self.state_applied_version))
                for r in self.resolvers))
            self._apply_state_entries(replies[0].state_entries)
            await self.log_system.push(prev_version, version, {},
                                       self._known_committed)
            if version > self._known_committed:
                self._known_committed = version
            self.sequencer.report_committed(version)
        except Exception as e:
            from ..runtime.trace import TraceEvent
            TraceEvent("EmptyBatchFailed", severity=30) \
                .detail("Error", repr(e)[:200]).detail("Version", version).log()
            # an assigned version must never be abandoned (re-resolving or
            # re-pushing an empty batch is harmless)
            if version is not None:
                await self._repair_chain(prev_version, version, False, False)

    @staticmethod
    def _join_abort_words(reply, final: list[int],
                          idx: list[int] | None) -> bool:
        """Bitmask AND-join (ISSUE 18): fold a reply's packed abort
        words into ``final`` touching only set bits; ``idx`` maps the
        reply's positions to batch positions (None = identity, the
        broadcast twin).  Bit decode is conflict_bit + too_old_bit —
        exactly the codes pack_abort_words packed, so the result is
        bit-identical to the per-verdict scatter.  Returns False when
        the reply carries no words (knob off / old peer) and the caller
        must run the scatter twin."""
        words = reply.abort_words
        if words is None:
            return False
        nw = len(words) // 2
        for w in range(nw):
            cw = words[w]
            while cw:
                b = (cw & -cw).bit_length() - 1
                cw &= cw - 1
                i = w * 32 + b
                v = 1 + ((words[nw + w] >> b) & 1)
                j = i if idx is None else idx[i]
                if v > final[j]:
                    final[j] = v
        return True

    # --- the pipeline (REF: commitBatch) ---

    async def _commit_batch(self, batch: list[tuple[CommitTransactionRequest,
                                                    asyncio.Future, float,
                                                    object]]
                            ) -> None:
        # Pre-validate anything that could raise during tagging (malformed
        # versionstamp offsets) BEFORE a version is assigned, so a bad
        # request fails alone instead of wedging the version chain.
        now = asyncio.get_running_loop().time()
        for _req, _fut, t_enq, _ctx in batch:
            self.stages.record("batch_fill", now - t_enq)
        valid: list[tuple[CommitTransactionRequest, asyncio.Future,
                          _span.SpanContext | None]] = []
        for req, fut, _t, ctx in batch:
            try:
                if is_state_txn(req):
                    check_state_txn_reads(req)
                    # the database lock gates state transactions BEFORE
                    # resolution: once resolved, a state txn's metadata
                    # mutations ride every resolver's committed-state
                    # stream to every proxy unconditionally, so rejecting
                    # it afterwards would leave proxies' metadata applied
                    # for a commit the client was told failed (REF: the
                    # lockedKey check gating applyMetadataMutations).
                    # The local lock view can be STALE-LOCKED on an idle
                    # cluster (an unlock committed through another proxy
                    # only reaches us via state entries): resolve an
                    # empty batch first — it applies every pending state
                    # entry — and only reject if still locked.  The
                    # refresh is rate-limited so a tight client retry
                    # loop against a genuinely locked database costs one
                    # version-chain round per second, not one per retry.
                    if self._locks[-1][1] is not None \
                            and not getattr(req, "lock_aware", False):
                        now = asyncio.get_running_loop().time()
                        if now - getattr(self, "_lock_refreshed", -1e9) > 1.0:
                            self._lock_refreshed = now
                            await self._empty_batch()
                        if self._locks[-1][1] is not None:
                            from ..runtime.errors import DatabaseLocked
                            raise DatabaseLocked()
                for m in req.mutations:
                    if m.type in PRIVATE_TYPES:
                        # proxies append private markers themselves after
                        # tagging; one arriving IN a client request is
                        # forged and would discard a shard or corrupt a
                        # feed's lifecycle
                        raise ClientInvalidOperation(
                            "private mutation type in client commit")
                    self._substitute_versionstamp(m, 0, 0)
                valid.append((req, fut, ctx))
            except Exception as pre_err:
                # pair the .queued event for a pre-validation reject
                self.spans.event("CommitDebug", ctx,
                                 "CommitProxyServer.commitBatch.Rejected",
                                 Error=type(pre_err).__name__)
                if not fut.done():
                    from ..runtime.errors import DatabaseLocked
                    fut.set_exception(
                        pre_err if isinstance(pre_err, DatabaseLocked)
                        else ClientInvalidOperation())
        if not valid:
            return
        reqs = [r for r, _, _ in valid]
        futs = [f for _, f, _ in valid]
        ctxs = [c for _, _, c in valid]
        # sampled txns riding this batch; downstream hops (resolver, TLog
        # push) key to the FIRST — extra sampled txns keep their
        # proxy-level milestones but lose per-hop spans (counted dropped)
        sampled = [c for c in ctxs if c is not None]
        batch_ctx = sampled[0] if sampled else None
        if len(sampled) > 1:
            self.spans.drop(len(sampled) - 1)
        for c in sampled:
            self.spans.event("CommitDebug", c,
                             "CommitProxyServer.commitBatch.Before",
                             Txns=len(reqs))
        batch_began = asyncio.get_running_loop().time()
        prev_version = version = None
        resolved = pushed = push_started = False
        repair_tagged: dict[int, MutationBatch] | None = None
        is_state = any(is_state_txn(r) for r in reqs)
        loop = asyncio.get_running_loop()
        try:
            t0 = loop.time()
            prev_version, version = await self.sequencer.get_commit_version()
            self.stages.record("version_wait", loop.time() - t0)
            for c in sampled:
                self.spans.event("CommitDebug", c,
                                 "CommitProxyServer.commitBatch."
                                 "GotCommitVersion", Version=version)
            txns = [TxnRequest(r.read_conflict_ranges, r.write_conflict_ranges,
                               r.read_snapshot) for r in reqs]
            state_txns = None
            if is_state:
                # singleton by the batcher's construction; ranges ride
                # unclipped + mutations piggyback so every resolver logs
                # the identical committed-state stream.  Packed since 713
                # (ROADMAP PR 3 follow-up (a)): one encode here, and the
                # resolver's state log + every proxy's reply share the
                # same columnar struct the rest of the pipeline speaks.
                assert len(reqs) == 1
                state_txns = [(0, MutationBatch.from_mutations(
                    reqs[0].mutations))]

            # Routed mesh (ISSUE 16): each resolver gets ONLY the txns
            # whose clipped conflict ranges are non-empty on its
            # partition (a sparse sub-batch — the index map stays here
            # and the verdicts scatter back below), and a partition every
            # txn clips empty against gets a header-only version advance
            # (empty txns) it answers without touching its backend.
            # State batches stay broadcast, unclipped and alone (the
            # verdict-agreement invariant).  Knob off = the broadcast
            # twin below, verbatim.
            routed = self.knobs.RESOLVER_MESH_ROUTING and not is_state
            final = [COMMITTED] * len(reqs)

            # broadcast to all resolvers, clipped to each partition
            async def ask(res: Resolver):
                sent = txns if is_state else \
                    [clip_txn_to_range(t, res.key_range) for t in txns]
                return await res.resolve(
                    ResolveBatchRequest(prev_version, version, sent,
                                        state_txns,
                                        self.state_applied_version))

            async def ask_routed(res: Resolver, sub: list[TxnRequest]):
                return await res.resolve(
                    ResolveBatchRequest(prev_version, version, sub, None,
                                        self.state_applied_version))
            t0 = loop.time()
            # the resolver hop inherits a child span via the contextvar:
            # gather's tasks copy the active context at creation, so the
            # (possibly remote) resolvers see the sampled trace
            if routed:
                index_maps: list[list[int]] = []
                subs: list[list[TxnRequest]] = []
                for ri, res in enumerate(self.resolvers):
                    sub, idx = [], []
                    for i, t in enumerate(txns):
                        ct = clip_txn_to_range(t, res.key_range)
                        if ct.read_ranges or ct.write_ranges:
                            sub.append(ct)
                            idx.append(i)
                    subs.append(sub)
                    index_maps.append(idx)
                    st = self.route_stats[ri]
                    st["sends"] += 1
                    st["txns_routed"] += len(sub)
                    if not sub:
                        st["header_only"] += 1
                    # per-partition scatter events (ISSUE 17 satellite):
                    # a sampled txn's timeline shows WHICH partitions
                    # resolved it — and which answered header-only —
                    # instead of one opaque resolve hop
                    for c in sampled:
                        self.spans.event("CommitDebug", c,
                                         "CommitProxyServer.commitBatch."
                                         "RoutedScatter", Partition=ri,
                                         Txns=len(sub),
                                         HeaderOnly=int(not sub))
                with _span.child_scope(batch_ctx):
                    replies = await asyncio.gather(
                        *(ask_routed(r, sub)
                          for r, sub in zip(self.resolvers, subs)))
                for c in sampled:
                    self.spans.event("CommitDebug", c,
                                     "CommitProxyServer.commitBatch."
                                     "RoutedGather", Version=version,
                                     Partitions=len(self.resolvers))
                # scatter the sparse verdicts into the AND-join: a txn a
                # partition never judged contributes COMMITTED there —
                # identical to broadcasting its empty clip (no ranges,
                # no conflict).  TOO_OLD dominates, then CONFLICT.
                # A reply carrying abort_words (RESOLVER_VERDICT_BITMASK)
                # takes the bitmask join: all-COMMITTED partitions — the
                # steady-state majority — skip the scatter outright, and
                # aborting ones touch only their set bits.
                for reply, idx in zip(replies, index_maps):
                    if not self._join_abort_words(reply, final, idx):
                        for j, v in zip(idx, reply.verdicts):
                            final[j] = max(final[j], v)
            else:
                with _span.child_scope(batch_ctx):
                    replies = await asyncio.gather(
                        *(ask(r) for r in self.resolvers))
                # AND the verdicts: TOO_OLD dominates, then CONFLICT
                for reply in replies:
                    if not self._join_abort_words(reply, final, None):
                        for i, v in enumerate(reply.verdicts):
                            final[i] = max(final[i], v)
            self.stages.record("resolve", loop.time() - t0)
            resolved = True
            for c in sampled:
                self.spans.event("CommitDebug", c,
                                 "CommitProxyServer.commitBatch."
                                 "AfterResolution", Version=version)

            # apply the committed state stream (our own state batch AND
            # other proxies' — identical on every resolver, take the
            # first's; a header-only reply still carries the piggyback)
            # BEFORE tagging, then tag with the map as of THIS batch's
            # version
            my_markers = self._apply_state_entries(
                replies[0].state_entries, own_version=version)
            shard_map = self.map_at(version)
            backup_tags = self.backup_tags_at(version)
            # database lock, authoritative as of THIS version (the state
            # entries above include any lock/unlock committed before us in
            # version order).  Applies to USER transactions only: their
            # exclusion from tagging is side-effect-free (the resolver
            # write-history entry causes at most spurious conflicts, never
            # a durable mutation).  A state txn that slipped the
            # pre-resolution check in the lock's propagation window
            # commits normally — its metadata is already in every
            # resolver's stream, and acking it keeps client and cluster
            # state consistent (the lock fences state txns steady-state,
            # like the reference).
            lock_uid = None if is_state else self.locked_at(version)

            # tag mutations of committed txns, in batch order; the log
            # system replicates each tag onto its hosting logs.  With a
            # backup tag active, the whole ordered stream rides under it
            # too (the continuous mutation-log backup feed).  The packed
            # MutationBatch is built ONCE here; each tag's payload is an
            # index slice of it (``select``), and a tag owning every
            # mutation — the single-shard common case — ships the batch
            # itself with zero copies.
            builder = MutationBatchBuilder()
            tag_idx: dict[int, list[int]] = {}
            order = 0
            orders: list[int] = [0] * len(reqs)
            locked_out: set[int] = set()
            for i, (req, verdict) in enumerate(zip(reqs, final)):
                if verdict != COMMITTED:
                    continue
                if lock_uid is not None and not getattr(req, "lock_aware",
                                                        False):
                    locked_out.add(i)
                    continue
                orders[i] = order
                for m in req.mutations:
                    m = self._substitute_versionstamp(m, version, order)
                    if m.type == MutationType.CLEAR_RANGE:
                        tags = shard_map.tags_for_range(m.param1, m.param2)
                    else:
                        tags = shard_map.tags_for_key(m.param1)
                    mi = builder.add(int(m.type), m.param1, m.param2)
                    for t in tags:
                        tag_idx.setdefault(t, []).append(mi)
                    for bt in backup_tags:
                        # a backup tag numerically colliding with a
                        # storage tag must not index the mutation twice
                        # (the seed's list append duplicated it — which
                        # double-applied atomics on that replica)
                        if bt not in tags:
                            tag_idx.setdefault(bt, []).append(mi)
                order += 1
            # private markers for metadata this batch committed (shard
            # handoffs, feed register/pop/destroy): each addressed tag
            # sees the marker at exactly this version in its own stream
            for t, mt, p1, p2 in my_markers:
                mi = builder.add(mt, p1, p2)
                tag_idx.setdefault(t, []).append(mi)
            batch_packed = builder.finish()
            tagged: dict[int, MutationBatch] = {
                t: batch_packed.select(ix) for t, ix in tag_idx.items()}
            repair_tagged = tagged

            push_started = True
            t0 = loop.time()
            with _span.child_scope(batch_ctx):
                await self.log_system.push(prev_version, version, tagged,
                                           self._known_committed)
            self.stages.record("push", loop.time() - t0)
            pushed = True
            if version > self._known_committed:
                self._known_committed = version
            for c in sampled:
                self.spans.event("CommitDebug", c,
                                 "CommitProxyServer.commitBatch."
                                 "AfterLogPush", Version=version)
            self.sequencer.report_committed(version)

            self.total_batches += 1
            self.counters.counter("CommitBatchIn").add(1)
            self.latency_hist.sample_seconds(
                asyncio.get_running_loop().time() - batch_began)
            for i, fut in enumerate(futs):
                if fut.done():
                    continue
                self.spans.event("CommitDebug", ctxs[i],
                                 "CommitProxyServer.commitBatch.Reply",
                                 Version=version,
                                 Committed=bool(final[i] == COMMITTED
                                                and i not in locked_out))
                if i in locked_out:
                    from ..runtime.errors import DatabaseLocked
                    fut.set_exception(DatabaseLocked())
                elif final[i] == COMMITTED:
                    self.total_committed += 1
                    self.counters.counter("TxnCommitOut").add(1)
                    fut.set_result(CommitResult(
                        version, pack_versionstamp(version, orders[i])))
                elif final[i] == TOO_OLD:
                    self.total_conflicts += 1
                    self.counters.counter("TxnConflicts").add(1)
                    fut.set_exception(TransactionTooOld())
                else:
                    self.total_conflicts += 1
                    self.counters.counter("TxnConflicts").add(1)
                    fut.set_exception(NotCommitted())
        except asyncio.CancelledError:
            # cancelled mid-push (role stop during an epoch change): some
            # TLog may already hold the batch, so the outcome is exactly
            # as ambiguous as the non-cancel failure path — a freely
            # retryable error here would let a client double-commit
            err = CommitUnknownResult() if push_started \
                else ClusterVersionChanged()
            for fut in futs:
                if not fut.done():
                    fut.set_exception(err)
            raise
        except Exception as e:
            from ..runtime.trace import TraceEvent
            TraceEvent("CommitBatchFailed", severity=30) \
                .detail("Version", version).detail("Resolved", resolved) \
                .detail("Pushed", pushed).detail("Error", repr(e)[:200]).log()
            for c in sampled:
                self.spans.event("CommitDebug", c,
                                 "CommitProxyServer.commitBatch.Error",
                                 Version=version, Error=type(e).__name__)
            # once any TLog may hold the batch, the outcome is ambiguous:
            # clients must see commit_unknown_result (maybe-committed), not
            # a freely-retryable transport error that would double-apply
            # mutations on retry (REF: NativeAPI tryCommit error mapping)
            client_err = CommitUnknownResult() if push_started else e
            for fut in futs:
                if not fut.done():
                    fut.set_exception(client_err)
            # complete the version chain: downstream roles are waiting on
            # prev_version ordering, and an abandoned version would wedge
            # every later batch cluster-wide
            if version is not None:
                await self._repair_chain(prev_version, version, resolved,
                                         pushed, repair_tagged,
                                         carries_state=is_state,
                                         cause=e)

    async def _repair_chain(self, prev_version: Version, version: Version,
                            resolved: bool, pushed: bool,
                            tagged: dict[int, MutationBatch] | None = None,
                            carries_state: bool = False,
                            cause: BaseException | None = None) -> None:
        """Complete an interrupted batch's version chain.  Once the batch
        RESOLVED, its verdicts (and any committed state transaction) are
        in every resolver's history, so the repair must push the batch's
        REAL payload — an empty substitute would let later batches commit
        durably on top of a layout change that never reached the logs
        (TLog pushes ack duplicates idempotently, so re-pushing a
        partially-delivered version is safe).  If a STATE-bearing batch
        resolved but the failure hit BEFORE tagging was computed
        (``tagged is None``), the payload cannot be reconstructed: the
        committed state txn is in every resolver's stream with its
        metadata mutations unrecoverable here.  Pushing an empty
        substitute would durably erase it, so the proxy FAIL-STOPS —
        refuses further commits and probes dead on its role-liveness
        slot — forcing an epoch recovery that rebuilds from the
        resolvers' state streams.  A pure USER batch in the same spot is
        safe to repair with an empty push: its clients already hold
        commit_unknown_result (maybe-committed permits not-committed),
        and the stray resolver write history costs at most spurious
        conflicts inside the MVCC window."""
        if resolved and tagged is None and carries_state:
            from ..runtime.trace import TraceEvent
            self._failed = cause or RuntimeError("unrepairable state batch")
            TraceEvent("CommitBatchUnrepairable", severity=30) \
                .detail("Version", version).log()
            return
        try:
            if not resolved:
                await asyncio.gather(*(r.resolve(
                    ResolveBatchRequest(prev_version, version, [], None,
                                        self.state_applied_version))
                    for r in self.resolvers))
            if not pushed:
                await self.log_system.push(prev_version, version,
                                           tagged if resolved and tagged
                                           else {}, self._known_committed)
            self.sequencer.report_committed(version)
        except Exception:
            pass  # a failed repair means the epoch is dead; recovery's job

    @staticmethod
    def _substitute_versionstamp(m: Mutation, version: Version,
                                 order: int) -> Mutation:
        """Splice the 10-byte commit versionstamp into key/value at the
        trailing 4-byte little-endian offset (API ≥ 520 wire format,
        REF:fdbserver/CommitProxyServer.actor.cpp)."""
        if m.type == MutationType.SET_VERSIONSTAMPED_KEY:
            stamped = CommitProxy._splice(m.param1, version, order)
            return Mutation(MutationType.SET_VALUE, stamped, m.param2)
        if m.type == MutationType.SET_VERSIONSTAMPED_VALUE:
            stamped = CommitProxy._splice(m.param2, version, order)
            return Mutation(MutationType.SET_VALUE, m.param1, stamped)
        return m

    @staticmethod
    def _splice(param: bytes, version: Version, order: int) -> bytes:
        if len(param) < 4:
            raise ValueError("versionstamp param lacks offset suffix")
        pos = struct.unpack("<I", param[-4:])[0]
        raw = param[:-4]
        if pos + 10 > len(raw):
            raise ValueError("versionstamp offset out of range")
        return raw[:pos] + pack_versionstamp(version, order) + raw[pos + 10:]
