"""Coordination — the generation register and leader election.

Reference: REF:fdbserver/Coordination.actor.cpp (GenerationReg /
coordinationServer) + REF:fdbserver/LeaderElection.actor.cpp — a small set
of coordinator processes store the cluster's most important few hundred
bytes (who leads, which TLog generation is live) behind a Paxos-flavored
generation register:

- ``read(gen)``: a reader first *registers* its read generation; the
  coordinator promises never to accept a write from any older generation,
  and returns the freshest (write_gen, value) it has accepted.
- ``write(gen, value)``: accepted iff ``gen`` is newer than both the
  largest read generation registered and the largest write generation
  accepted.

A client that completes both phases against a **majority** of
coordinators knows its value is the unique latest — the single-decree
Paxos core FDB uses for cluster state (CoordinatedState).  Leader
election rides the same machinery plus per-coordinator candidacy
tracking with virtual-time leases.

State is durable when a filesystem is provided (OnDemandStore analog):
a coordinator that reboots remembers its promises.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Any

from ..runtime.errors import FdbError, _err
from ..runtime.knobs import Knobs
from ..runtime.trace import TraceEvent

NotLatestGeneration = _err(2903, "not_latest_generation",
                           "A newer generation has been seen by this coordinator")
CoordinatorsUnreachable = _err(
    2904, "coordinators_unreachable",
    "No majority of coordinators reachable")


# generations order lexicographically: (counter, candidate_id)
Generation = tuple[int, int]
GEN_ZERO: Generation = (0, 0)


@dataclasses.dataclass
class LeaderInfo:
    leader_id: int
    address: Any            # NetworkAddress of the cluster controller
    lease_end: float        # virtual-time lease expiry (coordinator clock)


class Coordinator:
    """One coordinator process (role "coordinator")."""

    def __init__(self, knobs: Knobs, fs=None, path: str | None = None) -> None:
        self.knobs = knobs
        self._fs = fs
        self._path = path
        self.max_read_gen: Generation = GEN_ZERO
        self.write_gen: Generation = GEN_ZERO
        self.value: Any = None
        self._leader: LeaderInfo | None = None

    # --- durability (OnDemandStore) ---

    @classmethod
    async def open(cls, knobs: Knobs, fs, path: str) -> "Coordinator":
        from ..rpc.wire import decode
        co = cls(knobs, fs, path)
        f = fs.open(path)
        data = await f.read(0, f.size())
        if data:
            try:
                st = decode(data)
                co.max_read_gen = tuple(st["r"])
                co.write_gen = tuple(st["w"])
                co.value = st["v"]
            except Exception:
                TraceEvent("CoordStateCorrupt", severity=30).detail(
                    "Path", path).log()
        return co

    async def _persist(self) -> None:
        if self._fs is None:
            return
        from ..rpc.wire import encode
        f = self._fs.open(self._path)
        await f.truncate(0)
        await f.write(0, encode({"r": list(self.max_read_gen),
                                 "w": list(self.write_gen),
                                 "v": self.value}))
        await f.sync()

    # --- generation register (GenerationRegInterface) ---

    async def read(self, gen: list | Generation) -> tuple[Generation, Generation, Any]:
        """Register a read at ``gen``; promise excludes older writers.
        Returns (max_read_gen, write_gen, value)."""
        gen = tuple(gen)
        if gen > self.max_read_gen:
            self.max_read_gen = gen
            await self._persist()
        return self.max_read_gen, self.write_gen, self.value

    async def write(self, gen: list | Generation, value: Any) -> Generation:
        """Accept iff gen is at least as new as every promise; returns the
        coordinator's max read generation (so a rejected writer learns
        what to beat)."""
        gen = tuple(gen)
        if gen < self.max_read_gen or gen <= self.write_gen:
            raise NotLatestGeneration()
        self.write_gen = gen
        self.value = value
        await self._persist()
        return self.max_read_gen

    async def open_database(self) -> Any:
        """Read-only client entry (OpenDatabaseCoordRequest analog): hand
        back the latest accepted cluster state WITHOUT registering a read
        generation — clients must never invalidate writers."""
        return self.value

    # --- leader election (LeaderElectionRegInterface) ---

    async def candidacy(self, candidate_id: int, address: Any) -> tuple[int, Any]:
        """Offer to lead; returns the current leader (possibly the caller).
        First viable candidate wins until its lease lapses."""
        now = asyncio.get_running_loop().time()
        if self._leader is None or now >= self._leader.lease_end:
            self._leader = LeaderInfo(
                candidate_id, address,
                now + self.knobs.LEADER_LEASE_DURATION)
            TraceEvent("CoordLeaderChange").detail("Leader", candidate_id).log()
        return self._leader.leader_id, self._leader.address

    async def read_leader(self) -> tuple[int, Any] | None:
        """Read-only leader query (the reference's monitorLeader side):
        returns the CURRENT unexpired leader or None — never grants.
        Candidacy-on-read is what seeds leader ping-pong: a respawned
        (empty) coordinator would grant to the first caller while the
        quorum still honors the incumbent's lease."""
        now = asyncio.get_running_loop().time()
        if self._leader is not None and now < self._leader.lease_end:
            return self._leader.leader_id, self._leader.address
        return None

    async def leader_heartbeat(self, candidate_id: int) -> bool:
        """Renew the lease; False tells a deposed leader to stand down."""
        now = asyncio.get_running_loop().time()
        if self._leader is not None and self._leader.leader_id == candidate_id \
                and now < self._leader.lease_end:
            self._leader.lease_end = now + self.knobs.LEADER_LEASE_DURATION
            return True
        return False


class CoordinatedState:
    """Client view over a quorum of coordinators — CoordinatedState /
    MovableCoordinatedState in the reference: read-modify-write of the
    cluster state blob with single-decree safety."""

    def __init__(self, coordinators: list, my_id: int,
                 knobs: Knobs | None = None) -> None:
        self.coordinators = coordinators      # Coordinator objects or stubs
        self.my_id = my_id
        self.knobs = knobs
        self._gen_counter = 0
        self._read_gen: Generation | None = None

    @property
    def _majority(self) -> int:
        return len(self.coordinators) // 2 + 1

    async def _quorum(self, calls) -> list:
        """Run calls; return successful results, raising unless a
        majority succeeded.  Each call is individually bounded: a dead
        coordinator must cost at most the bound — derived from the knobs
        like elect_leader's — not stall the whole round (its vote just
        doesn't count)."""
        timeout = (self.knobs.FAILURE_TIMEOUT * 2
                   if self.knobs is not None else 4.0)

        async def bounded(c):
            return await asyncio.wait_for(c, timeout)

        results = await asyncio.gather(*(bounded(c) for c in calls),
                                       return_exceptions=True)
        ok = [r for r in results if not isinstance(r, BaseException)]
        if len(ok) < self._majority:
            real = [r for r in results if isinstance(r, FdbError)]
            if real and all(isinstance(r, NotLatestGeneration) for r in real):
                raise NotLatestGeneration()
            raise CoordinatorsUnreachable()
        return ok

    async def read(self) -> tuple[Generation, Any]:
        """Phase-1 read from a majority: registers a fresh read generation
        and returns (read_gen, freshest accepted value).  After this, no
        writer at an older generation can commit at any majority (the two
        majorities intersect at a coordinator holding our promise)."""
        self._gen_counter += 1
        gen = (self._gen_counter, self.my_id)
        replies = await self._quorum(
            [c.read(list(gen)) for c in self.coordinators])
        # learn the newest generation around so the next read beats it
        max_seen = max(r[0] for r in replies)
        self._gen_counter = max(self._gen_counter, max_seen[0])
        self._read_gen = gen
        best = max(replies, key=lambda r: r[1])    # freshest accepted write
        return gen, best[2]

    async def write(self, value: Any) -> None:
        """Phase-2 write at the generation of OUR read phase — never a
        fresher one, or a value committed after our read could be silently
        overwritten (the single-decree Paxos ballot discipline).  Raises
        NotLatestGeneration if a newer reader/writer got in; the caller
        must re-read (adopting the newer value) before retrying."""
        if self._read_gen is None:
            raise RuntimeError("write() before read()")
        gen, self._read_gen = self._read_gen, None
        await self._quorum([c.write(list(gen), value)
                            for c in self.coordinators])

    async def read_modify_write(self, update) -> Any:
        """Retry loop: read, apply ``update(old) -> new``, write."""
        while True:
            _, old = await self.read()
            new = update(old)
            try:
                await self.write(new)
                return new
            except NotLatestGeneration:
                await asyncio.sleep(0.05)


async def elect_leader(coordinators: list, candidate_id: int, address: Any,
                       knobs: Knobs) -> tuple[int, Any]:
    """Find (or become) the leader.

    Phase 0 — read-only: if a MAJORITY already agrees on a live leader,
    follow it without nominating.  Nominating unconditionally lets a
    freshly-restarted coordinator (empty register) grant its slot to
    whichever bystander asks first, seeding split grants and leadership
    ping-pong while the incumbent is perfectly healthy.

    Phase 1 — candidacy, only when no live-leader majority exists:
    returns the winning (leader_id, address) the quorum agrees on (ties
    broken by count, then lowest id — deterministic).

    Every per-coordinator RPC is bounded well under the lease duration:
    an unreachable coordinator otherwise delays the round past the
    winner's own lease (its grant expires before the winner ever learns
    it won — the region-failover stand-down loop)."""
    rpc_timeout = min(knobs.LEADER_LEASE_DURATION / 4,
                      knobs.FAILURE_TIMEOUT)

    async def bounded(c):
        return await asyncio.wait_for(c, rpc_timeout)

    reads = await asyncio.gather(
        *(bounded(c.read_leader()) for c in coordinators),
        return_exceptions=True)
    tally0: dict[tuple[int, Any], int] = {}
    for r in reads:
        if isinstance(r, BaseException) or r is None:
            continue
        a = r[1]
        key = (r[0], tuple(a) if isinstance(a, list) else a)
        tally0[key] = tally0.get(key, 0) + 1
    if tally0:
        (lid, laddr), votes = max(tally0.items(), key=lambda kv: kv[1])
        if votes >= len(coordinators) // 2 + 1:
            return lid, laddr
    results = await asyncio.gather(
        *(bounded(c.candidacy(candidate_id, address)) for c in coordinators),
        return_exceptions=True)
    ok = [r for r in results if not isinstance(r, BaseException)]
    if len(ok) < len(coordinators) // 2 + 1:
        raise CoordinatorsUnreachable()
    tally: dict[tuple[int, Any], int] = {}
    for r in ok:
        # addresses decode from the wire as lists; normalize for hashing
        a = r[1]
        key = (r[0], tuple(a) if isinstance(a, list) else a)
        tally[key] = tally.get(key, 0) + 1
    (leader_id, addr), _ = min(tally.items(),
                               key=lambda kv: (-kv[1], kv[0][0]))
    return leader_id, addr
