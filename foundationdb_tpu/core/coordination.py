"""Coordination — the generation register and leader election.

Reference: REF:fdbserver/Coordination.actor.cpp (GenerationReg /
coordinationServer) + REF:fdbserver/LeaderElection.actor.cpp — a small set
of coordinator processes store the cluster's most important few hundred
bytes (who leads, which TLog generation is live) behind a Paxos-flavored
generation register:

- ``read(gen)``: a reader first *registers* its read generation; the
  coordinator promises never to accept a write from any older generation,
  and returns the freshest (write_gen, value) it has accepted.
- ``write(gen, value)``: accepted iff ``gen`` is newer than both the
  largest read generation registered and the largest write generation
  accepted.

A client that completes both phases against a **majority** of
coordinators knows its value is the unique latest — the single-decree
Paxos core FDB uses for cluster state (CoordinatedState).  Leader
election rides the same machinery plus per-coordinator candidacy
tracking with virtual-time leases.

State is durable when a filesystem is provided (OnDemandStore analog):
a coordinator that reboots remembers its promises.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Any

from ..runtime.errors import FdbError, _err
from ..runtime.knobs import Knobs
from ..runtime.trace import TraceEvent

# 2910/2911: these used to claim 2903/2904, COLLIDING with the
# change-feed errors in runtime/errors.py — error_from_code resolved
# whichever registered last, so a feed stream's change_feed_not_
# registered surfaced client-side as not_latest_generation and escaped
# the cursor's handling (found by the ISSUE 12 hostile-disk farm at
# seed 4: io-error-induced stream failovers hit the mistyped path)
NotLatestGeneration = _err(2910, "not_latest_generation",
                           "A newer generation has been seen by this coordinator")
CoordinatorsUnreachable = _err(
    2911, "coordinators_unreachable",
    "No majority of coordinators reachable")


# generations order lexicographically: (counter, candidate_id)
Generation = tuple[int, int]
GEN_ZERO: Generation = (0, 0)


@dataclasses.dataclass
class LeaderInfo:
    leader_id: int
    address: Any            # NetworkAddress of the cluster controller
    lease_end: float        # virtual-time lease expiry (coordinator clock)


@dataclasses.dataclass
class Nomination:
    candidate_id: int
    address: Any
    expires: float          # nominations are soft: a dead candidate's
                            # entry lapses and the next-best takes over


class Coordinator:
    """One coordinator process (role "coordinator")."""

    def __init__(self, knobs: Knobs, fs=None, path: str | None = None) -> None:
        self.knobs = knobs
        self._fs = fs
        self._path = path
        self.max_read_gen: Generation = GEN_ZERO
        self.write_gen: Generation = GEN_ZERO
        self.value: Any = None
        self._leader: LeaderInfo | None = None
        self._nominations: dict[int, Nomination] = {}
        # set by change_coordinators (MovableCoordinatedState's forward
        # pointer): once retired, this coordinator refuses register and
        # election traffic and forwards callers to the new set
        self.moved_to: list | None = None

    # --- durability (OnDemandStore) ---

    @classmethod
    async def open(cls, knobs: Knobs, fs, path: str) -> "Coordinator":
        """Recover from the newest valid of two alternating crc-framed
        slots (ISSUE 12).  The state used to be truncate-rewritten in
        place, so a kill tearing the write (truncate persisted, data
        dropped) silently reset this coordinator to GEN_ZERO — a
        split-brain seed the hostile-disk sim surfaces immediately.  The
        un-written slot always holds the previous synced state; the
        legacy single file is still read for pre-slot disks."""
        from ..rpc.wire import SlottedBlob, decode, unframe
        co = cls(knobs, fs, path)
        co._slots = SlottedBlob(fs, path)
        best = None
        payload, slots_seen = await co._slots.load()
        found = slots_seen
        if payload is not None:
            best = decode(payload)
        if best is None:
            # pre-helper slot format (ISSUE 12): crc-framed dict with
            # its own embedded seq
            for suffix in (".a", ".b"):
                f = fs.open(path + suffix)
                data = await f.read(0, f.size())
                if not data:
                    continue
                try:
                    st = decode(unframe(data))
                except Exception:  # noqa: BLE001 — torn slot: other wins
                    continue
                if best is None or st.get("seq", 0) > best.get("seq", 0):
                    best = st
            if best is not None:
                # keep alternation continuous across the envelope
                # migration (never clobber the only valid slot)
                co._slots.seed(best.get("seq", 0))
        if best is None and slots_seen >= 2:
            # both slots populated yet neither decodes: a crash always
            # leaves the previously-synced slot intact (the write
            # alternates), so this is corruption of COMMITTED quorum
            # state — silently resetting to GEN_ZERO would let a stale
            # leader win a quorum it already lost (the split-brain seed
            # the dual slots exist to prevent; ISSUE 12)
            from ..runtime.errors import DiskCorrupt
            raise DiskCorrupt(
                f"both coordinator state slots of {path} are damaged — "
                f"refusing to silently reset the quorum state")
        if best is None:
            f = fs.open(path)
            data = await f.read(0, f.size())
            if data:
                found += 1
                try:
                    best = decode(data)
                except Exception:  # noqa: BLE001 — legacy torn write
                    pass
        if best is not None:
            co.max_read_gen = tuple(best["r"])
            co.write_gen = tuple(best["w"])
            co.value = best["v"]
            co.moved_to = best.get("m")
        elif found:
            TraceEvent("CoordStateCorrupt", severity=30).detail(
                "Path", path).detail("Slots", found).log()
        return co

    _slots = None
    _persist_lock = None

    async def _persist(self) -> None:
        if self._fs is None:
            return
        from ..rpc.wire import SlottedBlob, encode
        # serialized: concurrent RPC handlers must never have BOTH slots
        # dirty at once (a kill could then tear both, and the recovery
        # invariant "one synced slot always survives" would not hold),
        # nor write their seqs out of order.  The seq/slot-turn
        # discipline lives in the shared SlottedBlob (ISSUE 13).
        if self._persist_lock is None:
            import asyncio
            self._persist_lock = asyncio.Lock()
        async with self._persist_lock:
            if self._slots is None:
                self._slots = SlottedBlob(self._fs, self._path)
            await self._slots.save(encode({
                "r": list(self.max_read_gen),
                "w": list(self.write_gen),
                "v": self.value,
                "m": self.moved_to}))

    # --- quorum migration (MovableCoordinatedState,
    #     REF:fdbserver/Coordination.actor.cpp) ---

    def _check_moved(self) -> None:
        if self.moved_to is not None:
            from ..runtime.errors import CoordinatorsChanged
            raise CoordinatorsChanged()

    async def move(self, new_addrs: list) -> bool:
        """Retire this coordinator: record the forward pointer and refuse
        all register/election traffic from now on.  Idempotent.  Called
        by change_coordinators AFTER the cluster state has been copied to
        the new quorum — so a visible forward pointer always implies the
        new set is authoritative."""
        if self.moved_to is None:
            self.moved_to = [list(a) if isinstance(a, tuple) else a
                             for a in new_addrs]
            self._leader = None
            self._nominations.clear()
            await self._persist()
            TraceEvent("CoordinatorMoved").detail(
                "NewSet", str(self.moved_to)).log()
        return True

    async def get_forward(self) -> list | None:
        """Where did this quorum go?  None while still serving."""
        return self.moved_to

    # --- generation register (GenerationRegInterface) ---

    async def read(self, gen: list | Generation) -> tuple[Generation, Generation, Any]:
        """Register a read at ``gen``; promise excludes older writers.
        Returns (max_read_gen, write_gen, value)."""
        self._check_moved()
        gen = tuple(gen)
        if gen > self.max_read_gen:
            self.max_read_gen = gen
            await self._persist()
        return self.max_read_gen, self.write_gen, self.value

    async def write(self, gen: list | Generation, value: Any) -> Generation:
        """Accept iff gen is at least as new as every promise; returns the
        coordinator's max read generation (so a rejected writer learns
        what to beat)."""
        self._check_moved()
        gen = tuple(gen)
        if gen < self.max_read_gen or gen <= self.write_gen:
            raise NotLatestGeneration()
        self.write_gen = gen
        self.value = value
        await self._persist()
        return self.max_read_gen

    async def open_database(self) -> Any:
        """Read-only client entry (OpenDatabaseCoordRequest analog): hand
        back the latest accepted cluster state WITHOUT registering a read
        generation — clients must never invalidate writers.  After a
        quorum change, clients get the forward pointer instead."""
        if self.moved_to is not None:
            return {"__moved_to__": self.moved_to}
        return self.value

    # --- leader election (LeaderElectionRegInterface) ---
    #
    # Two-phase nominate/confirm (REF:fdbserver/LeaderElection.actor.cpp
    # CandidacyRequest -> LeaderHeartbeat): NOMINATE records a candidate
    # without granting anything; each coordinator independently converges
    # on a deterministic best nominee; CONFIRM grants the lease only when
    # the confirmer is still this coordinator's best nominee AND no other
    # leader holds an unexpired lease.  Grant-on-first-ask (the previous
    # single-phase candidacy) let a freshly-restarted coordinator hand
    # its slot to whichever bystander asked first — split grants and
    # leadership ping-pong under churn.  With two phases, two candidates
    # can never both assemble confirming majorities inside one lease:
    # the majorities intersect at a coordinator whose lease guard
    # rejects the second confirm.

    def _best_nominee(self, now: float) -> "Nomination | None":
        live = [n for n in self._nominations.values() if now < n.expires]
        if not live:
            return None
        return min(live, key=lambda n: n.candidate_id)

    async def nominate(self, candidate_id: int, address: Any) -> list:
        """Phase 1: record/refresh this candidacy; grants nothing.
        Returns [0, leader_id, addr] when an unexpired confirmed leader
        exists, else [1, best_nominee_id, addr]."""
        self._check_moved()
        now = asyncio.get_running_loop().time()
        self._nominations[candidate_id] = Nomination(
            candidate_id, address, now + self.knobs.NOMINATION_TIMEOUT)
        if self._leader is not None and now < self._leader.lease_end:
            return [0, self._leader.leader_id, self._leader.address]
        best = self._best_nominee(now)
        return [1, best.candidate_id, best.address]

    async def confirm(self, candidate_id: int, round_id: int = 0) -> bool:
        """Phase 2: grant the lease iff the caller is still this
        coordinator's best nominee and no OTHER unexpired leader exists.
        Idempotent for the incumbent (True without extending the lease —
        renewal is leader_heartbeat's job).  ``round_id`` fences the grant
        against stale withdraws (see withdraw)."""
        self._check_moved()
        now = asyncio.get_running_loop().time()
        if self._leader is not None and now < self._leader.lease_end:
            if self._leader.leader_id == candidate_id:
                # monotonic re-fence: a DELAYED confirm from an older
                # round must never lower the fence, or the matching stale
                # withdraw could revoke the newer win
                self._lease_round = max(
                    getattr(self, "_lease_round", 0), round_id)
                return True
            return False
        best = self._best_nominee(now)
        if best is None or best.candidate_id != candidate_id:
            return False
        self._leader = LeaderInfo(
            candidate_id, best.address,
            now + self.knobs.LEADER_LEASE_DURATION)
        self._lease_round = round_id
        TraceEvent("CoordLeaderChange").detail("Leader", candidate_id).log()
        return True

    async def withdraw(self, candidate_id: int, round_id: int = 0) -> bool:
        """Release a lease this candidate holds HERE (losing candidates
        call this after a failed confirm round).  A candidate that won
        confirm at only a minority otherwise parks those coordinators
        behind its unexpired lease for LEADER_LEASE_DURATION, stalling
        the next election wave.  Safe: the caller did not believe it was
        leader in ``round_id`` (it saw < majority), and the round fence
        rejects a withdraw delivered late (e.g. past a client timeout
        over TCP) after the SAME candidate legitimately won a LATER
        confirm round — without it, the stale withdraw would revoke the
        new lease and open a split-brain window."""
        if self._leader is not None \
                and self._leader.leader_id == candidate_id \
                and getattr(self, "_lease_round", 0) == round_id:
            self._leader = None
            TraceEvent("CoordLeaseWithdrawn") \
                .detail("Candidate", candidate_id).log()
            return True
        return False

    async def read_leader(self) -> tuple[int, Any] | None:
        """Read-only leader query (the reference's monitorLeader side):
        returns the CURRENT unexpired leader or None — never grants.
        Candidacy-on-read is what seeds leader ping-pong: a respawned
        (empty) coordinator would grant to the first caller while the
        quorum still honors the incumbent's lease."""
        self._check_moved()
        now = asyncio.get_running_loop().time()
        if self._leader is not None and now < self._leader.lease_end:
            return self._leader.leader_id, self._leader.address
        return None

    async def leader_heartbeat(self, candidate_id: int) -> bool:
        """Renew the lease; False tells a deposed leader to stand down."""
        self._check_moved()
        now = asyncio.get_running_loop().time()
        if self._leader is not None and self._leader.leader_id == candidate_id \
                and now < self._leader.lease_end:
            self._leader.lease_end = now + self.knobs.LEADER_LEASE_DURATION
            return True
        return False


class CoordinatedState:
    """Client view over a quorum of coordinators — CoordinatedState /
    MovableCoordinatedState in the reference: read-modify-write of the
    cluster state blob with single-decree safety."""

    def __init__(self, coordinators: list, my_id: int,
                 knobs: Knobs | None = None) -> None:
        self.coordinators = coordinators      # Coordinator objects or stubs
        self.my_id = my_id
        self.knobs = knobs
        self._gen_counter = 0
        self._read_gen: Generation | None = None

    @property
    def _majority(self) -> int:
        return len(self.coordinators) // 2 + 1

    async def _quorum(self, calls) -> list:
        """Run calls; return successful results, raising unless a
        majority succeeded.  Each call is individually bounded: a dead
        coordinator must cost at most the bound — derived from the knobs
        like elect_leader's — not stall the whole round (its vote just
        doesn't count)."""
        timeout = (self.knobs.FAILURE_TIMEOUT * 2
                   if self.knobs is not None else 4.0)

        async def bounded(c):
            return await asyncio.wait_for(c, timeout)

        results = await asyncio.gather(*(bounded(c) for c in calls),
                                       return_exceptions=True)
        ok = [r for r in results if not isinstance(r, BaseException)]
        if len(ok) < self._majority:
            real = [r for r in results if isinstance(r, FdbError)]
            if real and all(isinstance(r, NotLatestGeneration) for r in real):
                raise NotLatestGeneration()
            from ..runtime.errors import CoordinatorsChanged
            if any(isinstance(r, CoordinatorsChanged) for r in real):
                # a retired quorum: the caller must follow the forward
                # pointers (get_forward) to the new set
                raise CoordinatorsChanged()
            raise CoordinatorsUnreachable()
        return ok

    async def read(self, raw: bool = False) -> tuple[Generation, Any]:
        """Phase-1 read from a majority: registers a fresh read generation
        and returns (read_gen, freshest accepted value).  After this, no
        writer at an older generation can commit at any majority (the two
        majorities intersect at a coordinator holding our promise).

        If the freshest value is a quorum-change INTENT marker (written by
        change_coordinators phase 1), normal consumers get
        CoordinatorsChanged carrying the target set — the caller must
        complete or follow the move (ClusterHost does).  ``raw=True``
        (the mover itself) returns the marker."""
        self._gen_counter += 1
        gen = (self._gen_counter, self.my_id)
        replies = await self._quorum(
            [c.read(list(gen)) for c in self.coordinators])
        # learn the newest generation around so the next read beats it
        max_seen = max(r[0] for r in replies)
        self._gen_counter = max(self._gen_counter, max_seen[0])
        self._read_gen = gen
        best = max(replies, key=lambda r: r[1])    # freshest accepted write
        value = best[2]
        if not raw and isinstance(value, dict) and "__moving_to__" in value:
            from ..runtime.errors import CoordinatorsChanged
            e = CoordinatorsChanged()
            e.moving_to = value["__moving_to__"]
            e.inner_value = value.get("__value__")
            raise e
        return gen, value

    async def write(self, value: Any) -> None:
        """Phase-2 write at the generation of OUR read phase — never a
        fresher one, or a value committed after our read could be silently
        overwritten (the single-decree Paxos ballot discipline).  Raises
        NotLatestGeneration if a newer reader/writer got in; the caller
        must re-read (adopting the newer value) before retrying."""
        if self._read_gen is None:
            raise RuntimeError("write() before read()")
        gen, self._read_gen = self._read_gen, None
        await self._quorum([c.write(list(gen), value)
                            for c in self.coordinators])

    async def read_modify_write(self, update) -> Any:
        """Retry loop: read, apply ``update(old) -> new``, write."""
        while True:
            _, old = await self.read()
            new = update(old)
            try:
                await self.write(new)
                return new
            except NotLatestGeneration:
                await asyncio.sleep(0.05)


async def change_coordinators(old_coords: list, new_coords: list,
                              new_addrs: list, knobs: Knobs,
                              mover_id: int = 0) -> None:
    """Change the coordinator set — changeQuorum
    (REF:fdbclient/ManagementAPI.actor.cpp::changeQuorum over
    MovableCoordinatedState, REF:fdbserver/Coordination.actor.cpp).

    Three phases, each crash-safe:
      1. INTENT through the OLD quorum: the cluster-state value is
         replaced by a generation-fenced marker {__moving_to__, __value__}.
         Any concurrent writer (another mover, the CC) now conflicts; any
         reader learns the move and can complete it (ClusterHost does).
      2. COPY: the preserved value is written into the NEW quorum's
         registers.  A crash before phase 3 leaves the old quorum
         authoritative-but-marked; re-running is idempotent.
      3. RETIRE: every old coordinator records the forward pointer and
         refuses register/election traffic (majority required; the rest
         best-effort — a visible forward always implies phase 2 is done,
         so two quorums can never both accept writes: the old set's
         majority is fenced by the intent generation until retired, and
         retired coordinators serve only the forward).

    ``new_addrs`` are the wire-shaped addresses ([ip, port]) recorded in
    forward pointers and intent markers; ``new_coords`` the matching
    stubs (or Coordinator objects in-process)."""
    if len(new_coords) != len(new_addrs) or not new_coords:
        raise ValueError("new coordinator stubs/addresses mismatch")
    wire_addrs = [list(a) if isinstance(a, tuple) else
                  ([a.ip, a.port] if hasattr(a, "ip") else list(a))
                  for a in new_addrs]
    cs_old = CoordinatedState(old_coords, mover_id, knobs=knobs)
    while True:
        _gen, cur = await cs_old.read(raw=True)
        if isinstance(cur, dict) and "__moving_to__" in cur:
            # an interrupted move: preserve the ORIGINAL value; our
            # target set wins via the generation fence below
            cur = cur.get("__value__")
        try:
            await cs_old.write({"__moving_to__": wire_addrs,
                                "__value__": cur})
            break
        except NotLatestGeneration:
            # the CC wrote cstate between our read and write: adopt the
            # newer value and retry the intent (read_modify_write loop)
            await asyncio.sleep(0.05)
    await complete_coordinator_move(old_coords, new_coords, wire_addrs,
                                    cur, knobs, mover_id)
    TraceEvent("CoordinatorsChangedOK").detail(
        "NewSet", str(wire_addrs)).log()


async def complete_coordinator_move(old_coords: list, new_coords: list,
                                    wire_addrs: list, value: Any,
                                    knobs: Knobs, mover_id: int = 0) -> None:
    """Phases 2-3 of change_coordinators — also the completion path a
    ClusterHost runs when it finds an interrupted move's intent marker.

    Clobber guard: if ANY old coordinator already serves a forward
    pointer, phase 2 is known complete and a new-set CC may already be
    writing newer state there — the copy is skipped and only the
    retirement of the remaining old coordinators is finished.
    Concurrent completers that both pass the guard write the SAME
    preserved value (idempotent)."""
    timeout = (knobs.FAILURE_TIMEOUT * 2 if knobs is not None else 4.0)

    async def fwd(c):
        return await asyncio.wait_for(c.get_forward(), timeout)

    fwds = await asyncio.gather(*(fwd(c) for c in old_coords),
                                return_exceptions=True)
    already = any(f for f in fwds if f and not isinstance(f, BaseException))
    if not already:
        cs_new = CoordinatedState(new_coords, mover_id, knobs=knobs)
        try:
            await cs_new.read(raw=True)
            await cs_new.write(value)
        except NotLatestGeneration:
            pass    # a racing completer's identical copy won — fine

    async def retire(c):
        return await asyncio.wait_for(c.move(wire_addrs), timeout)

    # coordinators in BOTH sets keep serving (the common replace-one
    # operation); safety holds because any still-electable old majority
    # and any new majority intersect at a shared coordinator whose
    # single-lease guard serializes the two elections
    new_keys = {tuple(a) for a in wire_addrs}

    def shared(c) -> bool:
        if c in new_coords:
            return True
        a = getattr(c, "_address", None)
        return a is not None and (a.ip, a.port) in {(k[0], k[1])
                                                    for k in new_keys}

    retiring = [c for c in old_coords if not shared(c)]
    if retiring:
        acks = await asyncio.gather(*(retire(c) for c in retiring),
                                    return_exceptions=True)
        good = sum(1 for a in acks if a is True)
        if good < len(retiring) // 2 + 1:
            raise CoordinatorsUnreachable()


def _addr_key(a: Any):
    """Addresses decode from the wire as lists; normalize for hashing."""
    return tuple(a) if isinstance(a, list) else a


def _addr_restore(a: Any):
    return list(a) if isinstance(a, tuple) else a


async def elect_leader(coordinators: list, candidate_id: int, address: Any,
                       knobs: Knobs) -> tuple[int, Any]:
    """Find (or become) the leader — two-phase nominate/confirm.

    Phase 0 — read-only: if a MAJORITY already agrees on a live leader,
    follow it without nominating (a healthy incumbent is never disturbed
    by an election storm — nominations grant nothing, but skipping them
    keeps restarted-coordinator registers quiet).

    Phase 1 — nominate: record this candidacy at every coordinator and
    learn each one's deterministic best nominee (lowest live candidate
    id) or its confirmed leader.  A majority reporting the same
    confirmed leader ⇒ follow it.

    Phase 2 — confirm, only when a majority's best nominee is US: each
    coordinator re-checks its own nominee view and incumbent lease at
    grant time, so two candidates can never both assemble confirming
    majorities inside one lease.  A majority of True ⇒ we lead.

    Otherwise (someone else is the convergent nominee, or the confirm
    race was lost) back off with per-candidate deterministic jitter and
    retry until ELECTION_TIMEOUT, then raise CoordinatorsUnreachable so
    the caller's outer loop takes over.  Every per-coordinator RPC is
    bounded well under the lease duration: an unreachable coordinator
    must not delay a round past the winner's own lease."""
    from ..runtime.rng import DeterministicRandom

    k = knobs
    rpc_timeout = min(k.LEADER_LEASE_DURATION / 4, k.FAILURE_TIMEOUT)
    majority = len(coordinators) // 2 + 1
    loop = asyncio.get_running_loop()
    # jitter decorrelates candidates' retry rounds; seeding off the
    # candidate id keeps simulation replays exact
    rng = DeterministicRandom((candidate_id << 16) ^ 0x1eade1ec)
    deadline = loop.time() + k.ELECTION_TIMEOUT

    async def bounded(c):
        return await asyncio.wait_for(c, rpc_timeout)

    def top(tally: dict) -> tuple[tuple[int, Any], int] | None:
        if not tally:
            return None
        # deterministic: most votes, ties to the lowest candidate id
        return min(tally.items(), key=lambda kv: (-kv[1], kv[0][0]))

    async def poll_leader() -> tuple[int, Any] | None:
        """Read-only leader check: a MAJORITY agreeing on one unexpired
        leader ⇒ (id, addr), else None."""
        reads = await asyncio.gather(
            *(bounded(c.read_leader()) for c in coordinators),
            return_exceptions=True)
        tally: dict[tuple[int, Any], int] = {}
        for r in reads:
            if isinstance(r, BaseException) or r is None:
                continue
            key = (r[0], _addr_key(r[1]))
            tally[key] = tally.get(key, 0) + 1
        best = top(tally)
        if best is not None and best[1] >= majority:
            (lid, laddr), _ = best
            return lid, _addr_restore(laddr)
        return None

    # Liveness under asymmetric partitions: a candidate every coordinator
    # converges on (it can NOMINATE everywhere) whose CONFIRM path is
    # broken would otherwise keep refreshing its nominations forever and
    # park the election — rivals can never become best nominee.  After
    # two consecutive failed confirm rounds as the convergent nominee,
    # stand down: stop nominating long enough for our nominations to
    # lapse (NOMINATION_TIMEOUT) so rivals converge, while still polling
    # read-only for the leader they elect.
    failed_confirms = 0
    # Round fence for confirm/withdraw: a withdraw delivered late (past a
    # client timeout) must not revoke a lease won in a LATER round.
    # Seeded from the monotonic clock so rounds stay strictly increasing
    # ACROSS elect_leader invocations of the same candidate — a stale
    # withdraw from a previous invocation must not match a fresh win's
    # fence (the coordinator-side fence is monotonic too).
    round_id = int(loop.time() * 1e6)

    while True:
        # Phase 0: follow an already-confirmed live leader.
        led = await poll_leader()
        if led is not None:
            return led

        # Phase 1: nominate everywhere; tally leaders and nominees.
        noms = await asyncio.gather(
            *(bounded(c.nominate(candidate_id, address))
              for c in coordinators),
            return_exceptions=True)
        ok = [r for r in noms if not isinstance(r, BaseException)]
        if len(ok) < majority:
            from ..runtime.errors import CoordinatorsChanged
            if any(isinstance(r, CoordinatorsChanged) for r in noms):
                # a retired quorum: surface the typed error so the caller
                # follows the forward pointers instead of blind-retrying
                raise CoordinatorsChanged()
            raise CoordinatorsUnreachable()
        lead_tally: dict[tuple[int, Any], int] = {}
        nom_tally: dict[tuple[int, Any], int] = {}
        for kind, cid, a in ok:
            t = lead_tally if kind == 0 else nom_tally
            key = (cid, _addr_key(a))
            t[key] = t.get(key, 0) + 1
        bestl = top(lead_tally)
        if bestl is not None and bestl[1] >= majority:
            (lid, laddr), _ = bestl
            return lid, _addr_restore(laddr)

        # Phase 2: confirm only when the convergent nominee is us.
        bestn = top(nom_tally)
        if bestn is not None and bestn[1] >= majority \
                and bestn[0][0] == candidate_id:
            round_id += 1
            confs = await asyncio.gather(
                *(bounded(c.confirm(candidate_id, round_id))
                  for c in coordinators),
                return_exceptions=True)
            if sum(1 for r in confs if r is True) >= majority:
                return candidate_id, address
            failed_confirms += 1
            # Lost the round: release every lease this round may have
            # granted — including at coordinators whose True reply was
            # LOST (timeout), or they stay parked behind the unexpired
            # lease for LEADER_LEASE_DURATION.  Safe: we know we lost
            # round_id, and the fence stops a late-delivered withdraw
            # from revoking a lease we win in a later round.
            await asyncio.gather(
                *(bounded(c.withdraw(candidate_id, round_id))
                  for c in coordinators),
                return_exceptions=True)
            if failed_confirms >= 2:
                # our confirm path is broken while our nominate path works
                # (asymmetric partition): stand down so our nominations
                # lapse and rivals can converge; keep watching read-only
                # for whoever they elect
                failed_confirms = 0
                lapse_end = loop.time() + k.NOMINATION_TIMEOUT * 1.25
                while loop.time() < lapse_end:
                    await asyncio.sleep(k.ELECTION_BACKOFF)
                    led = await poll_leader()
                    if led is not None:
                        return led
                    if loop.time() >= deadline:
                        raise CoordinatorsUnreachable()
        else:
            failed_confirms = 0

        if loop.time() >= deadline:
            raise CoordinatorsUnreachable()
        await asyncio.sleep(k.ELECTION_BACKOFF * (0.5 + rng.random()))
