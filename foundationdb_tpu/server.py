"""fdbserver analog — the per-process entry point.

Reference: REF:fdbserver/fdbserver.actor.cpp — one process, one listen
address: serves a coordinator role when its address is named in the
cluster file, and always runs a ClusterHost (worker + election candidate
+ cluster controller when elected).  Three of these on localhost make a
working cluster:

    python -m foundationdb_tpu.server -C fdb.cluster -l 127.0.0.1:4500
    python -m foundationdb_tpu.server -C fdb.cluster -l 127.0.0.1:4501
    python -m foundationdb_tpu.server -C fdb.cluster -l 127.0.0.1:4502

Knobs are settable ``--knob_name=value`` exactly like the reference.
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import signal
import sys

from .core.cluster_controller import ClusterConfigSpec
from .core.cluster_file import ClusterFile
from .core.cluster_host import ClusterHost
from .core.coordination import Coordinator
from .rpc.stubs import CoordinatorClient, serve_role
from .rpc.tcp_transport import TcpTransport
from .rpc.transport import (NetworkAddress, WLTOKEN_COORDINATOR,
                            WLTOKEN_FIRST_AVAILABLE)
from .runtime.knobs import Knobs
from .runtime.trace import TraceEvent

BASE = WLTOKEN_FIRST_AVAILABLE


def parse_args(argv):
    ap = argparse.ArgumentParser(
        prog="foundationdb_tpu.server",
        description="Run one cluster process (worker + coordinator when "
                    "named in the cluster file).")
    ap.add_argument("-C", "--cluster-file", required=True)
    ap.add_argument("-l", "--listen", required=True, metavar="IP:PORT")
    ap.add_argument("--spec", default="", help="role counts, e.g. "
                    "logs=2,resolvers=1,storage_servers=2,min_workers=3")
    ap.add_argument("--tls-cert", default="", help="mutual TLS certificate")
    ap.add_argument("--tls-key", default="")
    ap.add_argument("--tls-ca", default="")
    args, extra = ap.parse_known_args(argv)
    knob_overrides = {}
    for e in extra:
        if e.startswith("--knob_") and "=" in e:
            name, val = e[len("--knob_"):].split("=", 1)
            knob_overrides[name] = val
        else:
            ap.error(f"unknown argument {e!r}")
    return args, knob_overrides


def parse_spec(text: str) -> ClusterConfigSpec:
    spec = ClusterConfigSpec()
    if text:
        for part in text.split(","):
            name, _, val = part.partition("=")
            if not hasattr(spec, name):
                raise SystemExit(f"unknown spec field {name!r}")
            setattr(spec, name, int(val))
    return spec


async def run_server(cluster_file: str, listen: str, spec: ClusterConfigSpec,
                     knobs: Knobs, ready_event: asyncio.Event | None = None,
                     tls=None):
    cf = ClusterFile.load(cluster_file)
    ip, _, port = listen.rpartition(":")
    addr = NetworkAddress(ip, int(port))

    transport = TcpTransport(addr, tls=tls)
    await transport.listen()

    # outbound-only client transports: a unique address identity each, no
    # listener (mirrors the reference's ephemeral outbound connections)
    counter = itertools.count(1)

    def client_transport() -> TcpTransport:
        return TcpTransport(
            NetworkAddress(ip, int(port) * 1000 + next(counter)), tls=tls)

    # EVERY process serves a coordination register (idle unless the
    # connection string names its address) so `coordinators` can move the
    # quorum onto any process — exactly like fdbserver
    coordinator = Coordinator(knobs)
    serve_role(transport, "coordinator", coordinator, WLTOKEN_COORDINATOR)
    if addr in cf.coordinators:
        TraceEvent("CoordinatorStarted").detail("Address", str(addr)).log()

    from .rpc.stubs import make_coordinator_stubs

    def coord_factory(addrs):
        return make_coordinator_stubs(addrs,
                                      transport_factory=client_transport)

    def on_repoint(addrs):
        # persist the new connection string so a restart finds the new set
        ClusterFile.repoint(cluster_file, addrs)

    coord_stubs = coord_factory(cf.coordinators)
    host_id = int(port)           # unique per process on one box
    host = ClusterHost(host_id, knobs, transport, client_transport, BASE,
                       coord_stubs, spec,
                       coordinator_factory=coord_factory,
                       on_repoint=on_repoint)
    host.start()
    TraceEvent("ServerStarted").detail("Address", str(addr)) \
        .detail("Cluster", cf.cluster_id).log()
    if ready_event is not None:
        ready_event.set()

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    await stop.wait()
    await host.stop()
    await transport.close()


def main(argv=None) -> int:
    args, knob_overrides = parse_args(argv if argv is not None else sys.argv[1:])
    # Real-TCP deployments run on wall clocks with real scheduling
    # stalls: a neighbor process's startup burst (JAX import alone costs
    # seconds of CPU) can starve the controller's heartbeat loop past
    # the sim-tuned 2s lease, churning leadership exactly when a crashed
    # server respawns.  Production-grade leases absorb such pauses; the
    # sim keeps the short ones for fast deterministic failover tests.
    # Explicit --knob overrides still win.
    knobs = Knobs().override(LEADER_LEASE_DURATION=8.0,
                             FAILURE_TIMEOUT=2.0)
    knobs = knobs.set_from_strings(knob_overrides)
    spec = parse_spec(args.spec)
    tls = None
    if args.tls_cert:
        from .rpc.tcp_transport import TlsConfig
        tls = TlsConfig(args.tls_cert, args.tls_key, args.tls_ca)
    try:
        asyncio.run(run_server(args.cluster_file, args.listen, spec, knobs,
                               tls=tls))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
