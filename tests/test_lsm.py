"""LSM engine: flush/compaction/tombstones/recovery behind IKeyValueStore.

Reference: the disk engines behind REF:fdbserver/IKeyValueStore.h
(Redwood/RocksDB); crash semantics proven with the lossy sim filesystem.
"""

from __future__ import annotations

import foundationdb_tpu.storage.lsm as lsm_mod
from foundationdb_tpu.client import Database
from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
from foundationdb_tpu.runtime.files import SimFileSystem
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.storage.kv_store import OP_CLEAR, OP_SET
from foundationdb_tpu.storage.lsm import LSMKVStore


def test_lsm_basic_and_recovery(monkeypatch):
    monkeypatch.setattr(lsm_mod, "_MEMTABLE_BYTES", 2000)
    monkeypatch.setattr(lsm_mod, "_BLOCK_BYTES", 256)
    monkeypatch.setattr(lsm_mod, "_MAX_RUNS", 3)

    async def main():
        fs = SimFileSystem()
        kv = await LSMKVStore.open(fs, "db/lsm")
        # enough writes to force several flushes + a compaction
        for round_ in range(8):
            ops = [(OP_SET, b"k%03d" % i, b"r%d-%03d" % (round_, i))
                   for i in range(40)]
            await kv.commit(ops, {"durable_version": round_})
        assert len(kv._runs) <= 3 + 1, "compaction never ran"
        assert kv.get(b"k005") == b"r7-005"
        assert kv.get(b"nope") is None
        # clears become tombstones that win over older runs
        await kv.commit([(OP_CLEAR, b"k010", b"k020")], {"durable_version": 9})
        assert kv.get(b"k015") is None
        rows = list(kv.range(b"k000", b"k999"))
        assert [k for k, _ in rows] == [b"k%03d" % i for i in range(40)
                                        if not (10 <= i < 20)]
        assert all(v == b"r7-%03d" % int(k[1:]) for k, v in rows)
        # reverse scan agrees
        rrows = list(kv.range(b"k000", b"k999", reverse=True))
        assert rrows == list(reversed(rows))
        await kv.close()

        # reopen: durable state identical (runs + WAL replay)
        kv2 = await LSMKVStore.open(fs, "db/lsm")
        assert kv2.meta == {"durable_version": 9}
        assert kv2.get(b"k015") is None
        assert list(kv2.range(b"k000", b"k999")) == rows
        await kv2.close()
    run_simulation(main())


def test_lsm_crash_loses_only_unsynced(monkeypatch):
    monkeypatch.setattr(lsm_mod, "_MEMTABLE_BYTES", 100_000)

    async def main():
        fs = SimFileSystem()
        kv = await LSMKVStore.open(fs, "db/crash")
        await kv.commit([(OP_SET, b"a", b"1")], {"durable_version": 1})
        # a write applied in memory but never committed (no WAL fsync)
        kv._apply_mem([(OP_SET, b"b", b"2")])
        fs.kill_unsynced()          # machine dies
        kv2 = await LSMKVStore.open(fs, "db/crash")
        assert kv2.get(b"a") == b"1"      # fsync'd commit survives
        assert kv2.get(b"b") is None      # unsynced write is gone
        await kv2.close()
    run_simulation(main())


def test_cluster_restart_resume_on_lsm_engine():
    """The durable-cluster restart test, on the LSM engine: committed data
    survives a full stop/start cycle through runs + WAL replay."""
    async def main():
        fs = SimFileSystem()
        k = Knobs().override(STORAGE_ENGINE="lsm")
        cluster = await Cluster.create(ClusterConfig(), k, fs=fs,
                                       data_dir="lsmclu")
        async with cluster:
            db = Database(cluster)
            for i in range(30):
                await db.set(b"p%02d" % i, b"v%02d" % i)
        cluster2 = await Cluster.create(ClusterConfig(), k, fs=fs,
                                        data_dir="lsmclu")
        async with cluster2:
            db2 = Database(cluster2)
            for i in range(30):
                assert await db2.get(b"p%02d" % i) == b"v%02d" % i
            rows = await db2.get_range(b"p", b"q", limit=0)
            assert len(rows) == 30
    run_simulation(main())
