"""metrics_tool acceptance (ISSUE 15): from a sim run's trace FILE
alone, ``lag`` reconstructs the per-tag durability-lag time-series,
``recovery`` shows the full version-cut audit of an INDUCED recovery
(epoch 1's initial recovery and the requested epoch 2), ``summary``
lists every role's series, and ``diff`` of a run against itself is
clean."""

from __future__ import annotations

import asyncio
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import metrics_tool  # noqa: E402

from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.runtime.trace import (Severity, TraceLog,
                                            get_trace_log, set_trace_log)
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster


def _record_sim(path: str) -> None:
    """A durable 4-machine sim: commits, several metric intervals, one
    INDUCED recovery (request_recovery → epoch 2), more intervals —
    all recorded to the trace file at ``path``."""
    log = TraceLog(path=path, min_severity=Severity.INFO)
    prev = get_trace_log()
    set_trace_log(log)
    try:
        knobs = Knobs().override(METRICS_INTERVAL=0.5,
                                 METRICS_EMITTER=True,
                                 STORAGE_DURABILITY_LAG=0.1)

        async def main():
            sim = SimulatedCluster(knobs, n_machines=4,
                                   durable_storage=True,
                                   spec=ClusterConfigSpec(min_workers=4,
                                                          replication=2))
            await sim.start()
            await asyncio.wait_for(sim.wait_epoch(1), 120)
            db = await sim.database()
            for i in range(6):
                async def body(tr, i=i):
                    tr.set(b"mt%04d" % i, b"v" * 64)
                await db.run(body)
            await asyncio.sleep(2.0)
            # the induced recovery the audit view must replay
            sim.leader_cc().request_recovery("metrics_tool-acceptance")
            await asyncio.wait_for(sim.wait_epoch(2), 120)
            await asyncio.sleep(2.0)
            await sim.stop()

        run_simulation(main(), seed=1504)
    finally:
        set_trace_log(prev)
        log.close()


def test_metrics_tool_views_from_trace_file_alone(tmp_path):
    path = os.path.join(str(tmp_path), "flight.jsonl")
    _record_sim(path)

    events = metrics_tool._load([path])
    assert events, "the sim recorded nothing"

    # --- summary: every core role kind has a series with a cadence ---
    summary = metrics_tool.summarize(events)
    kinds = {k.split("/")[0] for k in summary["series"]}
    for kind in ("ProxyCommitMetrics", "GrvProxyMetrics",
                 "ResolverMetrics", "TLogMetrics", "StorageMetrics",
                 "SequencerMetrics", "RatekeeperMetrics",
                 "WorkerMetrics", "ClusterControllerMetrics"):
        assert kind in kinds, (kind, sorted(kinds))
    storage_series = [v for k, v in summary["series"].items()
                      if k.startswith("StorageMetrics/")]
    assert storage_series and all(
        v["cadence_mean_s"] is not None and v["cadence_mean_s"] <= 1.5
        for v in storage_series if v["n"] >= 3)

    # --- lag: the durability-lag time-series reconstructs per tag ---
    rep = metrics_tool.lag_report(events)
    assert rep["storage_series"], "no storage lag series reconstructed"
    assert all(n >= 2 for n in rep["storage_series"].values())
    series = rep["series"]["storage"]
    # a durable cluster under load recorded a real nonzero lag sample
    # somewhere (durability ticks lag applies by ~0.1s of versions)
    assert any(r["lag_versions"] > 0
               for rows in series.values() for r in rows), series
    # and the samples carry the window/queue gauges alongside
    assert all({"t", "lag_versions", "queue_bytes", "window_versions"}
               <= set(r) for rows in series.values() for r in rows)

    # --- recovery: both epochs' full audit, cuts included ---
    recs = metrics_tool.recovery_report(events)
    epochs = [r["epoch"] for r in recs]
    assert 1 in epochs and 2 in epochs, epochs
    by_epoch = {r["epoch"]: r for r in recs}
    for e in (1, 2):
        rec = by_epoch[e]
        assert rec["completed"], rec
        steps = [s["Step"] for s in rec["steps"]]
        assert steps[0] == "locking_cstate"
        assert "recruiting" in steps and "writing_cstate" in steps
        assert steps[-1] == "accepting_commits"
        assert rec["recovery_version"] is not None
    # epoch 2 locked the previous generation: its cut must be recorded
    locked = next(s for s in by_epoch[2]["steps"]
                  if s["Step"] == "locked_tlogs")
    assert locked["Tips"] and \
        locked["RecoveryVersion"] == min(locked["Tips"])
    assert locked["GenerationEnd"] == locked["RecoveryVersion"]
    # epoch 2's rejoin adopted the durable storage replicas
    assert by_epoch[2]["recovery_version"] > 0

    # --- diff of a run against itself: no deltas, full overlap ---
    d = metrics_tool.diff_report(events, events)
    assert d["series_a"] == d["series_b"] > 0
    assert all("only_in" not in r and r.get("max_rel", 0.0) == 0.0
               for r in d["rows"])

    # --- the CLI surfaces run end to end on the same file ---
    for view in (["summary"], ["lag", "--series"], ["recovery"],
                 ["diff", path, path]):
        argv = [view[0]] + (view[1:] if view[0] == "diff"
                            else [path] + view[1:])
        assert metrics_tool.main(argv) == 0
