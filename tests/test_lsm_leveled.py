"""Leveled, budgeted, background LSM compaction (ISSUE 14).

The lsm engine's compaction rebuilt as a leveled, partitioned,
budget-sliced background subsystem behind knob
``LSM_LEVELED_COMPACTION`` (ROADMAP item 5 (d)): L0 holds overlapping
flush runs, L1+ hold key-range-disjoint partitions, one compaction
rewrites only its slice plus the OVERLAPPING next-level partitions, and
``commit()`` never awaits a merge.  What this file proves:

- randomized leveled-vs-monolithic EQUIVALENCE: the same seeded op
  stream (sets, range clears, re-sets — tombstones crossing levels)
  serves byte-identically on both twins via ``get``/``get_batch``/
  ``range_runs``, DURING compaction, after a full drain, and after a
  reopen;
- the L1+ level invariants hold after every drain (span-disjoint,
  span-sorted partitions);
- crash-mid-compaction under ``DiskFaultProfile`` torn+corrupt kills
  swept across the compaction timeline (between run write, manifest,
  and input removal) recovers to a valid run set serving exactly the
  acked data — in either crash direction — and the orphan sweep leaves
  no unnamed run files behind;
- a PRE-leveled MANIFEST (no per-run levels) opens as all-L0, serves,
  and compacts in place — a pre-PR disk upgrades transparently;
- a reopened store with inherited run debt starts compacting without
  waiting for the next memtable overflow (the decoupled trigger).
"""

from __future__ import annotations

import asyncio
import random

import pytest

from foundationdb_tpu.rpc.wire import decode, encode
from foundationdb_tpu.runtime.files import DiskFaultProfile, SimFileSystem
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.rng import DeterministicRandom
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.storage.lsm import LSMKVStore

import foundationdb_tpu.storage.lsm as lsm_mod


@pytest.fixture(autouse=True)
def small_lsm(monkeypatch):
    """Tier-1-sized geometry: tiny memtable/blocks so flushes, leveled
    merges, trivial moves and multi-level spill all run in seconds."""
    monkeypatch.setattr(lsm_mod, "_MEMTABLE_BYTES", 6000)
    monkeypatch.setattr(lsm_mod, "_BLOCK_BYTES", 1024)
    monkeypatch.setattr(lsm_mod, "_MAX_RUNS", 3)


def _knobs(leveled: bool) -> Knobs:
    # a small slice budget so merges actually hit their yield points
    return Knobs().override(LSM_LEVELED_COMPACTION=leveled,
                            LSM_COMPACT_SLICE_BYTES=4096,
                            LSM_LEVEL_FANOUT=4)


def _op_stream(seed: int, n_commits: int, keyspace: int):
    """Seeded commit batches: sets with varied value sizes, ~5% range
    clears (tombstones that must cross levels correctly), re-sets of
    cleared keys."""
    rng = random.Random(seed)
    commits = []
    for _ in range(n_commits):
        batch = []
        for _ in range(rng.randrange(8, 40)):
            if rng.random() < 0.05:
                lo = rng.randrange(keyspace)
                hi = min(keyspace, lo + rng.randrange(1, keyspace // 8))
                batch.append((1, b"k%06d" % lo, b"k%06d" % hi))
            else:
                k = b"k%06d" % rng.randrange(keyspace)
                batch.append((0, k, bytes([rng.randrange(256)])
                              * rng.randrange(1, 80)))
        commits.append(batch)
    return commits


def _probes(keyspace: int, fmt: bytes = b"k%06d") -> list[bytes]:
    return sorted(fmt % i for i in range(0, keyspace, 7))


def _snapshot(kv, keyspace: int, fmt: bytes = b"k%06d"):
    """The full serving surface: batched points + flattened range runs
    (bytes-normalized so block-aliasing differences can't mask or fake
    a divergence)."""
    got = kv.get_batch(_probes(keyspace, fmt))
    assert any(g is not None for g in got), (
        "every point probe missed — the probe format does not match "
        "the keys this test writes")
    rows = [(bytes(k), bytes(v))
            for run in kv.range_runs(b"", b"\xff\xff")
            for k, v in run]
    return got, rows


def _check_level_invariants(kv) -> None:
    """L0 is anything; every deeper level must be span-sorted and
    span-disjoint — the property that lets a compaction select only
    the overlapping partitions."""
    for lvl, runs in enumerate(kv._levels[1:], start=1):
        for a, b in zip(runs, runs[1:]):
            assert a.first_key() <= b.first_key(), \
                f"L{lvl} partitions out of span order"
            assert a.last_key() < b.first_key(), \
                f"L{lvl} partitions overlap: {a.path} vs {b.path}"
        for r in runs:
            assert r.level == lvl


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_leveled_vs_monolithic_equivalence_randomized(seed):
    """Same op stream → byte-identical get/get_batch/range_runs on both
    twins: sampled DURING compaction (mid-stream, debt outstanding),
    after a drain, and after a reopen."""
    keyspace = 3000
    commits = _op_stream(seed, n_commits=120, keyspace=keyspace)

    async def ingest(leveled: bool):
        fs = SimFileSystem()
        kv = await LSMKVStore.open(fs, "db/lsm", knobs=_knobs(leveled))
        mid = []
        for i, batch in enumerate(commits):
            await kv.commit(batch, {"durable_version": i + 1})
            if i % 37 == 36:
                # serving must be correct WHILE the background
                # compactor holds debt — no drain before sampling
                mid.append(_snapshot(kv, keyspace))
        if leveled:
            await kv.wait_compaction_idle()
            _check_level_invariants(kv)
        final = _snapshot(kv, keyspace)
        metrics = kv.metrics()      # before close: reopen resets counters
        await kv.close()
        kv2 = await LSMKVStore.open(fs, "db/lsm", knobs=_knobs(leveled))
        reopened = _snapshot(kv2, keyspace)
        await kv2.close()
        return mid, final, reopened, metrics

    async def main():
        mid_l, fin_l, re_l, m_l = await ingest(True)
        mid_m, fin_m, re_m, m_m = await ingest(False)
        assert mid_l == mid_m, "mid-ingest serving diverged"
        assert fin_l == fin_m, "post-drain serving diverged"
        assert re_l == re_m, "post-reopen serving diverged"
        assert fin_l == re_l, "reopen changed the leveled twin's data"
        assert m_l["lsm_leveled"] and not m_m["lsm_leveled"]
        assert m_l["lsm_compactions"] > 0, (
            "the leveled compactor never ran — this test proved nothing")

    run_simulation(main(), seed=seed)


def test_tombstones_crossing_levels_and_bottom_drop():
    """A key set, pushed to a deep level, then cleared: the tombstone
    must shadow it from every read while deeper levels still hold the
    value, survive a reopen, and drop only once it reaches the deepest
    level."""
    async def main():
        fs = SimFileSystem()
        kv = await LSMKVStore.open(fs, "db/lsm", knobs=_knobs(True))
        v = 0
        # phase 1: build a multi-level keyspace holding victim keys
        for i in range(40):
            v += 1
            await kv.commit(
                [(0, b"t%05d" % (j % 600), b"old" * 10)
                 for j in range(i * 17, i * 17 + 25)],
                {"durable_version": v})
        await kv.wait_compaction_idle()
        assert len(kv._levels) > 1, "keyspace never left L0"
        assert kv.get(b"t%05d" % 5) is not None
        # phase 2: clear a band, then re-set part of it
        v += 1
        await kv.commit([(1, b"t%05d" % 100, b"t%05d" % 300)],
                        {"durable_version": v})
        for k in range(100, 300):
            assert kv.get(b"t%05d" % k) is None, "tombstone not serving"
        v += 1
        await kv.commit([(0, b"t%05d" % 150, b"resurrected")],
                        {"durable_version": v})
        # push the tombstones down through the levels
        for i in range(40):
            v += 1
            await kv.commit(
                [(0, b"u%05d" % j, b"pad" * 10)
                 for j in range(i * 25, i * 25 + 25)],
                {"durable_version": v})
        await kv.wait_compaction_idle()
        def check(kv):
            for k in range(100, 300):
                want = b"resurrected" if k == 150 else None
                assert kv.get(b"t%05d" % k) == want
            rows = {bytes(r[0]) for run in kv.range_runs(b"t", b"u")
                    for r in run}
            assert b"t%05d" % 99 in rows
            assert b"t%05d" % 150 in rows
            assert b"t%05d" % 200 not in rows
        check(kv)
        await kv.close()
        kv2 = await LSMKVStore.open(fs, "db/lsm", knobs=_knobs(True))
        check(kv2)
        await kv2.close()
    run_simulation(main())


@pytest.mark.parametrize("kill_yields", [1, 3, 7, 15, 40, 1000])
def test_crash_mid_compaction_recovers(kill_yields):
    """Torn+corrupt kills swept across the compaction timeline: the
    budget-sliced compactor yields the loop every few KB of merged
    input, so killing after N loop yields cuts it mid-run-write,
    around a manifest install, or (N large) after a full drain.  At
    every cut a fresh open serves exactly the acked data, then drains
    the inherited debt and STILL serves it (the decoupled reopen
    trigger), with no unnamed run files left behind."""
    async def main():
        prof = DiskFaultProfile()
        prof.arm(DeterministicRandom(kill_yields), torn_p=1.0,
                 corrupt_p=1.0, sector=512)
        fs = SimFileSystem(profile=prof)
        kv = await LSMKVStore.open(fs, "db/lsm", knobs=_knobs(True))
        expected: dict[bytes, bytes] = {}
        rng = random.Random(99)
        v = 0
        for i in range(60):
            v += 1
            batch = []
            for _ in range(20):
                k = b"c%05d" % rng.randrange(800)
                val = bytes([rng.randrange(256)]) * rng.randrange(1, 60)
                batch.append((0, k, val))
                expected[k] = val
            await kv.commit(batch, {"durable_version": v})
        # the compactor is mid-flight (commit() only nudges): each
        # sleep(0) hands it one slice-budget of progress, then the
        # plug gets pulled.  Tear the unsynced bytes FIRST and copy
        # the dead disk before anything else runs — the abandoned
        # task's cancellation cleanup then touches only the old
        # (post-mortem-irrelevant) filesystem, the way a real crash
        # gives a dying process no say over the surviving platter.
        for _ in range(kill_yields):
            await asyncio.sleep(0)
        fs.kill_unsynced()              # torn + corrupted unsynced bytes
        fs2 = SimFileSystem()
        fs2.disks = {p: bytearray(b) for p, b in fs.disks.items()}
        kv._closed = True
        t = kv._compact_task
        if t is not None and not t.done():
            t.cancel()
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

        kv2 = await LSMKVStore.open(fs2, "db/lsm", knobs=_knobs(True))
        keys = sorted(expected)
        def check(kv_):
            got = kv_.get_batch(keys)
            for k, g in zip(keys, got):
                assert g == expected[k], f"lost/garbled acked key {k!r}"
            rows = [(bytes(r[0]), bytes(r[1]))
                    for run in kv_.range_runs(b"", b"\xff")
                    for r in run]
            assert rows == [(k, expected[k]) for k in keys]
        check(kv2)
        # the orphan sweep reclaimed every file the manifest does not
        # name — in BOTH crash directions
        live = {r.path for r in kv2._runs}
        assert set(fs2.listdir("db/lsm.run.")) == live
        # inherited debt drains without any new commit arriving
        await kv2.wait_compaction_idle()
        check(kv2)
        _check_level_invariants(kv2)
        await kv2.close()
    run_simulation(main(), seed=11)


def test_orphan_run_files_swept_at_open():
    """The kill-between-manifest-and-input-removal direction, staged
    exactly: run files the manifest does not name (a compaction's
    inputs the dying process never removed, or outputs it never named)
    are swept at open and never affect serving."""
    async def main():
        fs = SimFileSystem()
        kv = await LSMKVStore.open(fs, "db/lsm", knobs=_knobs(True))
        rng = random.Random(3)
        v = 0
        for i in range(30):
            v += 1
            await kv.commit(
                [(0, b"o%05d" % rng.randrange(400), b"y" * 45)
                 for _ in range(20)],
                {"durable_version": v})
        await kv.wait_compaction_idle()
        want = _snapshot(kv, 400, b"o%05d")
        await kv.close()
        # plant orphans: a stale duplicate of a live run under an
        # unnamed path (removal never ran) and a torn garbage file
        # (output never named)
        live = fs.listdir("db/lsm.run.")
        fs.disks["db/lsm.run.99999990"] = bytearray(fs.disks[live[0]])
        fs.disks["db/lsm.run.99999991"] = bytearray(b"\x00" * 64)
        kv2 = await LSMKVStore.open(fs, "db/lsm", knobs=_knobs(True))
        assert _snapshot(kv2, 400, b"o%05d") == want
        assert set(fs.listdir("db/lsm.run.")) == \
            {r.path for r in kv2._runs}, "orphans not swept"
        await kv2.close()
    run_simulation(main())


def test_pre_leveled_manifest_opens_serves_and_compacts():
    """A MANIFEST written before ISSUE 14 carries no per-run levels:
    it must open with every run in L0 (the monolithic twin's shape),
    serve byte-identically, and compact in place from there."""
    async def main():
        fs = SimFileSystem()
        # build real multi-run state with the MONOLITHIC twin — the
        # trigger parked sky-high so enough runs persist that the
        # leveled open inherits REAL debt — then strip the manifest
        # down to the pre-PR schema
        lsm_mod._MAX_RUNS = 99
        try:
            kv = await LSMKVStore.open(fs, "db/lsm", knobs=_knobs(False))
            rng = random.Random(5)
            v = 0
            for i in range(60):
                v += 1
                await kv.commit(
                    [(0, b"m%05d" % rng.randrange(2000),
                      bytes([rng.randrange(256)]) * 40)
                     for _ in range(20)],
                    {"durable_version": v})
        finally:
            lsm_mod._MAX_RUNS = 3
        want, rows = _snapshot(kv, 2000, b"m%05d")
        n_runs = len(kv._runs)
        assert n_runs > 1, "need a multi-run manifest for this test"
        payload, _found = await kv._man_sb.load()
        man = decode(payload)
        assert "levels" in man
        del man["levels"]               # the pre-ISSUE-14 schema
        await kv._man_sb.save(encode(man))
        await kv.close()

        kv2 = await LSMKVStore.open(fs, "db/lsm", knobs=_knobs(True))
        assert [r.level for r in kv2._runs] == [0] * n_runs, (
            "pre-leveled manifest did not load as all-L0")
        assert _snapshot(kv2, 2000, b"m%05d") == (want, rows)
        # the all-L0 debt is picked up by the open() nudge and
        # partitions into the leveled shape in place
        await kv2.wait_compaction_idle()
        assert _snapshot(kv2, 2000, b"m%05d") == (want, rows)
        _check_level_invariants(kv2)
        assert kv2.metrics()["lsm_compactions"] > 0
        await kv2.close()
        # ...and the upgraded manifest round-trips back into either mode
        kv3 = await LSMKVStore.open(fs, "db/lsm", knobs=_knobs(False))
        assert _snapshot(kv3, 2000, b"m%05d") == (want, rows)
        await kv3.close()
    run_simulation(main())


def test_reopened_store_with_inherited_debt_compacts_without_commit():
    """The decoupled trigger (ISSUE 14 satellite): run debt inherited
    through a reopen starts draining from open() itself — no commit,
    no memtable overflow needed."""
    async def main():
        fs = SimFileSystem()
        # build run debt with the compaction trigger parked sky-high,
        # so > _MAX_RUNS flush runs reach the manifest uncompacted
        lsm_mod._MAX_RUNS = 99
        try:
            kv = await LSMKVStore.open(fs, "db/lsm", knobs=_knobs(False))
            v = 0
            for i in range(40):
                v += 1
                await kv.commit(
                    [(0, b"d%05d" % ((i * 37 + j) % 400), b"x" * 50)
                     for j in range(25)],
                    {"durable_version": v})
            await kv.close()
        finally:
            lsm_mod._MAX_RUNS = 3
        # reopen in LEVELED mode with > _MAX_RUNS runs on disk
        kv2 = await LSMKVStore.open(fs, "db/lsm", knobs=_knobs(True))
        assert len(kv2._levels[0]) > lsm_mod._MAX_RUNS, (
            "build phase left no inherited debt — the test is void")
        assert kv2._compact_task is not None, (
            "open() did not nudge the compactor despite inherited "
            "L0 debt")
        await kv2.wait_compaction_idle()
        assert kv2._debt_bytes() == 0
        _check_level_invariants(kv2)
        await kv2.close()
    run_simulation(main())
