"""changeQuorum against a LIVE simulated cluster: the quorum moves to
fresh machines under traffic and mover crashes at every phase; the
cluster must converge with no split-brain and no lost data (VERDICT r4
item 3 chaos test)."""

import asyncio

from foundationdb_tpu.core.cluster_client import fetch_cluster_state
from foundationdb_tpu.core.coordination import (CoordinatedState,
                                                NotLatestGeneration,
                                                change_coordinators)
from foundationdb_tpu.rpc.stubs import CoordinatorClient
from foundationdb_tpu.rpc.transport import WLTOKEN_COORDINATOR
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster


def _new_set(sim, idxs):
    addrs = [[sim.machines[i].ip, sim.machines[i].addr.port] for i in idxs]
    t = sim.client_transport()
    from foundationdb_tpu.rpc.transport import NetworkAddress
    stubs = [CoordinatorClient(t, NetworkAddress(a[0], a[1]),
                               WLTOKEN_COORDINATOR) for a in addrs]
    return addrs, stubs


async def _rw_check(sim, key, val):
    db = await sim.database()
    tr = db.create_transaction()
    while True:
        try:
            tr.set(key, val)
            await tr.commit()
            break
        except Exception as e:  # noqa: BLE001 — retry through recoveries
            try:
                await tr.on_error(e)
            except Exception:
                tr = db.create_transaction()
    tr = db.create_transaction()
    while True:
        try:
            got = await tr.get(key)
            return got
        except Exception as e:  # noqa: BLE001
            try:
                await tr.on_error(e)
            except Exception:
                tr = db.create_transaction()


def test_change_quorum_live_cluster():
    """Clean changeQuorum under a live cluster: new set serves, data
    survives, hosts repoint, writes keep working afterwards."""
    async def main():
        sim = SimulatedCluster(n_machines=6, n_coordinators=3)
        await sim.start()
        await sim.wait_epoch(1)
        assert (await _rw_check(sim, b"before", b"move")) == b"move"

        addrs, new_stubs = _new_set(sim, [3, 4, 5])
        old_stubs = sim.coordinator_stubs()
        await change_coordinators(old_stubs, new_stubs, addrs,
                                  sim.knobs, mover_id=777)
        # clients must now find the cluster through the NEW set
        sim.coord_addrs = [sim.machines[i].addr for i in (3, 4, 5)]

        # the cluster re-elects on the new quorum and serves both old and
        # new data; hosts repoint via forward pointers
        async def converged():
            while True:
                try:
                    st = await fetch_cluster_state(sim.coordinator_stubs())
                    if st.get("epoch", 0) >= 1:
                        return st
                except Exception:  # noqa: BLE001
                    pass
                await asyncio.sleep(0.25)
        st = await asyncio.wait_for(converged(), 60.0)
        assert (await _rw_check(sim, b"after", b"quorum")) == b"quorum"
        db = await sim.database()
        tr = db.create_transaction()
        assert (await tr.get(b"before")) == b"move"
        # every machine's host eventually points at the new set
        await sim.stop()
    run_simulation(main(), seed=11)


def test_change_quorum_mover_dies_after_intent():
    """Mover crash after phase 1 (intent only): the cluster's own hosts
    complete the move; no operator intervention, no lost data."""
    async def main():
        sim = SimulatedCluster(n_machines=6, n_coordinators=3)
        await sim.start()
        await sim.wait_epoch(1)
        assert (await _rw_check(sim, b"k", b"v1")) == b"v1"

        addrs, _ = _new_set(sim, [3, 4, 5])
        old_stubs = sim.coordinator_stubs()
        # phase 1 only — the mover "dies" here
        mover = CoordinatedState(old_stubs, 888, knobs=sim.knobs)
        while True:      # the CC writes cstate concurrently: retry the fence
            _, cur = await mover.read(raw=True)
            try:
                await mover.write({"__moving_to__": addrs, "__value__": cur})
                break
            except NotLatestGeneration:
                await asyncio.sleep(0.05)

        # the CC hits the intent on its next cstate read, completes the
        # move, and the cluster converges on the new set
        sim.coord_addrs = [sim.machines[i].addr for i in (3, 4, 5)]

        async def converged():
            while True:
                try:
                    st = await fetch_cluster_state(sim.coordinator_stubs())
                    if st.get("epoch", 0) >= 1:
                        return st
                except Exception:  # noqa: BLE001
                    pass
                await asyncio.sleep(0.5)
        await asyncio.wait_for(converged(), 90.0)
        assert (await _rw_check(sim, b"k2", b"v2")) == b"v2"
        db = await sim.database()
        tr = db.create_transaction()
        assert (await tr.get(b"k")) == b"v1"
        await sim.stop()
    run_simulation(main(), seed=12)


def test_change_quorum_overlapping_set():
    """Replace ONE coordinator (the common operational move): members of
    both sets keep serving; only the replaced coordinator retires."""
    async def main():
        sim = SimulatedCluster(n_machines=6, n_coordinators=3)
        await sim.start()
        await sim.wait_epoch(1)
        assert (await _rw_check(sim, b"o", b"1")) == b"1"

        # {0,1,2} -> {1,2,3}: machine 0 retires, 1 and 2 stay
        addrs, new_stubs = _new_set(sim, [1, 2, 3])
        old_stubs = sim.coordinator_stubs()
        await change_coordinators(old_stubs, new_stubs, addrs,
                                  sim.knobs, mover_id=555)
        assert sim.machines[0].coordinator.moved_to == addrs
        assert sim.machines[1].coordinator.moved_to is None
        assert sim.machines[2].coordinator.moved_to is None
        sim.coord_addrs = [sim.machines[i].addr for i in (1, 2, 3)]

        async def converged():
            while True:
                try:
                    st = await fetch_cluster_state(sim.coordinator_stubs())
                    if st.get("epoch", 0) >= 1:
                        return st
                except Exception:  # noqa: BLE001
                    pass
                await asyncio.sleep(0.25)
        await asyncio.wait_for(converged(), 60.0)
        assert (await _rw_check(sim, b"o2", b"2")) == b"2"
        db = await sim.database()
        tr = db.create_transaction()
        assert (await tr.get(b"o")) == b"1"
        await sim.stop()
    run_simulation(main(), seed=14)


def test_change_quorum_with_machine_kill_mid_change():
    """A coordinator machine of the OLD set dies mid-change (between copy
    and retire): the move still completes and the cluster survives."""
    async def main():
        sim = SimulatedCluster(n_machines=6, n_coordinators=3)
        await sim.start()
        await sim.wait_epoch(1)
        assert (await _rw_check(sim, b"x", b"1")) == b"1"

        addrs, new_stubs = _new_set(sim, [3, 4, 5])
        old_stubs = sim.coordinator_stubs()
        # phases 1+2 by hand
        mover = CoordinatedState(old_stubs, 999, knobs=sim.knobs)
        while True:
            _, cur = await mover.read(raw=True)
            inner = cur
            try:
                await mover.write({"__moving_to__": addrs,
                                   "__value__": inner})
                break
            except NotLatestGeneration:
                await asyncio.sleep(0.05)
        csn = CoordinatedState(new_stubs, 999, knobs=sim.knobs)
        await csn.read(raw=True)
        await csn.write(inner)
        # one old coordinator machine dies before any retire
        await sim.machines[0].kill()

        sim.coord_addrs = [sim.machines[i].addr for i in (3, 4, 5)]

        async def converged():
            while True:
                try:
                    st = await fetch_cluster_state(sim.coordinator_stubs())
                    if st.get("epoch", 0) >= 1:
                        return st
                except Exception:  # noqa: BLE001
                    pass
                await asyncio.sleep(0.5)
        await asyncio.wait_for(converged(), 90.0)
        assert (await _rw_check(sim, b"y", b"2")) == b"2"
        await sim.stop()
    run_simulation(main(), seed=13)
