"""The operational workload specs (backup/DR chaos, live-move storm,
lock cycling + directory churn, region failover, engine migration) run
green at a fixed seed each — the same specs the chaos farm fans out.
"""

from __future__ import annotations

import os

import pytest

from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.spec import load_spec, run_spec

SPECS = os.path.join(os.path.dirname(__file__), "specs")


def _run(name: str, seed: int) -> dict:
    spec = load_spec(os.path.join(SPECS, name))

    async def main():
        return await run_spec(spec, seed=seed)

    return run_simulation(main(), seed=seed)


def test_backup_dr_chaos_spec():
    r = _run("backup_dr_chaos.toml", 21)
    assert r["phase1"]["BackupUnderAttrition"]["snapshots"] >= 1
    assert r["phase1"]["MachineAttrition"]["machines_killed"] == 2


def test_livemove_storm_spec():
    r = _run("livemove_storm.toml", 22)
    assert r["phase1"]["LiveMoveStorm"]["splits"] >= 1


def test_lock_directory_spec():
    r = _run("lock_directory.toml", 23)
    assert r["phase1"]["LockCycling"]["lock_cycles"] == 3
    assert r["phase1"]["DirectoryOps"]["dir_ops"] == 50   # 25 x 2 clients


def test_region_chaos_spec():
    r = _run("region_chaos.toml", 24)
    assert r["phase1"]["RegionFailover"]["failover_rounds"] == 1


def test_engine_migration_spec():
    r = _run("engine_migration_chaos.toml", 25)
    assert r["phase1"]["EngineMigration"]["migrated_replicas"] > 0


def test_api_correctness_chaos_spec():
    r = _run("api_correctness_chaos.toml", 26)
    assert r["phase1"]["ApiCorrectness"]["committed"] == 40
    assert r["phase1"]["Sideband"]["causally_checked"] == 15
    assert r["phase1"]["BankTransfer"]["transfers"] == 30
    assert r["phase1"]["MachineAttrition"]["machines_killed"] == 2
