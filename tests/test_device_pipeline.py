"""Device commit pipeline + device read serving (ISSUE 6).

Pipeline semantics against a scripted fake backend (enqueue order,
fusion, barriers, poison/drain/close), verdict parity of the CPU twin
vs the jax backend under the SAME pipeline grouping (too-old floors
included), the resolver integration (knob on/off equivalence, barrier
state batches, teardown), and the storage-side device gather path
(engine-path equivalence, staleness/threshold fallbacks, the
PackedKeyIndex generation contract the mirror keys on).
"""

from __future__ import annotations

import asyncio

import pytest

from foundationdb_tpu.device.pipeline import DevicePipeline, supports_pipeline
from foundationdb_tpu.device.read_serve import DeviceReadServer
from foundationdb_tpu.ops.batch import TxnRequest
from foundationdb_tpu.runtime.errors import ResolverFailed
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.storage.key_index import PackedKeyIndex
from foundationdb_tpu.storage.kv_store import OP_SET, MemoryKVStore


# --------------------------------------------------------------------------
# pipeline semantics over a scripted backend


class FakeBackend:
    """Minimal encoded-backend twin: records every group dispatch and
    floor update; verdicts are (version, index-in-batch) echoes so
    reorderings are detectable in the output."""

    def __init__(self, fail_on_dispatch: int | None = None,
                 fail_sync_on_dispatch: int | None = None) -> None:
        self.groups: list[tuple[list[int], int]] = []  # (versions, floor)
        self.floor = 0
        self._dispatches = 0
        self._fail_on = fail_on_dispatch
        self._fail_sync_on = fail_sync_on_dispatch

    def set_oldest_version(self, v: int) -> None:
        self.floor = max(self.floor, v)

    def resolve_group_begin(self, batches, versions):
        self._dispatches += 1
        n = self._dispatches
        if self._fail_on is not None and n == self._fail_on:
            raise RuntimeError("scripted dispatch failure")
        self.groups.append((list(versions), self.floor))

        async def finish():
            await asyncio.sleep(0)
            if self._fail_sync_on is not None and n == self._fail_sync_on:
                raise RuntimeError("scripted sync failure")
            return [[(v, i) for i in range(len(txns))]
                    for txns, v in zip(batches, versions)]

        return finish()


def _txns(n: int) -> list[TxnRequest]:
    return [TxnRequest([(b"a", b"b")], [(b"a", b"b")], 0)] * n


def _knobs(**over) -> Knobs:
    return Knobs().override(**over)


def test_supports_pipeline_probe():
    assert supports_pipeline(FakeBackend())
    assert not supports_pipeline(object())


def test_pipeline_preserves_enqueue_order_and_fuses():
    async def main():
        be = FakeBackend()
        pipe = DevicePipeline(be, _knobs(RESOLVER_GROUP_MAX=4))
        futs = [pipe.submit(_txns(2), 100 + i) for i in range(10)]
        rows = [await f for f in futs]
        await pipe.close()
        # verdicts come back per batch, in enqueue order, undisturbed by
        # the group boundaries
        assert rows == [[(100 + i, 0), (100 + i, 1)] for i in range(10)]
        # every batch was submitted upfront, so fusion packed
        # group_max-sized groups in version order
        assert [vs for vs, _ in be.groups] == [
            [100, 101, 102, 103], [104, 105, 106, 107], [108, 109]]
        m = pipe.metrics()
        assert m["device_enqueued"] == 10
        assert m["device_dispatches"] == 3
        assert m["device_batches_dispatched"] == 10
        assert m["device_readbacks"] == 3
        assert m["device_group_mean"] == pytest.approx(10 / 3, abs=0.01)
        assert m["device_queue_depth"] == 0 and m["device_inflight"] == 0
    asyncio.run(main())


def test_pipeline_slides_oldest_version_with_one_group_lag():
    async def main():
        window = 50
        be = FakeBackend()
        pipe = DevicePipeline(
            be, _knobs(RESOLVER_GROUP_MAX=2,
                       MAX_WRITE_TRANSACTION_LIFE_VERSIONS=window))
        for v in (100, 110, 120, 130):
            pipe.submit(_txns(1), v)
        await pipe.drain()
        await pipe.close()
        # group [100,110] dispatches at the epoch floor (no lag source),
        # group [120,130] at 110-50: the PREVIOUS group's last version
        assert [f for _, f in be.groups] == [0, 110 - window]
    asyncio.run(main())


def test_pipeline_barrier_ends_group():
    async def main():
        be = FakeBackend()
        pipe = DevicePipeline(be, _knobs(RESOLVER_GROUP_MAX=8))
        pipe.submit(_txns(1), 100)
        pipe.submit(_txns(1), 110, barrier=True)   # a state-txn batch
        pipe.submit(_txns(1), 120)
        await pipe.drain()
        await pipe.close()
        assert [vs for vs, _ in be.groups] == [[100, 110], [120]]
    asyncio.run(main())


def test_pipeline_poison_on_dispatch_failure():
    async def main():
        poisons = []
        be = FakeBackend(fail_on_dispatch=1)
        pipe = DevicePipeline(be, _knobs(RESOLVER_GROUP_MAX=2),
                              on_poison=poisons.append)
        futs = [pipe.submit(_txns(1), 100 + i) for i in range(5)]
        for f in futs:
            with pytest.raises(ResolverFailed):
                await f
        assert len(poisons) == 1
        assert pipe.poisoned is not None
        # a submit after poison fails immediately instead of hanging
        with pytest.raises(ResolverFailed):
            await pipe.submit(_txns(1), 200)
        assert pipe.metrics()["device_poisoned"] == 1
        await pipe.close()
    asyncio.run(main())


def test_pipeline_poison_on_sync_failure():
    async def main():
        be = FakeBackend(fail_sync_on_dispatch=1)
        pipe = DevicePipeline(be, _knobs(RESOLVER_GROUP_MAX=2))
        futs = [pipe.submit(_txns(1), 100 + i) for i in range(3)]
        outcomes = []
        for f in futs:
            try:
                outcomes.append(await f)
            except ResolverFailed:
                outcomes.append("failed")
        # the failed dispatch's two batches fail and the pipeline
        # poisons; the third batch's dispatch was already in flight
        # AHEAD of the failure (depth 2) and still delivers — exactly
        # the legacy fused path's discipline.  Nothing submitted AFTER
        # the poison resolves.
        assert outcomes == ["failed", "failed", [(102, 0)]]
        assert pipe.poisoned is not None
        with pytest.raises(ResolverFailed):
            await pipe.submit(_txns(1), 200)
        await pipe.close()
    asyncio.run(main())


def test_pipeline_pump_survives_poison_while_parked_at_depth_gate():
    """A readback failing while the pump is parked at the depth gate
    poisons the pipeline and DRAINS the queue; the resumed pump must
    exit cleanly instead of assembling an empty group and dying on
    group[-1] (regression: unhandled IndexError killed the pump task)."""
    async def main():
        be = FakeBackend(fail_sync_on_dispatch=1)
        pipe = DevicePipeline(be, _knobs(RESOLVER_GROUP_MAX=1,
                                         RESOLVER_PIPELINE_DEPTH=2))
        futs = [pipe.submit(_txns(1), 100 + i) for i in range(6)]
        for f in futs:
            try:
                await f
            except ResolverFailed:
                pass
        await pipe.drain()
        assert pipe._pump_task.done()
        assert pipe._pump_task.exception() is None   # clean exit, no crash
        await pipe.close()
    asyncio.run(main())


def test_pipeline_close_discard_fails_queued():
    async def main():
        be = FakeBackend()
        pipe = DevicePipeline(be, _knobs())
        fut = pipe.submit(_txns(1), 100)
        await pipe.close(discard=True)
        with pytest.raises(ResolverFailed):
            await fut
        with pytest.raises(ResolverFailed):
            await pipe.submit(_txns(1), 110)
    asyncio.run(main())


def test_pipeline_reset_stats_keeps_queue_state():
    async def main():
        be = FakeBackend()
        pipe = DevicePipeline(be, _knobs())
        await pipe.submit(_txns(1), 100)
        assert pipe.metrics()["device_dispatches"] == 1
        pipe.reset_stats()
        m = pipe.metrics()
        assert m["device_dispatches"] == 0 and m["device_enqueued"] == 0
        await pipe.resolve(_txns(1), 110)
        assert pipe.metrics()["device_dispatches"] == 1
        await pipe.close()
    asyncio.run(main())


# --------------------------------------------------------------------------
# verdict parity: CPU twin vs jax backend under the same pipeline


def test_pipeline_parity_numpy_vs_jax_with_evictions():
    """Both encoded backends through DevicePipeline with deterministic
    grouping over a workload whose ring evicts and whose snapshots cross
    the too-old floor: verdicts must be bit-identical (the ISSUE 6
    invariant; the perf_smoke resolve stage runs the bigger version)."""
    import sys
    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0] + "/tools")
    import perf_smoke

    knobs = Knobs().override(
        RESOLVER_BATCH_TXNS=8, RESOLVER_RANGES_PER_TXN=2,
        CONFLICT_RING_CAPACITY=256, KEY_ENCODE_BYTES=16,
        CONFLICT_WINDOW_SLOTS=32,
        MAX_WRITE_TRANSACTION_LIFE_VERSIONS=300)
    batches, versions = perf_smoke._resolve_workload(24, 8, 2, 77)

    from foundationdb_tpu.ops.backends import make_conflict_backend

    async def run(kind: str) -> list:
        be = make_conflict_backend(
            knobs.override(RESOLVER_CONFLICT_BACKEND=kind))
        pipe = DevicePipeline(be, knobs)
        futs = [pipe.submit(t, v) for t, v in zip(batches, versions)]
        rows = [await f for f in futs]
        await pipe.close()
        return [x for r in rows for x in r]

    twin = asyncio.run(run("numpy"))
    dev = asyncio.run(run("tpu"))
    assert twin == dev
    from foundationdb_tpu.ops.batch import TOO_OLD
    assert any(x == TOO_OLD for x in twin), \
        "workload failed to exercise the too-old boundary"


# --------------------------------------------------------------------------
# resolver integration


def _resolve_requests(n_batches: int, seed: int):
    import sys
    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0] + "/tools")
    import perf_smoke

    from foundationdb_tpu.core.resolver import ResolveBatchRequest

    batches, versions = perf_smoke._resolve_workload(n_batches, 6, 2, seed)
    reqs = []
    prev = 0
    for txns, v in zip(batches, versions):
        reqs.append(ResolveBatchRequest(prev, v, txns))
        prev = v
    return reqs


def test_resolver_pipeline_knob_equivalence():
    """The SAME request stream through a pipeline-on and a pipeline-off
    resolver yields identical verdicts (numpy backend; serial awaited
    submission so both paths see one batch per dispatch)."""
    from foundationdb_tpu.core.resolver import Resolver

    reqs = _resolve_requests(20, 99)

    def run(pipeline_on: bool) -> list:
        knobs = Knobs().override(
            RESOLVER_BATCH_TXNS=6, RESOLVER_RANGES_PER_TXN=2,
            CONFLICT_RING_CAPACITY=256, KEY_ENCODE_BYTES=16,
            MAX_WRITE_TRANSACTION_LIFE_VERSIONS=300,
            RESOLVER_DEVICE_PIPELINE=pipeline_on)

        async def main():
            r = Resolver(knobs)
            assert (r._pipeline is not None) == pipeline_on
            out = []
            for req in reqs:
                reply = await r.resolve(req)
                out.extend(reply.verdicts)
            await r.stop()
            return out
        return asyncio.run(main())

    assert run(True) == run(False)


def test_resolver_stop_discards_pipeline():
    from foundationdb_tpu.core.resolver import Resolver

    reqs = _resolve_requests(4, 5)
    knobs = Knobs().override(
        RESOLVER_BATCH_TXNS=6, RESOLVER_RANGES_PER_TXN=2,
        CONFLICT_RING_CAPACITY=256, KEY_ENCODE_BYTES=16)

    async def main():
        r = Resolver(knobs)
        assert r._pipeline is not None
        fut = r._pipeline.submit([t for t in reqs[0].txns], reqs[0].version)
        await r.stop()
        with pytest.raises(ResolverFailed):
            await fut
        # metrics still answer after teardown (status probes survive)
        m = await r.metrics()
        assert m["device_poisoned"] == 1
    asyncio.run(main())


def test_legacy_dispatch_loop_survives_poison_while_parked_at_gate():
    """The knob-OFF twin of the pump depth-gate regression: a group sync
    failing while the legacy _dispatch_loop is parked at the in-flight
    gate poisons the resolver and drains _pending; the resumed loop must
    exit cleanly instead of assembling an empty group and dying on
    group[-1] (IndexError)."""
    from foundationdb_tpu.core.resolver import Resolver

    reqs = _resolve_requests(4, 42)
    knobs = Knobs().override(
        RESOLVER_BATCH_TXNS=6, RESOLVER_RANGES_PER_TXN=2,
        CONFLICT_RING_CAPACITY=256, KEY_ENCODE_BYTES=16,
        RESOLVER_DEVICE_PIPELINE=False,
        RESOLVER_GROUP_MAX=1, RESOLVER_MAX_INFLIGHT_GROUPS=1)

    async def main():
        r = Resolver(knobs)
        assert r._pipeline is None and r._fuse
        r.backend = FakeBackend(fail_sync_on_dispatch=1)
        outs = await asyncio.gather(*(r.resolve(req) for req in reqs),
                                    return_exceptions=True)
        assert all(isinstance(o, ResolverFailed) for o in outs)
        for _ in range(5):      # let the parked loop resume and exit
            await asyncio.sleep(0)
        assert r._dispatch_task.done()
        assert r._dispatch_task.exception() is None
    asyncio.run(main())


def test_resolver_metrics_carry_pipeline_counters():
    from foundationdb_tpu.core.resolver import Resolver

    knobs = Knobs().override(RESOLVER_BATCH_TXNS=6,
                             CONFLICT_RING_CAPACITY=256,
                             KEY_ENCODE_BYTES=16)

    async def main():
        r = Resolver(knobs)
        for req in _resolve_requests(3, 11):
            await r.resolve(req)
        m = await r.metrics()
        assert m["device_pipeline"] == 1
        assert m["device_enqueued"] == 3
        assert m["device_dispatches"] >= 1
        assert m["total_batches"] == 3
        await r.stop()
    asyncio.run(main())


# --------------------------------------------------------------------------
# PackedKeyIndex generation contract (what the device mirror keys on)


def test_key_index_gen_tracks_base_mutations_only():
    idx = PackedKeyIndex()
    g0 = idx.gen
    idx.add_many([b"k%03d" % i for i in range(10)])
    # inserts live in the pending overlay until a merge: the mirror
    # probes the overlay host-side, so gen must NOT move yet
    pend = len(idx.pending_run())
    if pend:                      # small adds stay pending
        assert idx.gen == g0
    idx._merge()
    assert idx.gen > g0
    g1 = idx.gen
    assert idx.base_run() == sorted(b"k%03d" % i for i in range(10))
    assert idx.pending_run() == []
    assert len(idx.base_prefixes()) == 10
    idx.discard_many([b"k003"])
    assert idx.gen > g1


# --------------------------------------------------------------------------
# device read serving


def _engine_with(n: int) -> MemoryKVStore:
    kv = MemoryKVStore(None, "t")
    kv._apply([(OP_SET, b"dk%05d" % i, b"v%05d" % i) for i in range(n)])
    return kv


def test_device_read_server_matches_engine_path():
    kv = _engine_with(500)
    kv.packed_index._merge()
    knobs = Knobs().override(STORAGE_DEVICE_READ_MIN_BATCH=4)
    srv = DeviceReadServer(kv, knobs)
    assert srv.active
    # the mirror cold-starts stale: the FIRST batch is served by the
    # engine path (None = caller falls through) and primes the upload
    assert srv.get_batch([b"dk00000"] * 8) is None
    # mix of present keys, missing keys, and keys beyond both ends
    keys = sorted({b"dk%05d" % (i * 37 % 700) for i in range(64)}
                  | {b"aaaa", b"zzzz"})
    got = srv.get_batch(keys)
    assert got is not None
    assert got == kv.get_batch(keys)
    m = srv.metrics()
    assert m["device_read_batches"] == 1
    assert m["device_read_keys"] == len(keys)
    assert m["device_read_fallbacks"] == 1
    assert m["device_read_uploads"] == 1


def test_device_read_server_probes_pending_overlay():
    """Keys inserted since the last merge live in the pending overlay;
    the mirror stays fresh (gen unmoved) and the overlay is probed
    host-side — results still identical to the engine."""
    kv = _engine_with(200)
    kv.packed_index._merge()
    knobs = Knobs().override(STORAGE_DEVICE_READ_MIN_BATCH=4)
    srv = DeviceReadServer(kv, knobs)
    srv.get_batch([b"dk%05d" % i for i in range(8)])    # builds the mirror
    gen = kv.packed_index.gen
    kv._apply([(OP_SET, b"zz-new%02d" % i, b"nv") for i in range(4)])
    if kv.packed_index.gen != gen:
        pytest.skip("small add unexpectedly merged — overlay not testable")
    keys = [b"zz-new00", b"zz-new03", b"dk00001", b"zz-none"]
    got = srv.get_batch(sorted(keys))
    assert got == kv.get_batch(sorted(keys))


def test_device_read_server_stale_mirror_falls_back_then_refreshes():
    kv = _engine_with(300)
    kv.packed_index._merge()
    knobs = Knobs().override(STORAGE_DEVICE_READ_MIN_BATCH=4)
    srv = DeviceReadServer(kv, knobs)
    keys = [b"dk%05d" % i for i in range(16)]
    assert srv.get_batch(keys) is None          # cold start primes mirror
    assert srv.get_batch(keys) is not None
    uploads = srv._dir.uploads
    # a merge bumps gen: the NEXT batch takes the engine path (correct
    # results either way) and triggers a re-upload for the one after
    kv._apply([(OP_SET, b"dk%05d" % (1000 + i), b"nv") for i in range(600)])
    kv.packed_index._merge()
    assert srv.get_batch(keys) is None          # stale: engine serves
    assert srv._dir.uploads == uploads + 1      # ...and refresh happened
    got = srv.get_batch(keys)                   # fresh again: device serves
    assert got == kv.get_batch(keys)
    assert srv.metrics()["device_read_fallbacks"] == 2  # cold start + stale


def test_device_read_server_threshold_and_knob_gates():
    kv = _engine_with(100)
    knobs = Knobs().override(STORAGE_DEVICE_READ_MIN_BATCH=32)
    srv = DeviceReadServer(kv, knobs)
    assert srv.active
    assert srv.get_batch([b"dk00001"] * 8) is None      # below threshold
    assert srv.metrics()["device_read_fallbacks"] == 1
    off = DeviceReadServer(kv, Knobs().override(
        STORAGE_DEVICE_READ_SERVE=False))
    assert not off.active
    assert off.get_batch([b"dk%05d" % i for i in range(64)]) is None


def test_storage_server_wires_device_reads():
    """The capability probe: an engine-backed storage server arms the
    device read path (jax+x64 are on under conftest) and surfaces its
    counters through metrics(); engineless servers stay inactive."""
    from foundationdb_tpu.core.data import KeyRange
    from foundationdb_tpu.core.storage_server import StorageServer
    from foundationdb_tpu.core.tlog import TLog

    async def main():
        knobs = Knobs()
        ss = StorageServer(knobs, 0, KeyRange(b"", b"\xff"), TLog(knobs),
                           engine=_engine_with(50))
        assert ss._device_reads is not None
        assert (await ss.metrics())["device_read_active"] == 1
        bare = StorageServer(knobs, 1, KeyRange(b"", b"\xff"), TLog(knobs))
        assert bare._device_reads is None
        assert "device_read_active" not in await bare.metrics()
    asyncio.run(main())


# --------------------------------------------------------------------------
# header-only (empty-clip) batches through the pipeline (ISSUE 18 sat. 3)
#
# With mesh routing the proxy sends header-only version advances to
# partitions every txn clipped empty against; the resolver's fast path
# answers most of them, but keepalives with routing off and state-barrier
# batches still cross the pipeline with ZERO txns.  The pump, the group
# encoder (zero chunks for a zero-txn batch), and the poison/drain paths
# must all treat them as first-class batches.


def test_pipeline_header_only_batches_drain_with_real_backends():
    import sys
    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0] + "/tools")
    import perf_smoke

    from foundationdb_tpu.ops.backends import make_conflict_backend

    knobs = Knobs().override(
        RESOLVER_BATCH_TXNS=8, RESOLVER_RANGES_PER_TXN=2,
        CONFLICT_RING_CAPACITY=256, KEY_ENCODE_BYTES=16,
        MAX_WRITE_TRANSACTION_LIFE_VERSIONS=300, RESOLVER_GROUP_MAX=4)
    batches, versions = perf_smoke._resolve_workload(12, 8, 2, 31)
    # every third batch becomes header-only (the empty-clip shape)
    batches = [([] if i % 3 == 1 else b) for i, b in enumerate(batches)]

    async def run(kind: str):
        be = make_conflict_backend(
            knobs.override(RESOLVER_CONFLICT_BACKEND=kind))
        pipe = DevicePipeline(be, knobs)
        futs = [pipe.submit(t, v) for t, v in zip(batches, versions)]
        rows = [await f for f in futs]
        await pipe.drain()
        await pipe.close()
        return rows

    twin = asyncio.run(run("numpy"))
    dev = asyncio.run(run("tpu"))
    assert twin == dev          # bit-identical with empties interleaved
    for i, row in enumerate(twin):
        assert len(row) == len(batches[i])  # empties yield empty rows


def test_pipeline_header_only_batch_as_barrier():
    async def main():
        be = FakeBackend()
        pipe = DevicePipeline(be, _knobs(RESOLVER_GROUP_MAX=8))
        f0 = pipe.submit(_txns(2), 100)
        f1 = pipe.submit(_txns(0), 110, barrier=True)   # empty state batch
        f2 = pipe.submit(_txns(1), 120)
        assert await f1 == []
        await f0, await f2
        await pipe.drain()
        await pipe.close()
        # the empty barrier still ends its group
        assert [vs for vs, _ in be.groups] == [[100, 110], [120]]
    asyncio.run(main())


def test_pipeline_poison_with_header_only_batches_queued():
    async def main():
        be = FakeBackend(fail_sync_on_dispatch=1)
        pipe = DevicePipeline(be, _knobs(RESOLVER_GROUP_MAX=2))
        futs = [pipe.submit(_txns(0), 100 + i) for i in range(4)]
        outcomes = []
        for f in futs:
            try:
                outcomes.append(await f)
            except ResolverFailed:
                outcomes.append("failed")
        # the failed dispatch's batches fail; anything already in flight
        # ahead still delivers its (empty) rows — no hangs, no crash
        assert outcomes[:2] == ["failed", "failed"]
        assert all(o in ("failed", []) for o in outcomes)
        assert pipe.poisoned is not None
        await pipe.drain()
        assert pipe._pump_task.done()
        assert pipe._pump_task.exception() is None
        with pytest.raises(ResolverFailed):
            await pipe.submit(_txns(0), 200)
        await pipe.close()
    asyncio.run(main())


def test_pipeline_close_discard_fails_queued_header_only():
    async def main():
        be = FakeBackend()
        pipe = DevicePipeline(be, _knobs())
        fut = pipe.submit(_txns(0), 100)
        await pipe.close(discard=True)
        with pytest.raises(ResolverFailed):
            await fut
    asyncio.run(main())


# --------------------------------------------------------------------------
# on-device verdict reduction (ISSUE 18 tentpole b)


def test_verdict_bitmask_parity_and_readback_cut():
    """The RESOLVER_VERDICT_BITMASK reduction: verdicts bit-identical to
    the raw-vector twin through the same pipeline, and the bytes the
    host actually synced shrink (4-byte summary per clean dispatch vs
    K*B i32)."""
    import sys
    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0] + "/tools")
    import perf_smoke

    from foundationdb_tpu.ops.backends import make_conflict_backend

    base = Knobs().override(
        RESOLVER_BATCH_TXNS=8, RESOLVER_RANGES_PER_TXN=2,
        CONFLICT_RING_CAPACITY=256, KEY_ENCODE_BYTES=16,
        CONFLICT_WINDOW_SLOTS=32,
        MAX_WRITE_TRANSACTION_LIFE_VERSIONS=300, RESOLVER_GROUP_MAX=4,
        RESOLVER_CONFLICT_BACKEND="tpu")
    batches, versions = perf_smoke._resolve_workload(24, 8, 2, 77)

    async def run(knobs):
        be = make_conflict_backend(knobs)
        pipe = DevicePipeline(be, knobs)
        futs = [pipe.submit(t, v) for t, v in zip(batches, versions)]
        rows = [await f for f in futs]
        await pipe.close()
        return [x for r in rows for x in r], be.readback_bytes

    raw, raw_bytes = asyncio.run(run(
        base.override(RESOLVER_VERDICT_BITMASK=False)))
    packed, packed_bytes = asyncio.run(run(
        base.override(RESOLVER_VERDICT_BITMASK=True)))
    assert raw == packed
    assert 0 < packed_bytes < raw_bytes


def test_verdict_bitmask_wire_words_roundtrip():
    """Resolver replies carry abort_words matching the verdict vector,
    and the proxy-side decode (conflict bit + too-old bit) reproduces the
    codes exactly."""
    from foundationdb_tpu.core.resolver import Resolver, pack_abort_words

    reqs = _resolve_requests(16, 77)
    knobs = Knobs().override(
        RESOLVER_BATCH_TXNS=6, RESOLVER_RANGES_PER_TXN=2,
        CONFLICT_RING_CAPACITY=256, KEY_ENCODE_BYTES=16,
        MAX_WRITE_TRANSACTION_LIFE_VERSIONS=300,
        RESOLVER_VERDICT_BITMASK=True)

    async def main():
        r = Resolver(knobs)
        saw_conflict = False
        for req in reqs:
            reply = await r.resolve(req)
            assert reply.abort_words is not None
            assert reply.abort_words == pack_abort_words(reply.verdicts)
            nw = (len(reply.verdicts) + 31) // 32
            for i, v in enumerate(reply.verdicts):
                w, b = divmod(i, 32)
                cbit = (reply.abort_words[w] >> b) & 1
                tbit = (reply.abort_words[nw + w] >> b) & 1
                assert v == cbit + tbit
                saw_conflict |= cbit == 1
        await r.stop()
        assert saw_conflict, "workload failed to exercise aborts"
    asyncio.run(main())


def test_verdict_bitmask_off_leaves_reply_none():
    from foundationdb_tpu.core.resolver import Resolver

    reqs = _resolve_requests(3, 5)
    knobs = Knobs().override(
        RESOLVER_BATCH_TXNS=6, RESOLVER_RANGES_PER_TXN=2,
        CONFLICT_RING_CAPACITY=256, KEY_ENCODE_BYTES=16,
        RESOLVER_VERDICT_BITMASK=False)

    async def main():
        r = Resolver(knobs)
        for req in reqs:
            assert (await r.resolve(req)).abort_words is None
        await r.stop()
    asyncio.run(main())


# --------------------------------------------------------------------------
# Pallas in-place ring write (ISSUE 18 tentpole c)


def test_ring_inplace_parity_through_pipeline():
    """RESOLVER_RING_INPLACE on (interpret-mode on CPU) vs off: verdicts
    bit-identical across a workload long enough to wrap the ring."""
    import sys
    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0] + "/tools")
    import perf_smoke

    from foundationdb_tpu.ops.backends import make_conflict_backend

    base = Knobs().override(
        RESOLVER_BATCH_TXNS=8, RESOLVER_RANGES_PER_TXN=2,
        CONFLICT_RING_CAPACITY=256, KEY_ENCODE_BYTES=16,
        CONFLICT_WINDOW_SLOTS=32,
        MAX_WRITE_TRANSACTION_LIFE_VERSIONS=300, RESOLVER_GROUP_MAX=4,
        RESOLVER_CONFLICT_BACKEND="tpu")
    batches, versions = perf_smoke._resolve_workload(24, 8, 2, 77)

    async def run(knobs):
        be = make_conflict_backend(knobs)
        pipe = DevicePipeline(be, knobs)
        futs = [pipe.submit(t, v) for t, v in zip(batches, versions)]
        rows = [await f for f in futs]
        await pipe.close()
        return [x for r in rows for x in r]

    off = asyncio.run(run(base.override(RESOLVER_RING_INPLACE=False)))
    on = asyncio.run(run(base.override(RESOLVER_RING_INPLACE=True)))
    assert off == on


# --------------------------------------------------------------------------
# group-size histogram (ISSUE 18 satellite 1)


def test_group_size_stats_histogram_surface():
    from foundationdb_tpu.device.pipeline import GroupSizeStats
    gs = GroupSizeStats()
    for n in (1, 4, 4, 2):
        gs.append(n)
    assert len(gs) == 4
    assert list(gs) == [1, 4, 4, 2]
    assert gs.max == 4
    assert gs.mean() == pytest.approx(11 / 4)
    # the trace histogram carries the same samples until its log flush
    assert gs.hist.count == 4 and gs.hist.total == pytest.approx(11)
    # a log-interval flush clears the Histogram but NOT the running
    # stats the gauges read
    gs.hist.clear()
    assert gs.mean() == pytest.approx(11 / 4) and gs.max == 4
    gs.clear()
    assert len(gs) == 0 and gs.mean() == 0.0 and list(gs) == []


# --------------------------------------------------------------------------
# sharded per-chip mirror (ISSUE 18 tentpole a)


def _sharded_knobs(shards: int, **over) -> Knobs:
    return Knobs().override(STORAGE_DEVICE_READ_MIN_BATCH=4,
                            STORAGE_DEVICE_READ_SHARDS=shards, **over)


def test_sharded_directory_matches_engine_and_twin():
    """The sharded mirror (4 shards over the forced 8 CPU devices)
    returns byte-identical batches to both the engine path and the
    single-directory twin."""
    import jax
    assert len(jax.devices()) >= 2   # conftest forces 8 host devices
    kv = _engine_with(800)
    kv.packed_index._merge()
    twin = DeviceReadServer(kv, _sharded_knobs(0))
    srv = DeviceReadServer(kv, _sharded_knobs(4))
    assert srv.active and srv._sharded and not twin._sharded
    keys = sorted({b"dk%05d" % (i * 37 % 1100) for i in range(96)}
                  | {b"aaaa", b"zzzz"})
    got = srv.get_batch(keys)       # sharded serves inline even from cold
    assert got is not None
    assert twin.get_batch(keys) is None     # twin cold start primes only
    assert got == kv.get_batch(keys) == twin.get_batch(keys)
    m = srv.metrics()
    assert m["device_read_shards"] == 4
    assert m["device_read_full_splits"] == 1
    assert m["device_read_shard_refreshes"] == 4
    assert m["device_read_gathers"] >= 2    # batch spanned > 1 shard


def test_sharded_directory_partial_refresh_serves_inline():
    """A localized merge re-ships only the touched shards, and the
    first post-merge batch is still served by the DEVICE (the
    single-directory twin falls back to the engine there)."""
    kv = _engine_with(600)
    kv.packed_index._merge()
    srv = DeviceReadServer(kv, _sharded_knobs(4))
    keys = [b"dk%05d" % i for i in range(32)]
    srv.get_batch(keys)                     # cold start: full split
    assert srv.get_batch(keys) is not None
    refr0 = srv._dir.shard_refreshes
    # a merge touching only the tail of the key space
    kv._apply([(OP_SET, b"dk%05d" % (5000 + i), b"nv") for i in range(300)])
    kv.packed_index._merge()
    got = srv.get_batch(keys)               # partial refresh + inline serve
    assert got is not None                  # no engine fallback
    assert got == kv.get_batch(keys)
    delta = srv._dir.shard_refreshes - refr0
    assert 1 <= delta < 4                   # only touched shards re-shipped
    assert srv.metrics()["device_read_full_splits"] == 1


def test_sharded_directory_lsm_blocks_mode(monkeypatch):
    """Sharded mirror over the lsm merged sparse directory: the routed
    per-shard searchsorted must locate the same global blocks."""
    import foundationdb_tpu.storage.lsm as lsm_mod
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.runtime.simloop import run_simulation
    from foundationdb_tpu.storage.lsm import LSMKVStore
    monkeypatch.setattr(lsm_mod, "_MEMTABLE_BYTES", 1500)
    monkeypatch.setattr(lsm_mod, "_BLOCK_BYTES", 200)
    monkeypatch.setattr(lsm_mod, "_MAX_RUNS", 8)

    async def main():
        import random
        fs = SimFileSystem()
        kv = await LSMKVStore.open(fs, "db/lsm")
        rng = random.Random(9)
        for round_ in range(8):
            ops = [(0, b"dk%04d" % rng.randrange(1200),
                    b"v%06d" % rng.randrange(10 ** 6)) for _ in range(60)]
            await kv.commit(ops, {"durable_version": round_})
        srv = DeviceReadServer(kv, _sharded_knobs(4))
        assert srv.active and srv._sharded
        probes = sorted({b"dk%04d" % rng.randrange(1400)
                         for _ in range(150)})
        got = srv.get_batch(probes)     # sharded serves inline from cold
        assert got is not None
        assert got == kv.get_batch(probes)

    run_simulation(main())


def test_device_read_staleness_gauge():
    """The staleness GAUGE: versions the mirror's refresh trails the
    engine tip — 0 while fresh, the version gap once stale, 0 again
    after the refresh."""
    kv = _engine_with(300)
    kv.packed_index._merge()
    tip = {"v": 100}
    knobs = Knobs().override(STORAGE_DEVICE_READ_MIN_BATCH=4)
    srv = DeviceReadServer(kv, knobs, version_fn=lambda: tip["v"])
    keys = [b"dk%05d" % i for i in range(16)]
    srv.get_batch(keys)                     # primes mirror at tip 100
    assert srv.get_batch(keys) is not None
    assert srv.staleness_versions() == 0    # fresh: gauge pinned to 0
    tip["v"] = 500
    assert srv.staleness_versions() == 0    # still fresh (overlay covers)
    kv._apply([(OP_SET, b"dk%05d" % (2000 + i), b"nv") for i in range(600)])
    kv.packed_index._merge()
    assert srv.staleness_versions() == 500 - 100    # stale: real gap
    assert srv.metrics()["device_read_staleness_versions"] == 400
    srv.get_batch(keys)                     # engine serves + refresh
    assert srv.staleness_versions() == 0
    assert srv.metrics()["device_read_staleness_versions"] == 0


def test_device_read_server_lsm_blocks_mode(monkeypatch):
    """The device gather under the lsm engine (ISSUE 11, ROADMAP item 1
    (e)): the mirror is the MERGED sparse directory, one searchsorted
    locates the candidate block in every run, and the host finish
    (``get_batch_located``) returns exactly what ``engine.get_batch``
    would — including tombstones resolved newest-wins and memtable-only
    keys probed host-side."""
    import foundationdb_tpu.storage.lsm as lsm_mod
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.runtime.simloop import run_simulation
    from foundationdb_tpu.storage.lsm import LSMKVStore
    monkeypatch.setattr(lsm_mod, "_MEMTABLE_BYTES", 1500)
    monkeypatch.setattr(lsm_mod, "_BLOCK_BYTES", 200)
    monkeypatch.setattr(lsm_mod, "_MAX_RUNS", 8)

    async def main():
        import random
        fs = SimFileSystem()
        kv = await LSMKVStore.open(fs, "db/lsm")
        rng = random.Random(9)
        for round_ in range(8):
            ops = [(0, b"dk%04d" % rng.randrange(1200),
                    b"v%06d" % rng.randrange(10 ** 6)) for _ in range(60)]
            ops.append((1, b"dk0100", b"dk0140"))
            await kv.commit(ops, {"durable_version": round_})
        assert len(kv._runs) >= 2
        assert kv.packed_index.device_mode == "blocks"
        knobs = Knobs().override(STORAGE_DEVICE_READ_MIN_BATCH=4)
        srv = DeviceReadServer(kv, knobs)
        assert srv.active
        probes = sorted({b"dk%04d" % rng.randrange(1400)
                         for _ in range(150)})
        assert srv.get_batch(probes) is None    # cold start primes mirror
        got = srv.get_batch(probes)
        assert got is not None
        assert got == kv.get_batch(probes)
        # a memtable-only key (no flush since) rides the host-side probe
        await kv.commit([(0, b"zz-memkey", b"mv")], {"durable_version": 99})
        qs = sorted(probes + [b"zz-memkey"])
        got2 = srv.get_batch(qs)
        if got2 is None:            # a flush bumped gen: refresh + retry
            got2 = srv.get_batch(qs)
        assert got2 == kv.get_batch(qs)
        # a flush (run-set change) stales the mirror exactly once
        big = [(0, b"fl%04d" % i, b"w" * 40) for i in range(50)]
        await kv.commit(big, {"durable_version": 100})
        g0 = kv.packed_index.gen
        assert srv.get_batch(probes) is None    # stale: engine serves
        assert kv.packed_index.gen == g0
        assert srv.get_batch(probes) == kv.get_batch(probes)

    run_simulation(main())
