"""fdbmonitor: supervise, restart crashed servers, clean teardown."""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

from foundationdb_tpu.core.cluster_file import ClusterFile
from foundationdb_tpu.rpc.transport import NetworkAddress

from test_server import free_ports

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_monitor_restarts_crashed_server(tmp_path):
    """The victim is a WORKER-ONLY server (4th process, not in the
    coordinator quorum): the monitor's contract under test is
    supervision — crash detection + respawn + the cluster still serving.
    Killing a coordinator host instead drags in real-time election
    failover, which is timing-sensitive on a loaded VM and covered
    deterministically by the sim suite (attrition/leader-kill tests)."""
    ports = free_ports(4)
    coord_ports, victim_port = ports[:3], ports[3]
    cf = ClusterFile("mon", "t1",
                     [NetworkAddress("127.0.0.1", p) for p in coord_ports])
    cf_path = tmp_path / "fdb.cluster"
    cf.save(str(cf_path))
    conf = tmp_path / "fdbmonitor.conf"
    conf.write_text(
        "[general]\n"
        f"cluster-file = {cf_path}\n"
        "restart-delay = 0.5\n"
        # replication=2: the kill must not be data loss (single-replica
        # memory-engine storage dies with its process; reads of a lost
        # shard retry endpoint_not_found forever — unavailability, not a
        # supervision failure)
        + "".join(f"[fdbserver.{p}]\nlisten = 127.0.0.1:{p}\n"
                  "spec = min_workers=4,storage_servers=4,replication=2\n"
                  for p in ports))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    mon = subprocess.Popen(
        [sys.executable, "-m", "foundationdb_tpu.monitor", "-C", str(conf)],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    try:
        # wait until the cluster serves (smoke through the CLI path)
        import asyncio

        from foundationdb_tpu.cli import open_cli
        from foundationdb_tpu.runtime.knobs import Knobs

        async def smoke():
            cli = await open_cli(str(cf_path), Knobs(), timeout=60)
            assert await cli.execute("set mk mv") == "Committed"

        asyncio.run(asyncio.wait_for(smoke(), 120))

        # find and SIGKILL the worker-only fdbserver; the monitor must
        # respawn it
        out = subprocess.run(
            ["pgrep", "-f", f"foundationdb_tpu.server.*{victim_port}"],
            capture_output=True, text=True)
        pids = [int(x) for x in out.stdout.split()]
        assert pids, "child server not found"
        os.kill(pids[0], signal.SIGKILL)
        deadline = time.time() + 30
        while time.time() < deadline:
            out = subprocess.run(
                ["pgrep", "-f", f"foundationdb_tpu.server.*{victim_port}"],
                capture_output=True, text=True)
            new = [int(x) for x in out.stdout.split()]
            if new and new[0] != pids[0]:
                break
            time.sleep(0.5)
        else:
            raise AssertionError("monitor never restarted the killed server")

        # cluster still serves after the restart (bounded: a wedge must
        # FAIL the test, not hang the suite)
        async def smoke2():
            cli = await open_cli(str(cf_path), Knobs(), timeout=60)
            out = await cli.execute("get mk")
            assert out == "`mk' is `mv'", out

        asyncio.run(asyncio.wait_for(smoke2(), 150))
    finally:
        mon.send_signal(signal.SIGTERM)
        try:
            mon.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            mon.kill()
            mon.communicate()
