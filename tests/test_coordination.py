"""Coordination: generation-register safety, quorum reads/writes, leader
election with lease failover — in-process and over the simulated network."""

import asyncio

import pytest

from foundationdb_tpu.core.coordination import (CoordinatedState, Coordinator,
                                                CoordinatorsUnreachable,
                                                NotLatestGeneration,
                                                elect_leader)
from foundationdb_tpu.rpc.sim_transport import SimNetwork, SimTransport
from foundationdb_tpu.rpc.stubs import CoordinatorClient, serve_role
from foundationdb_tpu.rpc.transport import NetworkAddress, WLTOKEN_FIRST_AVAILABLE
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


def test_register_rejects_stale_writer():
    async def main():
        k = Knobs()
        co = Coordinator(k)
        await co.read((5, 1))
        with pytest.raises(NotLatestGeneration):
            await co.write((4, 9), b"old")        # older than the promise
        await co.write((6, 1), b"new")
        with pytest.raises(NotLatestGeneration):
            await co.write((6, 1), b"again")      # not strictly newer
        _, wgen, val = await co.read((7, 2))
        assert wgen == (6, 1) and val == b"new"
    run_simulation(main())


def test_quorum_read_write_and_contention():
    """Two writers race through CoordinatedState; the loser observes the
    winner's value on re-read — no lost update, no split-brain value."""
    async def main():
        k = Knobs()
        coords = [Coordinator(k) for _ in range(3)]
        a = CoordinatedState(coords, my_id=1)
        b = CoordinatedState(coords, my_id=2)
        await a.read()
        await b.read()                 # b's read invalidates a's generation
        with pytest.raises(NotLatestGeneration):
            await a.write(b"from-a")
        await b.write(b"from-b")
        _, seen = await a.read()
        assert seen == b"from-b"
        new = await a.read_modify_write(lambda old: old + b"+a")
        assert new == b"from-b+a"
    run_simulation(main())


def test_quorum_survives_minority_coordinator_loss():
    async def main():
        k = Knobs()
        net = SimNetwork(k)
        addrs = [NetworkAddress("10.0.0.%d" % (i + 1), 4000) for i in range(3)]
        coords = [Coordinator(k) for _ in range(3)]
        for addr, co in zip(addrs, coords):
            t = SimTransport(net, addr)
            serve_role(t, "coordinator", co, WLTOKEN_FIRST_AVAILABLE)
        ct = SimTransport(net, NetworkAddress("10.0.1.1", 5000))
        stubs = [CoordinatorClient(ct, a, WLTOKEN_FIRST_AVAILABLE)
                 for a in addrs]
        cs = CoordinatedState(stubs, my_id=7)
        await cs.read_modify_write(lambda _: b"state1")
        net.kill(addrs[0])             # minority down: still works
        new = await cs.read_modify_write(lambda old: old + b"+2")
        assert new == b"state1+2"
        net.kill(addrs[1])             # majority down: unavailable
        with pytest.raises(CoordinatorsUnreachable):
            await cs.read()
    run_simulation(main(), seed=4)


def test_durable_register_survives_reboot():
    from foundationdb_tpu.runtime.files import SimFileSystem

    async def main():
        k = Knobs()
        fs = SimFileSystem()
        co = await Coordinator.open(k, fs, "coord-0")
        await co.read((3, 1))
        await co.write((4, 1), b"persisted")
        # reboot: reopen from the same file system
        co2 = await Coordinator.open(k, fs, "coord-0")
        assert co2.write_gen == (4, 1) and co2.value == b"persisted"
        with pytest.raises(NotLatestGeneration):
            await co2.write((2, 9), b"stale")   # promises survived too
    run_simulation(main())


def test_leader_election_single_winner_and_failover():
    async def main():
        k = Knobs().override(LEADER_LEASE_DURATION=2.0)
        coords = [Coordinator(k) for _ in range(3)]
        l1 = await elect_leader(coords, 11, "addr-11", k)
        l2 = await elect_leader(coords, 22, "addr-22", k)
        assert l1 == l2 == (11, "addr-11")    # first viable candidate wins

        # leader keeps the lease alive
        for _ in range(3):
            await asyncio.sleep(0.5)
            assert all([await c.leader_heartbeat(11) for c in coords])

        # leader dies: lease lapses, a new candidate takes over
        await asyncio.sleep(k.LEADER_LEASE_DURATION + 0.1)
        l3 = await elect_leader(coords, 22, "addr-22", k)
        assert l3 == (22, "addr-22")
        assert not await coords[0].leader_heartbeat(11)   # deposed
    run_simulation(main())


def test_restarted_coordinator_cannot_split_grant():
    """A coordinator that reboots with an empty register must not hand
    leadership to the first bystander who asks while the quorum still
    honors the incumbent's lease — the split-grant scenario the
    two-phase nominate/confirm protocol exists to prevent."""
    async def main():
        k = Knobs().override(LEADER_LEASE_DURATION=5.0)
        coords = [Coordinator(k) for _ in range(3)]
        won = await elect_leader(coords, 11, "addr-11", k)
        assert won == (11, "addr-11")
        coords[0] = Coordinator(k)          # reboot: empty register
        # a bystander elects: the fresh coordinator nominates it but must
        # not grant; the majority's confirmed leader wins the tally
        seen = await elect_leader(coords, 22, "addr-22", k)
        assert seen == (11, "addr-11")
        # and the fresh coordinator never confirmed the bystander
        assert coords[0]._leader is None
    run_simulation(main())


def test_nomination_storm_does_not_disturb_incumbent():
    """Ten rivals repeatedly electing against a healthy heartbeating
    leader all follow it; the incumbent is never deposed (the r3 gap:
    leadership ping-pong under churn)."""
    async def main():
        k = Knobs().override(LEADER_LEASE_DURATION=2.0)
        coords = [Coordinator(k) for _ in range(5)]
        won = await elect_leader(coords, 7, "addr-7", k)
        assert won == (7, "addr-7")

        deposed = False

        async def heartbeat():
            nonlocal deposed
            for _ in range(20):
                await asyncio.sleep(k.LEADER_HEARTBEAT_INTERVAL)
                good = sum([await c.leader_heartbeat(7) for c in coords])
                if good < 3:
                    deposed = True

        async def rival(cid):
            results = []
            for _ in range(5):
                results.append(await elect_leader(
                    coords, cid, f"addr-{cid}", k))
            return results

        hb = asyncio.get_running_loop().create_task(heartbeat())
        storms = await asyncio.gather(*(rival(100 + i) for i in range(10)))
        hb.cancel()
        assert not deposed
        for results in storms:
            assert all(r == (7, "addr-7") for r in results)
    run_simulation(main())


def test_dead_nominee_lapses():
    """A candidate that nominates and dies must not wedge the election:
    its (lowest-id, thus convergent-best) nomination expires after
    NOMINATION_TIMEOUT and the live candidate wins."""
    async def main():
        k = Knobs()
        coords = [Coordinator(k) for _ in range(3)]
        for c in coords:
            await c.nominate(1, "addr-dead")     # then never confirms
        won = await elect_leader(coords, 50, "addr-50", k)
        assert won == (50, "addr-50")
    run_simulation(main())


def test_election_churn_converges_10_of_10():
    """Under load — randomly delayed coordinator RPCs, some past the
    per-call timeout — concurrent candidates must converge on exactly
    one winner, every seed (the VERDICT r3 #8 churn scenario)."""
    from foundationdb_tpu.runtime.rng import DeterministicRandom

    class Flaky:
        """Per-call seeded random delay in front of a real coordinator."""

        def __init__(self, co, rng, max_delay):
            self._co, self._rng, self._d = co, rng, max_delay

        def __getattr__(self, name):
            m = getattr(self._co, name)

            async def call(*a):
                await asyncio.sleep(self._rng.random() * self._d)
                return await m(*a)
            return call

    def one_round(seed):
        async def main():
            # long lease: this test is about split grants during the
            # race, not lease-expiry failover
            k = Knobs().override(LEADER_LEASE_DURATION=10.0)
            rng = DeterministicRandom(seed)
            coords = [Coordinator(k) for _ in range(5)]
            # delays up to 0.8s vs a 0.5s rpc timeout: a good fraction
            # of calls time out, like an event loop starved by load
            flaky = [Flaky(c, rng, 0.8) for c in coords]
            winners = await asyncio.gather(
                *(elect_leader(flaky, 1 + i, f"a{1 + i}", k)
                  for i in range(4)),
                return_exceptions=True)
            ok = [w for w in winners if not isinstance(w, BaseException)]
            assert len(ok) >= 1
            assert len(set(ok)) == 1, f"seed {seed}: split winners {ok}"
            # the winner holds a MAJORITY of leases; a loser may have won
            # a minority confirm before losing the race (harmless —
            # leadership is a majority property), but never a majority
            tally = {}
            for c in coords:
                if c._leader is not None:
                    tally[c._leader.leader_id] = \
                        tally.get(c._leader.leader_id, 0) + 1
            assert tally.get(ok[0][0], 0) >= 3
            assert all(v < 3 for lid, v in tally.items() if lid != ok[0][0])
        run_simulation(main(), seed=seed)

    for seed in range(10):
        one_round(seed)


def test_partitioned_best_nominee_does_not_park_election():
    """Adversarial liveness (VERDICT r4 weak #7): the convergent lowest-id
    nominee can NOMINATE at every coordinator but its CONFIRM path to a
    majority is partitioned.  Without self-abdication it refreshes its
    nominations forever and no rival can ever become best nominee.  The
    candidate must stand down after repeated failed confirms so a rival
    wins within ELECTION_TIMEOUT — every seed."""
    from foundationdb_tpu.runtime.rng import DeterministicRandom

    class ConfirmPartitioned:
        """Nominate/read pass through; confirm (and withdraw) hang past
        the RPC timeout — an asymmetric partition on the grant path."""

        def __init__(self, co, rng):
            self._co, self._rng = co, rng

        def __getattr__(self, name):
            m = getattr(self._co, name)
            if name in ("confirm", "withdraw"):
                async def blackhole(*a):
                    await asyncio.sleep(60.0)      # > any rpc timeout
                    raise asyncio.TimeoutError()
                return blackhole

            async def call(*a):
                await asyncio.sleep(self._rng.random() * 0.01)
                return await m(*a)
            return call

    def one_round(seed):
        async def main():
            k = Knobs().override(LEADER_LEASE_DURATION=10.0)
            rng = DeterministicRandom(seed)
            coords = [Coordinator(k) for _ in range(3)]
            # candidate 1 (lowest id -> always the convergent nominee)
            # sees a confirm-partitioned view of ALL coordinators;
            # candidate 2 sees the healthy view
            part = [ConfirmPartitioned(c, rng) for c in coords]
            loop = asyncio.get_running_loop()
            t0 = loop.time()
            rival_done = []

            async def rival():
                w = await elect_leader(coords, 2, "a2", k)
                rival_done.append(loop.time() - t0)
                return w

            winners = await asyncio.gather(
                elect_leader(part, 1, "a1", k), rival(),
                return_exceptions=True)
            # the healthy rival must win within the election budget —
            # timed at ITS completion (the partitioned candidate may
            # legitimately run to its own deadline afterwards)
            assert winners[1] == (2, "a2"), f"seed {seed}: {winners}"
            assert rival_done and rival_done[0] < k.ELECTION_TIMEOUT, \
                f"seed {seed}: {rival_done}"
            # and the rival holds a true majority of leases
            tally = sum(1 for c in coords
                        if c._leader is not None and c._leader.leader_id == 2)
            assert tally >= 2, f"seed {seed}: leases {tally}"
            # the partitioned candidate either followed the rival (via its
            # stand-down read-only poll) or timed out — it must never
            # believe IT won
            assert winners[0] == (2, "a2") \
                or isinstance(winners[0], CoordinatorsUnreachable), \
                f"seed {seed}: {winners[0]}"
        run_simulation(main(), seed=seed)

    for seed in range(10):
        one_round(seed)


def test_election_deterministic():
    async def main():
        k = Knobs()
        coords = [Coordinator(k) for _ in range(5)]
        winners = await asyncio.gather(
            elect_leader(coords, 1, "a1", k),
            elect_leader(coords, 2, "a2", k),
            elect_leader(coords, 3, "a3", k))
        assert len(set(winners)) == 1
        return winners[0]

    assert run_simulation(main(), seed=8) == run_simulation(main(), seed=8)
