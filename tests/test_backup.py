"""Backup/restore: consistent snapshot cut + full restore (SURVEY §5.4(b))."""

from __future__ import annotations

import asyncio

import pytest

from foundationdb_tpu.backup import BackupAgent, RestoreError
from foundationdb_tpu.client.database import Database
from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
from foundationdb_tpu.runtime.files import SimFileSystem
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


def test_backup_restore_roundtrip():
    async def main():
        k = Knobs()
        fs = SimFileSystem()
        async with Cluster(ClusterConfig(), k) as cluster:
            db = Database(cluster)
            items = {b"bk%04d" % i: b"val%04d" % i for i in range(350)}

            async def fill(tr):
                for key, v in items.items():
                    tr.set(key, v)
            await db.run(fill)
            agent = BackupAgent(db, fs, "backups/b1", rows_per_file=100)
            manifest = await agent.backup()
            assert manifest.rows == 350 and len(manifest.range_files) == 4

            # concurrent-ish writes AFTER the snapshot must not be in it
            await db.set(b"bk9999", b"late")

        # restore into a FRESH cluster (the disaster-recovery path)
        async with Cluster(ClusterConfig(), k) as cluster2:
            db2 = Database(cluster2)
            await db2.set(b"junk", b"pre-restore")
            agent2 = BackupAgent(db2, fs, "backups/b1")
            await agent2.restore()
            rows = await db2.get_range(b"", b"\xff", limit=0)
            assert dict(rows) == items          # exact cut: no junk, no late row
    run_simulation(main())


def test_backup_is_consistent_cut_under_writes():
    """Writers race the backup; every key the backup contains must be from
    a single version cut (pairs written atomically are both-or-neither)."""
    async def main():
        k = Knobs()
        fs = SimFileSystem()
        async with Cluster(ClusterConfig(), k) as cluster:
            db = Database(cluster)

            async def seed(tr):
                for i in range(50):
                    tr.set(b"pa%03d" % i, b"0")
                    tr.set(b"pb%03d" % i, b"0")
            await db.run(seed)

            stop = asyncio.Event()

            async def writer():
                g = 1
                while not stop.is_set():
                    gen = b"%d" % g

                    async def bump(tr, gen=gen):
                        # the invariant: pa[i] and pb[i] always equal
                        for i in range(50):
                            tr.set(b"pa%03d" % i, gen)
                            tr.set(b"pb%03d" % i, gen)
                    await db.run(bump)
                    g += 1
                    await asyncio.sleep(0.01)

            w = asyncio.ensure_future(writer())
            agent = BackupAgent(db, fs, "backups/cut", rows_per_file=30)
            await agent.backup()
            stop.set()
            await w

        async with Cluster(ClusterConfig(), k) as c2:
            db2 = Database(c2)
            await BackupAgent(db2, fs, "backups/cut").restore()
            rows = dict(await db2.get_range(b"", b"\xff", limit=0))
            for i in range(50):
                assert rows[b"pa%03d" % i] == rows[b"pb%03d" % i], \
                    f"torn pair at {i}: backup is not a consistent cut"
    run_simulation(main())


def test_restore_requires_manifest():
    async def main():
        fs = SimFileSystem()
        async with Cluster(ClusterConfig(), Knobs()) as cluster:
            agent = BackupAgent(Database(cluster), fs, "backups/none")
            with pytest.raises(RestoreError):
                await agent.restore()
    run_simulation(main())
