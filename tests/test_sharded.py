"""Multi-resolver shard_map path on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from foundationdb_tpu.ops.batch import TxnRequest, encode_batch
from foundationdb_tpu.ops.conflict_np import NumpyConflictSet
from foundationdb_tpu.parallel.sharded import (have_shard_map,
                                               init_sharded_state,
                                               make_sharded_resolve_step)
from foundationdb_tpu.runtime import DeterministicRandom

# capability probe, not a hard import: a jax build without shard_map (in
# either its jax.shard_map or jax.experimental spelling) must SKIP these
# — tier-1 should go red only on real regressions, not env drift
pytestmark = pytest.mark.skipif(
    not have_shard_map(),
    reason="this jax build exposes no shard_map (jax.shard_map or "
           "jax.experimental.shard_map)")

W = 16
B, R = 8, 4


def mesh8():
    devs = np.array(jax.devices()[:8])
    return Mesh(devs, ("resolvers",))


def rand_txn(rng, version, keyspace):
    def rr():
        k = rng.choice(keyspace)
        return (k, k + b"\x01")
    return TxnRequest([rr() for _ in range(rng.random_int(0, R))],
                      [rr() for _ in range(rng.random_int(0, R))],
                      rng.random_int(max(0, version - 40), version + 1))


def test_sharded_matches_single_for_partition_contained_txns():
    """Every range of a txn inside ONE partition -> sharded == single.

    (A txn whose ranges span partitions can see phantom conflicts, like the
    reference's multi-resolver mode — covered by the next test.)
    """
    mesh = mesh8()
    step = make_sharded_resolve_step(mesh, W)
    state = init_sharded_state(mesh, capacity_per_shard=4096, width=W)
    twin = NumpyConflictSet(4096, W)

    rng = DeterministicRandom(9)
    # per-partition key pools; each txn draws all ranges from one pool
    pools = [[bytes([32 * p + off]) * 3 for off in range(4)] for p in range(8)]
    version = 100
    for _ in range(25):
        nt = rng.random_int(1, B + 1)
        txns = [rand_txn(rng, version, rng.choice(pools)) for _ in range(nt)]
        version += rng.random_int(1, 15)
        eb = encode_batch(txns, B, R, W)
        state, sv = step(state, eb.read_begin, eb.read_end, eb.write_begin,
                         eb.write_end, eb.read_snapshot, np.int64(version))
        tv = twin.resolve_encoded(eb, version)
        np.testing.assert_array_equal(np.asarray(sv), tv)


def test_sharded_cross_partition_conservative():
    """Txns spanning partitions: committed verdicts must still be safe —
    any divergence from the single-resolver twin is COMMITTED->CONFLICT."""
    mesh = mesh8()
    step = make_sharded_resolve_step(mesh, W)
    # append-slab rings consume B*R slots per batch regardless of commit
    # count, so capacity must cover the whole trace (15 batches) or the
    # floor rises and adds TOO_OLD divergence on top of the phantom kind
    state = init_sharded_state(mesh, capacity_per_shard=B * R * 64, width=W)
    twin = NumpyConflictSet(4096, W)

    rng = DeterministicRandom(10)
    version = 100
    diverged = False
    for _ in range(15):
        nt = rng.random_int(1, B + 1)
        txns = []
        for _ in range(nt):
            def wide():
                a = bytes([rng.random_int(0, 256), rng.random_int(0, 256)])
                b = bytes([rng.random_int(0, 256), rng.random_int(0, 256)])
                lo, hi = min(a, b), max(a, b)
                return (lo, hi + b"\x01")  # often spans several partitions
            txns.append(TxnRequest([wide() for _ in range(rng.random_int(0, R))],
                                   [wide() for _ in range(rng.random_int(0, R))],
                                   rng.random_int(max(0, version - 40), version + 1)))
        version += rng.random_int(1, 15)
        eb = encode_batch(txns, B, R, W)
        state, sv = step(state, eb.read_begin, eb.read_end, eb.write_begin,
                         eb.write_end, eb.read_snapshot, np.int64(version))
        tv = twin.resolve_encoded(eb, version)
        sv = np.asarray(sv)
        for i in range(nt):
            if sv[i] != tv[i]:
                assert (sv[i], tv[i]) == (1, 0), (i, sv[i], tv[i])
                diverged = True
        if diverged:
            break  # histories no longer comparable after a divergence


def test_sharded_state_is_actually_sharded():
    mesh = mesh8()
    state = init_sharded_state(mesh, capacity_per_shard=64, width=W)
    shardings = {d.device for d in state.hb.addressable_shards}
    assert len(shardings) == 8
