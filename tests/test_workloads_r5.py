"""The r5 workload batch (VERDICT r4 item 6): WriteDuringRead,
FuzzApiCorrectness, SelectorCorrectness, Storefront,
SpecialKeySpaceCorrectness, LowLatency, BackupToDBCorrectness (fast,
in-process cluster) + Rollback, RandomMoveKeys, TagThrottle (simulated
multi-machine cluster)."""

import asyncio

from foundationdb_tpu.workloads import run_workloads, run_workloads_on
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


def test_write_during_read_and_fuzz():
    res = run_workloads([
        {"testName": "WriteDuringRead", "rounds": 6, "opsPerRound": 20},
        {"testName": "FuzzApiCorrectness", "calls": 80},
        {"testName": "ConsistencyCheck"},
    ], seed=5, client_count=2)
    assert res["WriteDuringRead"]["ryw_checks"] > 0
    assert res["FuzzApiCorrectness"]["fuzz_typed_errors"] > 0
    assert res["FuzzApiCorrectness"]["fuzz_calls_ok"] > 0


def test_selector_storefront_specialkeys():
    res = run_workloads([
        {"testName": "SelectorCorrectness", "keys": 16, "probes": 40},
        {"testName": "Storefront", "orders": 15},
        {"testName": "SpecialKeySpaceCorrectness", "rounds": 3},
        {"testName": "ConsistencyCheck"},
    ], seed=6, client_count=2)
    assert res["SelectorCorrectness"]["selector_checks"] > 0
    assert res["Storefront"]["orders_placed"] > 0
    assert res["SpecialKeySpaceCorrectness"]["skx_rounds"] > 0


def test_lowlatency():
    res = run_workloads([
        {"testName": "LowLatency", "seconds": 3.0, "maxLatency": 10.0},
    ], seed=7, client_count=1)
    assert res["LowLatency"]["latency_probes"] > 0


def test_backup_to_db_switchover_sim():
    """DR switchover mid-traffic: the destination (now primary) serves a
    byte-identical copy.  Needs a coordinator-backed db (the DR tag
    stream follows recoveries)."""
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        sim = SimulatedCluster(
            n_machines=5, spec=ClusterConfigSpec(min_workers=5))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        res = await run_workloads_on(db, [
            {"testName": "BackupToDBCorrectness"},
        ], client_count=1)
        await sim.stop()
        return res

    run_simulation(main(), seed=24)


def test_rollback_workload_sim():
    """Acked writes survive a TLog-machine kill mid-stream."""
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        sim = SimulatedCluster(
            n_machines=6, durable_storage=True,
            spec=ClusterConfigSpec(min_workers=6, replication=2))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        res = await run_workloads_on(db, [
            {"testName": "Rollback", "sim": sim, "writes": 30,
             "killAt": 12},
            {"testName": "Cycle", "nodeCount": 8,
             "transactionsPerClient": 15},
        ], client_count=2)
        await sim.stop()
        return res

    res = run_simulation(main(), seed=21)
    assert res["Rollback"]["rollback_kills"] >= 1
    assert res["Rollback"]["acked_writes"] > 0


def test_random_move_keys_sim():
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        sim = SimulatedCluster(
            Knobs().override(DD_ENABLED=True),
            n_machines=6, spec=ClusterConfigSpec(min_workers=6,
                                                 replication=2))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        res = await run_workloads_on(db, [
            {"testName": "RandomMoveKeys", "sim": sim, "moves": 2,
             "secondsBetweenMoves": 1.5},
            {"testName": "Cycle", "nodeCount": 8,
             "transactionsPerClient": 20},
        ], client_count=2)
        await sim.stop()
        return res

    res = run_simulation(main(), seed=22)
    assert res["RandomMoveKeys"]["moves_requested"] >= 1


def test_tag_throttle_sim():
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        sim = SimulatedCluster(
            n_machines=5, spec=ClusterConfigSpec(min_workers=5))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        res = await run_workloads_on(db, [
            {"testName": "TagThrottle", "sim": sim, "seconds": 4.0,
             "tagRate": 3.0},
        ], client_count=1)
        await sim.stop()
        return res

    res = run_simulation(main(), seed=23)
    assert res["TagThrottle"]["untagged_txns"] \
        > res["TagThrottle"]["tagged_txns"]
