"""The online consistency scrubber (ISSUE 17).

Two claims, each load-bearing on its own:

1. **Key-exact catch**: a row corrupted on exactly ONE replica — a
   flipped value AND a phantom row the other replica never saw — is
   caught within one scrub pass and named exactly (key hex, pinned
   version, both replica addresses) in a severity-40 ``ScrubMismatch``.
2. **Zero false positives under chaos**: with machine kills, swizzle
   reboots, clogging, hostile disks and BUGGIFY all firing while the
   scrubber runs continuously, an honest cluster must produce ZERO
   ``ScrubMismatch`` and ZERO ``ScrubInvariantViolation`` events — the
   GV_* refusal discipline (re-pin and re-route, never report) is the
   entire credibility of the severity-40 alarm.
"""

from __future__ import annotations

import asyncio

import pytest

from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
from foundationdb_tpu.runtime.buggify import enable_buggify
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.runtime.trace import (Severity, TraceLog,
                                            get_trace_log, set_trace_log)
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

# the hot scrub cadence every test here runs: passes in well under a
# virtual second so catches land within a few sim seconds
SCRUB_KNOBS = dict(SCRUB_ENABLED=True,
                   SCRUB_PASS_INTERVAL=0.5,
                   SCRUB_WATCHDOG_INTERVAL=0.5,
                   SCRUB_PAGES_PER_SEC=500.0,
                   SCRUB_PAGE_ROWS=8,
                   SCRUB_MAX_PAGES_PER_REQUEST=4)

WAIT_S = 180.0  # virtual-clock ceiling per wait phase


@pytest.fixture(autouse=True)
def _buggify_off_after():
    yield
    enable_buggify(False)


@pytest.fixture()
def captured_trace():
    events: list[dict] = []
    sink = TraceLog(min_severity=Severity.INFO)
    sink.sink = events.append
    prev = get_trace_log()
    set_trace_log(sink)
    yield events
    set_trace_log(prev)


async def _wait_for(pred, what: str, ceiling_s: float = WAIT_S):
    for _ in range(int(ceiling_s / 0.25)):
        if pred():
            return
        await asyncio.sleep(0.25)
    raise AssertionError(f"{what} did not happen within "
                         f"{ceiling_s:.0f} virtual seconds")


def test_injected_corruption_caught_key_exact(captured_trace):
    """Both divergence flavors on one replica of a double-replicated
    team — a flipped value and a phantom row — each caught within one
    pass, each named key-exactly with both replica addresses; and the
    pass BEFORE the injection is clean (the false-positive guard)."""
    events = captured_trace
    flipped = {"key": b""}
    phantom = {"key": b""}

    async def main() -> None:
        knobs = Knobs().override(DD_ENABLED=True,
                                 STORAGE_DURABILITY_LAG=0.1,
                                 **SCRUB_KNOBS)
        sim = SimulatedCluster(knobs, n_machines=5,
                               spec=ClusterConfigSpec(min_workers=5,
                                                      replication=2))
        await sim.start()
        await asyncio.wait_for(sim.wait_epoch(1), 120)
        db = await sim.database()
        keys = [b"row%04d" % i for i in range(40)]
        for k in keys:
            async def body(tr, k=k):
                tr.set(k, b"honest-" + k)
            await db.run(body)

        await _wait_for(lambda: sim.leader_scrubber() is not None,
                        "scrubber recruitment")
        scr = sim.leader_scrubber()
        await _wait_for(lambda: scr.passes_complete >= 1,
                        "the first full pass")
        assert scr.mismatch_rows == 0, \
            "mismatch on an honest cluster — false positive"

        # one replica, two flavors of rot: a value flip on a written
        # row, and a row the rest of the team never saw
        victim = None
        for ss in sim.storage_objects():
            for k in keys:
                ghost = k + b"\x00zz"
                if (ss.shard.begin <= k < ss.shard.end
                        and ss.shard.begin <= ghost < ss.shard.end):
                    victim, flipped["key"], phantom["key"] = ss, k, ghost
                    break
            if victim is not None:
                break
        assert victim is not None
        victim.corrupt_for_test(flipped["key"], b"BITROT")
        victim.corrupt_for_test(phantom["key"], b"GHOST")
        await _wait_for(lambda: scr.mismatch_rows >= 2,
                        "detection of both injected rows")
        assert scr.invariant_violations == 0
        await sim.stop()

    run_simulation(main(), seed=1701)

    hits = {e["Key"]: e for e in events
            if e.get("Type") == "ScrubMismatch"}
    assert set(hits) == {flipped["key"].hex(), phantom["key"].hex()}, (
        f"caught {sorted(hits)}, expected exactly the two injected "
        f"keys — triage is not key-exact")
    for ev in hits.values():
        assert ev.get("Severity") == 40 and ev.get("Version", 0) > 0, ev
        assert len(str(ev.get("Replicas", "")).split(",")) == 2, (
            f"mismatch named {ev.get('Replicas')!r}, not both replicas")
    # the phantom flavor must show the honest replica holding nothing
    assert "<missing>" in str(hits[phantom["key"].hex()].get("Values")), \
        hits[phantom["key"].hex()]


def test_scrub_zero_false_positives_under_chaos(captured_trace):
    """The scrubber runs CONTINUOUSLY while the standard chaos mix
    fires — attrition (kills the leader's machine too, re-recruiting
    the scrubber), swizzle reboots, clogging, hostile disks, BUGGIFY —
    against invariant workloads on a durable double-replicated
    cluster.  An honest cluster under any amount of failure must
    produce zero mismatches and zero invariant violations, and a full
    pass must still complete AFTER the chaos settles."""
    from foundationdb_tpu.workloads.workload import run_workloads_on

    events = captured_trace
    enable_buggify(True)

    async def main() -> dict:
        knobs = Knobs().override(DD_ENABLED=True,
                                 BUGGIFY_ENABLED=True,
                                 STORAGE_DURABILITY_LAG=0.1,
                                 **SCRUB_KNOBS)
        sim = SimulatedCluster(knobs, n_machines=7, durable_storage=True,
                               spec=ClusterConfigSpec(min_workers=7,
                                                      replication=2))
        await sim.start()
        await asyncio.wait_for(sim.wait_epoch(1), 120)
        db = await sim.database()
        await _wait_for(lambda: sim.leader_scrubber() is not None,
                        "scrubber recruitment")
        specs = [
            {"testName": "Cycle", "nodeCount": 12,
             "transactionsPerClient": 10},
            {"testName": "Serializability", "numOps": 20},
            {"testName": "MachineAttrition", "sim": sim,
             "machinesToKill": 1},
            {"testName": "Swizzle", "sim": sim, "rounds": 1,
             "secondsBefore": 5.0},
            {"testName": "RandomClogging", "sim": sim,
             "testDuration": 6.0},
            {"testName": "DiskFault", "sim": sim, "testDuration": 8.0},
            {"testName": "ConsistencyCheck"},
        ]
        results = await run_workloads_on(db, specs, client_count=2)

        # the post-chaos proof: a FRESH full pass (the leader may have
        # been killed and the scrubber re-recruited with zero counters)
        await _wait_for(lambda: sim.leader_scrubber() is not None,
                        "post-chaos scrubber recruitment")
        scr = sim.leader_scrubber()
        settled = scr.passes_complete
        await _wait_for(lambda: scr.passes_complete > settled,
                        "a full post-chaos pass")
        assert scr.pages_scrubbed > 0
        await sim.stop()
        return results

    results = run_simulation(main(), seed=4242)
    assert results["Cycle"]["transactions"] == 20
    assert results["MachineAttrition"]["machines_killed"] >= 1

    false_pos = [e for e in events if e.get("Type") == "ScrubMismatch"]
    assert not false_pos, (
        f"FALSE POSITIVE under chaos: {false_pos[:3]} — a refusal "
        f"(GV_*) leaked through as a mismatch verdict")
    violations = [e for e in events
                  if e.get("Type") == "ScrubInvariantViolation"]
    assert not violations, (
        f"frontier watchdog fired on a healthy-but-chaotic cluster: "
        f"{violations[:3]} — an invariant is unsound under recovery")
