"""NumPy conflict-set twin vs. the brute-force oracle.

The randomized-workload-vs-oracle scheme mirrors the reference's
ConflictRange simulation workload (REF:fdbserver/workloads/ConflictRange.actor.cpp).
"""

import numpy as np
import pytest

from foundationdb_tpu.ops.batch import (COMMITTED, CONFLICT, TOO_OLD,
                                        TxnRequest, encode_batch)
from foundationdb_tpu.ops.conflict_np import NumpyConflictSet
from foundationdb_tpu.ops.oracle import OracleConflictSet
from foundationdb_tpu.runtime import DeterministicRandom

W = 16
B, R = 8, 4


def rand_key(rng, maxlen, alphabet=3):
    n = rng.random_int(1, maxlen + 1)
    return bytes(rng.random_int(0, alphabet) for _ in range(n))


def rand_range(rng, maxlen):
    a, b = rand_key(rng, maxlen), rand_key(rng, maxlen)
    if a == b:
        b = a + b"\x00"
    return (min(a, b), max(a, b))


def rand_txn(rng, snap_lo, snap_hi, maxlen):
    return TxnRequest(
        read_ranges=[rand_range(rng, maxlen) for _ in range(rng.random_int(0, R + 1))],
        write_ranges=[rand_range(rng, maxlen) for _ in range(rng.random_int(0, R + 1))],
        read_snapshot=rng.random_int(snap_lo, snap_hi),
    )


def run_trace(seed, maxlen, n_batches=30, capacity=256):
    """Drive twin and oracle through identical batches; return verdict traces."""
    rng = DeterministicRandom(seed)
    twin = NumpyConflictSet(capacity, W)
    oracle = OracleConflictSet()
    version = 100
    twin_trace, oracle_trace = [], []
    for _ in range(n_batches):
        nt = rng.random_int(1, B + 1)
        txns = [rand_txn(rng, max(0, version - 50), version + 1, maxlen) for _ in range(nt)]
        version += rng.random_int(1, 20)
        eb = encode_batch(txns, B, R, W)
        tv = twin.resolve_encoded(eb, version)[:nt].tolist()
        ov = oracle.resolve_batch(txns, version)
        twin_trace.append(tv)
        oracle_trace.append(ov)
        if rng.coinflip(0.2):
            oldest = version - rng.random_int(10, 60)
            twin.set_oldest_version(oldest)
            oracle.set_oldest_version(oldest)
    return twin_trace, oracle_trace


@pytest.mark.parametrize("seed", range(8))
def test_exact_parity_short_keys(seed):
    """Keys <= W bytes: twin must match the oracle verdict-for-verdict."""
    tt, ot = run_trace(seed, maxlen=W)
    assert tt == ot


@pytest.mark.parametrize("seed", range(4))
def test_safety_long_keys(seed):
    """Arbitrary-length keys: the committed schedule must be serializable.

    The twin may falsely abort (conservative truncation) but a committed
    txn must never have read anything a newer committed write touched —
    checked with exact byte-string math against the twin's own committed
    history.
    """
    rng = DeterministicRandom(seed + 1000)
    twin = NumpyConflictSet(512, W)
    shadow = []  # exact committed writes: (begin, end, version)
    version = 100
    for _ in range(30):
        nt = rng.random_int(1, B + 1)
        txns = [rand_txn(rng, max(0, version - 50), version + 1, maxlen=W * 3)
                for _ in range(nt)]
        version += rng.random_int(1, 20)
        eb = encode_batch(txns, B, R, W)
        v = twin.resolve_encoded(eb, version)
        batch_committed = []
        for i in range(nt):
            if v[i] != COMMITTED:
                continue
            t = txns[i]
            for (rb, re) in t.read_ranges:
                for (wb, we, wv) in shadow:
                    assert not (wv > t.read_snapshot and rb < we and wb < re), \
                        "committed txn read overlaps newer committed write"
                for (wb, we) in batch_committed:
                    assert not (rb < we and wb < re), \
                        "committed txn read overlaps earlier-in-batch committed write"
            batch_committed.extend(t.write_ranges)
        shadow.extend((b, e, version) for (b, e) in batch_committed)


def test_too_old_at_floor_boundary():
    twin = NumpyConflictSet(64, W, oldest_version=100)
    mk = lambda snap, k: TxnRequest([(k, k + b"\x00")], [(k, k + b"\x00")], snap)
    txns = [mk(99, b"a"), mk(100, b"b"), mk(101, b"c")]  # disjoint keys
    v = twin.resolve_encoded(encode_batch(txns, B, R, W), 200)
    assert v[0] == TOO_OLD           # snapshot < oldest
    assert v[1] == COMMITTED         # snapshot == oldest is fine
    assert v[2] == COMMITTED
    oracle = OracleConflictSet(oldest_version=100)
    assert oracle.resolve_batch(txns, 200) == [TOO_OLD, COMMITTED, COMMITTED]


def test_ring_overflow_forces_too_old():
    """Overwriting live history raises the floor -> old snapshots abort."""
    twin = NumpyConflictSet(capacity=B * R, width=W)
    version = 10
    # fill the ring with committed writes at increasing versions, then wrap
    for _ in range(6):
        txns = [TxnRequest([], [(bytes([i, j]), bytes([i, j, 0]))], version - 1)
                for i in range(4) for j in range(2)]
        eb = encode_batch(txns, B, R, W)
        twin.resolve_encoded(eb, version)
        version += 10
    assert twin.oldest_version > 0  # floor was raised by overwrites
    old_snap = twin.oldest_version - 1
    eb = encode_batch([TxnRequest([(b"zzz", b"zzzz")], [], old_snap)], B, R, W)
    assert twin.resolve_encoded(eb, version)[0] == TOO_OLD


def test_intra_batch_order_matters():
    """Earlier txn in batch wins; later reader of its write conflicts."""
    twin = NumpyConflictSet(64, W)
    t1 = TxnRequest([], [(b"k", b"k\x00")], 10)       # writes k
    t2 = TxnRequest([(b"k", b"k\x00")], [], 10)       # reads k
    v = twin.resolve_encoded(encode_batch([t1, t2], B, R, W), 20)
    assert v[0] == COMMITTED and v[1] == CONFLICT
    # reversed order: reader goes first, both commit
    twin2 = NumpyConflictSet(64, W)
    v2 = twin2.resolve_encoded(encode_batch([t2, t1], B, R, W), 20)
    assert v2[0] == COMMITTED and v2[1] == COMMITTED


def test_aborted_txn_writes_not_recorded():
    twin = NumpyConflictSet(64, W)
    oracle = OracleConflictSet()
    # batch 1: writer commits at v20
    w = TxnRequest([], [(b"a", b"a\x00")], 10)
    twin.resolve_encoded(encode_batch([w], B, R, W), 20)
    oracle.resolve_batch([w], 20)
    # batch 2: txn reads a at snapshot 10 -> conflict; its write to b aborted
    t = TxnRequest([(b"a", b"a\x00")], [(b"b", b"b\x00")], 10)
    assert twin.resolve_encoded(encode_batch([t], B, R, W), 30)[0] == CONFLICT
    assert oracle.resolve_batch([t], 30) == [CONFLICT]
    # batch 3: reader of b at snapshot 25 must COMMIT (b was never written)
    t3 = TxnRequest([(b"b", b"b\x00")], [], 25)
    assert twin.resolve_encoded(encode_batch([t3], B, R, W), 40)[0] == COMMITTED
    assert oracle.resolve_batch([t3], 40) == [COMMITTED]
