"""RPC layer tests: wire codec, sim network, networked cluster, TCP."""

import asyncio

import numpy as np
import pytest

from foundationdb_tpu.client import Database
from foundationdb_tpu.core.cluster import ClusterConfig
from foundationdb_tpu.core.cluster_rpc import NetworkedCluster
from foundationdb_tpu.core.data import (CommitTransactionRequest, KeyRange,
                                        Mutation, MutationType)
from foundationdb_tpu.rpc.sim_transport import SimNetwork, SimTransport
from foundationdb_tpu.rpc.transport import Endpoint, NetworkAddress
from foundationdb_tpu.rpc.wire import decode, encode
from foundationdb_tpu.runtime.errors import ConnectionFailed, NotCommitted
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


# --- wire codec ---

@pytest.mark.parametrize("obj", [
    None, True, False, 0, 1, -1, 1 << 62, -(1 << 62), 3.14, b"", b"bytes",
    "stré", [1, [2, 3]], (1, 2), {"a": 1, b"k": [None, True]},
    Mutation.set(b"k", b"v"),
    Mutation(MutationType.ADD, b"k", b"\x01"),
    KeyRange(b"a", b"b"),
    CommitTransactionRequest([(b"a", b"b")], [(b"c", b"d")],
                             [Mutation.set(b"k", b"v")], 42),
])
def test_wire_roundtrip(obj):
    assert decode(encode(obj)) == obj


def test_wire_ndarray():
    a = np.arange(24, dtype=np.uint32).reshape(2, 3, 4)
    b = decode(encode(a))
    assert b.dtype == a.dtype and (a == b).all()


def test_wire_rejects_unknown():
    class X:
        pass
    with pytest.raises(TypeError):
        encode(X())


# --- sim transport ---

def test_sim_request_reply_and_faults():
    async def main():
        net = SimNetwork(Knobs())
        a = SimTransport(net, NetworkAddress("10.0.0.1", 1))
        b = SimTransport(net, NetworkAddress("10.0.0.2", 1))

        async def double(x):
            return x * 2
        tok = b.dispatcher.register(double)
        ep = Endpoint(b.address, tok)

        assert await a.request(ep, 21) == 42

        # clog: delivery delayed but succeeds
        t0 = asyncio.get_running_loop().time()
        net.clog_pair(a.address, b.address, 0.5)
        assert await a.request(ep, 5) == 10
        assert asyncio.get_running_loop().time() - t0 >= 0.5

        # partition: request fails
        net.partition(a.address, b.address)
        with pytest.raises(ConnectionFailed):
            await a.request(ep, 1)
        net.heal(a.address, b.address)
        assert await a.request(ep, 1) == 2

        # kill: fails until reboot
        net.kill(b.address)
        with pytest.raises(ConnectionFailed):
            await a.request(ep, 1)
        net.reboot(b.address)
        assert await a.request(ep, 3) == 6
    run_simulation(main(), seed=1)


# --- full pipeline over the simulated network ---

def netsim(coro_fn, seed=0, config=None, knobs=None):
    async def main():
        async with NetworkedCluster(config or ClusterConfig(),
                                    knobs or Knobs()) as cluster:
            return await coro_fn(Database(cluster))
    return run_simulation(main(), seed=seed)


def multi():
    return ClusterConfig(commit_proxies=2, grv_proxies=2, resolvers=2,
                         logs=2, storage_servers=4)


@pytest.mark.parametrize("config", [None, multi()], ids=["single", "multi"])
def test_networked_set_get(config):
    async def body(db):
        await db.set(b"hello", b"world")
        assert await db.get(b"hello") == b"world"
        rows = await db.get_range(b"", b"\xff")
        assert rows == [(b"hello", b"world")]
    netsim(body, config=config)


def test_networked_conflict():
    async def body(db):
        await db.set(b"x", b"0")
        tr1 = db.create_transaction()
        tr2 = db.create_transaction()
        await tr1.get(b"x")
        await tr2.get(b"x")
        tr1.set(b"x", b"1")
        tr2.set(b"x", b"2")
        await tr1.commit()
        with pytest.raises(NotCommitted):
            await tr2.commit()
    netsim(body, config=multi())


def test_networked_cycle_workload():
    from foundationdb_tpu.workloads import run_workloads_on

    async def main():
        async with NetworkedCluster(multi(), Knobs()) as cluster:
            db = Database(cluster)
            return await run_workloads_on(
                db, [{"testName": "Cycle", "nodeCount": 10,
                      "transactionsPerClient": 10}], client_count=2)
    res = run_simulation(main(), seed=4)
    assert res["Cycle"]["transactions"] == 20


def test_networked_determinism():
    async def body(db):
        import asyncio as aio
        async def incr(tr):
            v = await tr.get(b"c")
            n = int.from_bytes(v, "big") if v else 0
            tr.set(b"c", (n + 1).to_bytes(4, "big"))
        # serial txns with concurrent pairs
        for _ in range(3):
            await aio.gather(db.run(incr), db.run(incr))
        return await db.get_range(b"", b"\xff")
    assert netsim(body, seed=17, config=multi()) == \
        netsim(body, seed=17, config=multi())


# --- real TCP transport (real event loop, localhost) ---

def test_tcp_transport_localhost():
    from foundationdb_tpu.rpc.tcp_transport import TcpTransport

    async def main():
        a = TcpTransport(NetworkAddress("127.0.0.1", 14601))
        b = TcpTransport(NetworkAddress("127.0.0.1", 14602))
        await a.listen()
        await b.listen()

        async def handler(x):
            return {"echo": x, "by": "b"}
        tok = b.dispatcher.register(handler)
        ep = Endpoint(b.address, tok)
        out = await a.request(ep, [1, b"two", None])
        assert out == {"echo": [1, b"two", None], "by": "b"}

        # errors propagate with their code
        from foundationdb_tpu.runtime.errors import NotCommitted as NC

        async def failing(x):
            raise NC()
        tok2 = b.dispatcher.register(failing)
        with pytest.raises(NC):
            await a.request(Endpoint(b.address, tok2), 0)

        # one-way delivery
        got = asyncio.get_running_loop().create_future()

        async def notify(x):
            if not got.done():
                got.set_result(x)
        tok3 = b.dispatcher.register(notify)
        a.one_way(Endpoint(b.address, tok3), b"ping")
        assert await asyncio.wait_for(got, 5) == b"ping"

        await a.close()
        await b.close()
    asyncio.run(main())
