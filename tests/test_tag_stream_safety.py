"""TagStream ack-safety: never emit a version a recovery can roll back.

Reference model: REF:fdbserver/TLogServer.actor.cpp peeks bound consumers
by minKnownCommittedVersion — a pushed-but-unacked version must not reach
an external consumer (DR destination, backup file), because recovery may
discard it (its client saw commit_unknown_result).  TagStream implements
the same discipline with a GRV+epoch confirm round; these tests script
the view/confirm surfaces to force the exact races.
"""

from __future__ import annotations

import asyncio

from foundationdb_tpu.backup.stream import TagStream
from foundationdb_tpu.core.tlog import TLogPeekReply


class ScriptedCursor:
    def __init__(self, replies):
        self.replies = list(replies)     # list of (entries, end) or callables
        self.version = 0                 # rewind target (observed)

    async def next(self):
        if not self.replies:
            await asyncio.sleep(3600)    # nothing more scripted
        item = self.replies.pop(0)
        entries, end = item() if callable(item) else item
        # honor rewinds like a real LogCursor: serve only >= self.version
        entries = [(v, m) for v, m in entries if v >= self.version]
        return TLogPeekReply(entries, end)


class ScriptedStream(TagStream):
    """TagStream with _view/_confirm replaced by scripts."""

    def __init__(self, begin, views, confirms):
        super().__init__(db=None, tag=99, begin=begin)
        self._views = list(views)        # (epoch, gen_begin, cursor)
        self._confirms = list(confirms)  # (grv, epoch)
        self.confirm_calls = 0

    async def _view(self):
        epoch, gen_begin, cursor = self._views.pop(0)
        self.view_epoch = epoch
        self.current_gen_begin = gen_begin
        cursor.version = self.frontier + 1
        self._cursor = cursor
        self._ls = None

    async def _confirm(self):
        self.confirm_calls += 1
        return self._confirms.pop(0)


def test_unconfirmed_tail_held_until_grv_passes():
    """Entries above the confirmed read version are withheld, then
    emitted once a (same-epoch) GRV covers them."""
    async def main():
        cur = ScriptedCursor([([(10, ["a"]), (12, ["b"])], 13),
                              ([(12, ["b"])], 13)])
        s = ScriptedStream(begin=10,
                           views=[(5, 0, cur)],
                           confirms=[(10, 5), (12, 5)])
        entries, end = await s.next()
        assert entries == [(10, ["a"])] and end == 11, (entries, end)
        entries, end = await s.next()
        assert entries == [(12, ["b"])] and end == 13
        assert s.confirm_calls == 2
    asyncio.run(asyncio.wait_for(main(), 10))


def test_phantom_version_discarded_on_epoch_roll():
    """A pulled-but-unacked version rolled back by a recovery is never
    emitted: the epoch check discards it and the re-pulled view (whose
    sealed generation excludes it) supplies the truth."""
    async def main():
        cur_old = ScriptedCursor([([(10, ["a"]), (12, ["phantom"])], 13)])
        # after recovery at version 11: 10 retained, 12 rolled back, a
        # NEW commit landed at 12 (version reuse across the recovery)
        cur_new = ScriptedCursor([([(10, ["a"]), (12, ["new"])], 15)])
        s = ScriptedStream(
            begin=10,
            views=[(5, 0, cur_old), (6, 11, cur_new)],
            confirms=[(12, 6),      # epoch moved: discard the old reply
                      (14, 6)])     # confirms the new generation's tail
        got = []
        while len(got) < 2:
            entries, _ = await s.next()
            got.extend(entries)
        assert got == [(10, ["a"]), (12, ["new"])], got
        assert all(m != ["phantom"] for _, m in got)
    asyncio.run(asyncio.wait_for(main(), 10))


def test_frontier_never_advances_past_unconfirmed_tip():
    """An empty reply whose end_version is an unacked peek tip must not
    advance the emitted frontier past the confirmed cap — a consumer
    persisting end-1 as 'applied through' would otherwise skip real
    commits landing numerically below the rolled-back tip."""
    async def main():
        cur = ScriptedCursor([([], 50),            # empty, tip way ahead
                              ([], 50),
                              ([(21, ["x"])], 50)])
        s = ScriptedStream(begin=10,
                           views=[(5, 0, cur)],
                           confirms=[(20, 5), (20, 5), (21, 5), (21, 5)])
        entries, end = await s.next()
        assert end - 1 <= 20, end
        assert s.frontier <= 20
        entries, end = await s.next()
        assert entries == [(21, ["x"])] and end - 1 <= 21
    asyncio.run(asyncio.wait_for(main(), 10))


def test_rewind_replays_span():
    """rewind() steps the frontier back so a consumer that failed to
    persist a span pulls it again."""
    async def main():
        cur = ScriptedCursor([([(10, ["a"]), (11, ["b"])], 12),
                              ([(10, ["a"]), (11, ["b"])], 12)])
        s = ScriptedStream(begin=10, views=[(5, 0, cur)],
                           confirms=[(11, 5), (11, 5)])
        e1, _ = await s.next()
        assert e1 == [(10, ["a"]), (11, ["b"])]
        s.rewind(9)
        e2, _ = await s.next()
        assert e2 == e1
    asyncio.run(asyncio.wait_for(main(), 10))
