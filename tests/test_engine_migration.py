"""Live storage-engine migration: `configure storage_engine=btree`.

Reference: REF:fdbclient/ManagementAPI.actor.cpp (changing the store
type) + REF:fdbserver/DataDistribution.actor.cpp — after a configure,
DD gradually replaces every storage server whose engine differs from
the configured type: each shard live-moves (dual-tag → fetch → flip)
onto freshly-recruited servers of the new type, with zero lost rows
and no recovery.
"""

from __future__ import annotations

import asyncio

from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
from foundationdb_tpu.core.management import configure
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster


def test_live_engine_migration_memory_to_btree():
    async def main():
        k = Knobs().override(DD_ENABLED=True, DD_INTERVAL=1.0,
                             STORAGE_ENGINE="memory")
        sim = SimulatedCluster(k, n_machines=6, durable_storage=True,
                               spec=ClusterConfigSpec(min_workers=6))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        assert all(s.get("engine") == "memory" for s in state1["storage"])
        db = await sim.database()

        written: dict[bytes, bytes] = {}
        stop = asyncio.Event()

        async def writer(wid: int) -> None:
            i = 0
            while not stop.is_set():
                items = {b"mig%02d%05d" % (wid, i + j): b"v" * 20
                         for j in range(4)}
                i += 4

                async def do(tr, items=items):
                    for key, v in items.items():
                        tr.set(key, v)
                await db.run(do)
                written.update(items)
                await asyncio.sleep(0.05)

        writers = [asyncio.ensure_future(writer(w)) for w in range(2)]
        await asyncio.sleep(0.5)        # some rows predate the configure
        await configure(db, storage_engine="btree")

        # every shard relocates onto btree-engine servers, live
        state2 = await sim.wait_state(
            lambda s: s["storage"]
            and all(e.get("engine") == "btree" for e in s["storage"]))
        await asyncio.sleep(1.0)        # let writes land post-migration
        stop.set()
        await asyncio.gather(*writers)

        assert state2["epoch"] == state1["epoch"], \
            "engine migration must not trigger a recovery"
        # old-team tags are fully retired from the state
        old_tags = {s["tag"] for s in state1["storage"]}
        assert not old_tags & {s["tag"] for s in state2["storage"]}

        tr = db.create_transaction()
        while True:
            try:
                rows = await tr.get_range(b"mig", b"mih", limit=0)
                break
            except Exception as e:  # noqa: BLE001 — follow the moves
                await tr.on_error(e)
        got = dict(rows)
        missing = [key for key in written if key not in got]
        assert not missing, f"{len(missing)} rows lost, e.g. {missing[:3]}"
        phantom = [key for key in got if key not in written]
        assert not phantom, f"{len(phantom)} phantoms"
        assert all(got[key] == v for key, v in written.items())

        # the destination replicas really run the B-tree engine: btree
        # head files exist on the machines hosting post-migration tags
        head_files = sum(
            1 for m in sim.machines
            for p in m.fs.listdir("data")
            if ".head" in p)
        assert head_files > 0, "no btree commit headers on any machine"

        # migrated data survives a recovery on the new engine (the
        # durable-resume path through BTreeKVStore)
        sim.leader_cc().request_recovery("engine-migration-test")
        state3 = await sim.wait_state(
            lambda s: s["epoch"] > state2["epoch"])
        assert all(e.get("engine") == "btree" for e in state3["storage"])
        db2 = await sim.database()
        tr = db2.create_transaction()
        while True:
            try:
                sample = await tr.get(sorted(written)[0])
                break
            except Exception as e:  # noqa: BLE001
                await tr.on_error(e)
        assert sample == written[sorted(written)[0]]
        await sim.stop()
    run_simulation(main())
