"""Test configuration.

Tests run on an 8-device *CPU* mesh so multi-resolver sharding
(shard_map over a jax Mesh) is exercised without TPU hardware, per the
deterministic-simulation philosophy: everything must be testable on one
CPU box (REF:fdbrpc/sim2.actor.cpp's raison d'être).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")  # conflict versions are int64
