"""Test configuration.

Tests run on an 8-device *CPU* mesh so multi-resolver sharding
(shard_map over a jax Mesh) is exercised without TPU hardware, per the
deterministic-simulation philosophy: everything must be testable on one
CPU box (REF:fdbrpc/sim2.actor.cpp's raison d'être).

Note: a pytest plugin imports jax before this conftest runs, so env vars
(JAX_ENABLE_X64 / JAX_PLATFORMS) are read too late — we must go through
jax.config.update, and set XLA_FLAGS before the first backend init.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")   # never touch the real TPU from tests
jax.config.update("jax_enable_x64", True)   # conflict versions are int64

# Per-test hang watchdog: a wedged test dumps every thread's stack and
# kills the run instead of stalling CI silently (pytest-timeout is not in
# this image; faulthandler is stdlib).  The dump goes to a REAL file:
# under pytest capture, sys.stderr is a temp buffer that os._exit throws
# away — a dump written there vanishes and the kill looks like a silent
# exit(1) with no summary.
import faulthandler

import pytest

_TEST_TIMEOUT_S = 600.0
_WATCHDOG_PATH = os.environ.get("FDBTPU_WATCHDOG_FILE",
                                "/tmp/fdbtpu_watchdog.txt")
_WATCHDOG_FILE = open(_WATCHDOG_PATH, "a")


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    _WATCHDOG_FILE.write(f"=== arming for {item.nodeid}\n")
    _WATCHDOG_FILE.flush()
    faulthandler.dump_traceback_later(_TEST_TIMEOUT_S, exit=True,
                                      file=_WATCHDOG_FILE)
    try:
        yield
    finally:
        faulthandler.cancel_dump_traceback_later()
