"""VersionedMap direct tests — the MVCC window structure under its r5
incremental compaction (touched-queue) rewrite.

The invariant under guard: every chain entry at or below a compaction
target has a queued (version, key) record, so the incremental
forget_before/drop_before reach exactly the same state as a full-map
walk would — checked here against a brute-force model over random
interleavings of set / clear_range / forget_before / drop_before /
rollback_after."""

import pytest

from foundationdb_tpu.runtime.rng import DeterministicRandom
from foundationdb_tpu.storage.versioned_map import VersionedMap


class ModelMap:
    """Brute force: full history, compacted by whole-map walks."""

    def __init__(self):
        self.chains: dict[bytes, list[tuple[int, bytes | None]]] = {}
        self.oldest = 0
        self.latest = 0

    def set(self, version, key, value):
        self.latest = version
        c = self.chains.setdefault(key, [])
        if c and c[-1][0] == version:
            c[-1] = (version, value)
        else:
            c.append((version, value))

    def clear_range(self, version, begin, end):
        self.latest = version
        for key in list(self.chains):
            if begin <= key < end and self.chains[key][-1][1] is not None:
                self.set(version, key, None)

    def get2(self, key, version):
        c = self.chains.get(key)
        if not c:
            return False, None
        best = None
        for v, val in c:
            if v <= version:
                best = (True, val)
        return best if best else (False, None)

    def forget_before(self, version):
        if version <= self.oldest:
            return
        self.oldest = version
        for key in list(self.chains):
            c = self.chains[key]
            i = len(c) - 1
            while i > 0 and c[i][0] > version:
                i -= 1
            del c[:i]
            if len(c) == 1 and c[0][1] is None and c[0][0] <= version:
                del self.chains[key]

    def drop_before(self, version):
        if version <= self.oldest:
            return
        self.oldest = version
        for key in list(self.chains):
            c = [e for e in self.chains[key] if e[0] > version]
            if c:
                self.chains[key] = c
            else:
                del self.chains[key]

    def rollback_after(self, version):
        if version >= self.latest:
            return
        self.latest = version
        for key in list(self.chains):
            c = [e for e in self.chains[key] if e[0] <= version]
            if c:
                self.chains[key] = c
            else:
                del self.chains[key]


def _assert_equal(vm: VersionedMap, model: ModelMap, version: int, keys):
    for key in keys:
        assert vm.get2(key, version) == model.get2(key, version), \
            (key, version)
    assert sorted(model.chains) == vm._index
    for key, chain in model.chains.items():
        assert vm._chains[key] == chain, key


@pytest.mark.parametrize("seed,consumer", [(0, "forget"), (1, "forget"),
                                           (2, "drop"), (3, "drop"),
                                           (4, "mixed_rollback"),
                                           (5, "mixed_rollback")])
def test_versioned_map_matches_brute_force(seed, consumer):
    rng = DeterministicRandom(seed)
    vm, model = VersionedMap(), ModelMap()
    keys = [b"k%02d" % i for i in range(12)]
    version = 0
    for step in range(300):
        version += rng.random_int(1, 5)
        op = rng.random_int(0, 10)
        if op < 6:
            k = keys[rng.random_int(0, len(keys))]
            val = b"v%d" % step
            vm.set(version, k, val)
            model.set(version, k, val)
        elif op < 8:
            lo = rng.random_int(0, len(keys))
            hi = rng.random_int(lo, len(keys) + 1)
            vm.clear_range(version, keys[lo] if lo < len(keys) else b"z",
                           keys[hi] if hi < len(keys) else b"z")
            model.clear_range(version, keys[lo] if lo < len(keys) else b"z",
                              keys[hi] if hi < len(keys) else b"z")
        elif op == 8:
            target = version - rng.random_int(0, 12)
            if consumer == "forget":
                vm.forget_before(target)
                model.forget_before(target)
            elif consumer == "drop":
                vm.drop_before(target)
                model.drop_before(target)
            else:
                back = version - rng.random_int(0, 6)
                vm.rollback_after(back)
                model.rollback_after(back)
                version = max(version - 6, model.latest, vm.latest_version)
                vm.forget_before(back - 8)
                model.forget_before(back - 8)
        else:
            # reads at several historical versions
            probe = version - rng.random_int(0, 15)
            if probe >= vm.oldest_version:
                for k in keys:
                    assert vm.get2(k, probe) == model.get2(k, probe)
        _assert_equal(vm, model, version, keys)
    # final full compaction drains the touched queue and converges
    if consumer == "drop":
        vm.drop_before(version)
        model.drop_before(version)
    else:
        vm.forget_before(version)
        model.forget_before(version)
    _assert_equal(vm, model, version + 1, keys)
    assert not vm._touched, f"queue not drained: {len(vm._touched)}"


def test_rollback_purges_stale_queue_records():
    """A rollback must not leave higher-version queue records parking
    the incremental compaction (r5 review finding)."""
    vm = VersionedMap()
    vm.set(10, b"a", b"1")
    vm.set(120, b"a", b"2")      # unacked suffix
    vm.set(120, b"b", b"x")
    vm.rollback_after(100)       # recovery cut
    assert all(v <= 100 for v, _k in vm._touched)
    # new generation writes at lower-than-rolled-back versions
    vm.set(106, b"b", b"y")
    vm.set(107, b"a", b"3")
    vm.forget_before(106)
    # the v=10 entry for "a" must be gone (folded into the base)
    assert vm._chains[b"a"] == [(10, b"1"), (107, b"3")] or \
        vm._chains[b"a"] == [(107, b"3")]
    vm.forget_before(110)
    assert vm._chains[b"a"] == [(107, b"3")]
    assert vm._chains[b"b"] == [(106, b"y")]
