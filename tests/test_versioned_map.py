"""VersionedMap direct tests — the MVCC window under BOTH
implementations (ISSUE 13): the legacy dict-of-chains with its r5
incremental compaction (touched-queue), and the columnar generational
window (tip + sealed segments) that replaces it by default.

The invariant under guard: every compaction path (incremental
forget_before/drop_before, lazy segment folds) reaches the same
OBSERVABLE state as a brute-force full-map walk — checked against a
model over random interleavings of set / clear_range / forget_before /
drop_before / rollback_after.  Legacy-mode runs additionally pin the
exact internal chain/queue state (the structures ARE its contract);
columnar internals are covered by tests/test_mvcc_window.py."""

import pytest

from foundationdb_tpu.runtime.rng import DeterministicRandom
from foundationdb_tpu.storage.versioned_map import VersionedMap


class ModelMap:
    """Brute force: full history, compacted by whole-map walks."""

    def __init__(self):
        self.chains: dict[bytes, list[tuple[int, bytes | None]]] = {}
        self.oldest = 0
        self.latest = 0

    def set(self, version, key, value):
        self.latest = version
        c = self.chains.setdefault(key, [])
        if c and c[-1][0] == version:
            c[-1] = (version, value)
        else:
            c.append((version, value))

    def clear_range(self, version, begin, end):
        self.latest = version
        for key in list(self.chains):
            if begin <= key < end and self.chains[key][-1][1] is not None:
                self.set(version, key, None)

    def get2(self, key, version):
        c = self.chains.get(key)
        if not c:
            return False, None
        best = None
        for v, val in c:
            if v <= version:
                best = (True, val)
        return best if best else (False, None)

    def forget_before(self, version):
        if version <= self.oldest:
            return
        self.oldest = version
        for key in list(self.chains):
            c = self.chains[key]
            i = len(c) - 1
            while i > 0 and c[i][0] > version:
                i -= 1
            del c[:i]
            if len(c) == 1 and c[0][1] is None and c[0][0] <= version:
                del self.chains[key]

    def drop_before(self, version):
        if version <= self.oldest:
            return
        self.oldest = version
        for key in list(self.chains):
            c = [e for e in self.chains[key] if e[0] > version]
            if c:
                self.chains[key] = c
            else:
                del self.chains[key]

    def rollback_after(self, version):
        if version >= self.latest:
            return
        self.latest = version
        for key in list(self.chains):
            c = [e for e in self.chains[key] if e[0] <= version]
            if c:
                self.chains[key] = c
            else:
                del self.chains[key]


def _assert_equal(vm, model: ModelMap, version: int, keys):
    for key in keys:
        assert vm.get2(key, version) == model.get2(key, version), \
            (key, version)
    assert sorted(model.chains) == vm.keys()
    if not vm.columnar:
        # the chain layout IS the legacy contract; the columnar window
        # retains invisible entries by design, so only observables match
        for key, chain in model.chains.items():
            assert vm._chains[key] == chain, key


def _small_columnar():
    """Columnar map with a tiny seal budget so a 300-step workload
    exercises seals, folds and segment probes, not just the tip."""
    return VersionedMap(columnar=True, seal_ops=7, seal_bytes=1 << 30,
                        seal_versions=1 << 40)


@pytest.mark.parametrize("columnar", [False, True])
@pytest.mark.parametrize("seed,consumer", [(0, "forget"), (1, "forget"),
                                           (2, "drop"), (3, "drop"),
                                           (4, "mixed_rollback"),
                                           (5, "mixed_rollback")])
def test_versioned_map_matches_brute_force(seed, consumer, columnar):
    rng = DeterministicRandom(seed)
    vm = _small_columnar() if columnar else VersionedMap(columnar=False)
    model = ModelMap()
    keys = [b"k%02d" % i for i in range(12)]
    version = 0
    for step in range(300):
        version += rng.random_int(1, 5)
        op = rng.random_int(0, 10)
        if op < 6:
            k = keys[rng.random_int(0, len(keys))]
            val = b"v%d" % step
            vm.set(version, k, val)
            model.set(version, k, val)
        elif op < 8:
            lo = rng.random_int(0, len(keys))
            hi = rng.random_int(lo, len(keys) + 1)
            vm.clear_range(version, keys[lo] if lo < len(keys) else b"z",
                           keys[hi] if hi < len(keys) else b"z")
            model.clear_range(version, keys[lo] if lo < len(keys) else b"z",
                              keys[hi] if hi < len(keys) else b"z")
        elif op == 8:
            target = version - rng.random_int(0, 12)
            if consumer == "forget":
                vm.forget_before(target)
                model.forget_before(target)
            elif consumer == "drop":
                vm.drop_before(target)
                model.drop_before(target)
            else:
                back = version - rng.random_int(0, 6)
                vm.rollback_after(back)
                model.rollback_after(back)
                version = max(version - 6, model.latest, vm.latest_version)
                vm.forget_before(back - 8)
                model.forget_before(back - 8)
        else:
            # reads at several historical versions
            probe = version - rng.random_int(0, 15)
            if probe >= vm.oldest_version:
                for k in keys:
                    assert vm.get2(k, probe) == model.get2(k, probe)
        _assert_equal(vm, model, version, keys)
    # final full compaction drains the touched queue and converges
    if consumer == "drop":
        vm.drop_before(version)
        model.drop_before(version)
    else:
        vm.forget_before(version)
        model.forget_before(version)
    _assert_equal(vm, model, version + 1, keys)
    if not vm.columnar:
        assert not vm._touched, f"queue not drained: {len(vm._touched)}"


# --- apply_batch: batched apply must be state-identical to the loop ---

@pytest.mark.parametrize("columnar", [False, True])
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_apply_batch_matches_sequential(seed, columnar):
    """Property: apply_batch over any chunking of a version-ordered op
    stream reaches EXACTLY the observable state (reads, keys,
    oldest/latest — plus chains/index/touched in legacy mode) the
    sequential set/clear_range loop reaches, with compactions
    interleaved between chunks."""
    from foundationdb_tpu.storage.versioned_map import OP_CLEAR, OP_SET
    rng = DeterministicRandom(seed)
    if columnar:
        seq, bat = _small_columnar(), _small_columnar()
    else:
        seq, bat = (VersionedMap(columnar=False),
                    VersionedMap(columnar=False))
    model = ModelMap()
    keys = [b"k%02d" % i for i in range(14)]
    version = 0
    pending: list[tuple[int, int, bytes, bytes]] = []

    def flush():
        nonlocal pending
        for v, op, p1, p2 in pending:
            if op == OP_SET:
                seq.set(v, p1, p2)
                model.set(v, p1, p2)
            else:
                seq.clear_range(v, p1, p2)
                model.clear_range(v, p1, p2)
        bat.apply_batch(pending)
        pending = []

    def assert_same():
        assert seq.keys() == bat.keys()
        assert (seq.oldest_version, seq.latest_version) == \
            (bat.oldest_version, bat.latest_version)
        for k in keys:
            for probe in (seq.oldest_version, version):
                assert seq.get2(k, probe) == bat.get2(k, probe), (k, probe)
        if not seq.columnar:
            assert seq._chains == bat._chains
            assert list(seq._touched) == list(bat._touched)

    for step in range(400):
        version += rng.random_int(1, 4)
        op = rng.random_int(0, 12)
        if op < 7:
            k = keys[rng.random_int(0, len(keys))]
            pending.append((version, OP_SET, k, b"v%d" % step))
        elif op < 9:
            lo = rng.random_int(0, len(keys))
            hi = rng.random_int(lo, len(keys) + 1)
            pending.append((version, OP_CLEAR,
                            keys[lo] if lo < len(keys) else b"z",
                            keys[hi] if hi < len(keys) else b"z"))
        elif op == 9:
            flush()
        elif op == 10 and rng.random_int(0, 2):
            flush()
            target = version - rng.random_int(0, 10)
            for vm in (seq, bat):
                vm.forget_before(target)
            model.forget_before(target)
        elif op == 11:
            flush()
            back = version - rng.random_int(0, 5)
            for vm in (seq, bat):
                vm.rollback_after(back)
            model.rollback_after(back)
            version = max(version, seq.latest_version)
        if rng.random_int(0, 4) == 0:
            flush()
            assert_same()
            _assert_equal(bat, model, version, keys)
    flush()
    assert_same()
    _assert_equal(bat, model, version, keys)


@pytest.mark.parametrize("columnar", [False, True])
def test_apply_batch_clear_sees_fresh_keys(columnar):
    """A clear_range later in the same batch must tombstone keys whose
    index insert was deferred earlier in the batch."""
    from foundationdb_tpu.storage.versioned_map import OP_CLEAR, OP_SET
    vm = VersionedMap(columnar=columnar)
    vm.apply_batch([
        (1, OP_SET, b"a", b"1"),
        (1, OP_SET, b"b", b"2"),
        (2, OP_CLEAR, b"a", b"b"),      # must see the fresh b"a"
        (3, OP_SET, b"a", b"3"),
    ])
    assert vm.get(b"a", 1) == b"1"
    assert vm.get(b"a", 2) is None      # tombstoned by the clear
    assert vm.get(b"a", 3) == b"3"
    assert vm.get(b"b", 3) == b"2"
    assert vm.keys() == [b"a", b"b"]


@pytest.mark.parametrize("columnar", [False, True])
def test_index_range_bounds_across_runs(columnar):
    """Range bounds must merge every layer: legacy's base run + pending
    overlay, columnar's sealed segment + fresh tip keys."""
    from foundationdb_tpu.storage.versioned_map import OP_SET
    vm = VersionedMap(columnar=columnar)
    # force a sealed/merged base layer, then overlay keys interleaved
    vm.apply_batch([(1, OP_SET, b"k%03d" % i, b"x") for i in range(0, 100, 2)])
    if columnar:
        vm._seal_tip()
    else:
        vm._index._merge()
    vm.apply_batch([(2, OP_SET, b"k%03d" % i, b"y") for i in range(1, 100, 2)])
    got, more = vm.range_read(b"k010", b"k020", 2)
    assert [k for k, _ in got] == [b"k%03d" % i for i in range(10, 20)]
    assert not more
    assert len(vm.keys()) == 100


@pytest.mark.parametrize("columnar", [False, True])
def test_apply_batch_vectorized_clear_bounds(columnar):
    """A run of consecutive clears over a large base resolves its bounds
    through the vectorized searchsorted fast paths — must match the
    sequential clear_range loop exactly."""
    from foundationdb_tpu.storage.versioned_map import OP_CLEAR, OP_SET
    n = 20_000
    sets = [(1, OP_SET, b"k%06d" % (i * 3), b"x") for i in range(n)]
    seq = VersionedMap(columnar=columnar)
    bat = VersionedMap(columnar=columnar)
    seq.apply_batch(sets)
    bat.apply_batch(sets)
    if columnar:
        seq._seal_tip()
        bat._seal_tip()
    else:
        seq._index._merge()
        bat._index._merge()
    clears = [(2 + i, OP_CLEAR, b"k%06d" % (i * 700), b"k%06d" % (i * 700 + 350))
              for i in range(24)]
    for v, _op, b, e in clears:
        seq.clear_range(v, b, e)
    bat.apply_batch(clears)
    assert seq.keys() == bat.keys()
    assert seq.latest_version == bat.latest_version
    for v, _op, b, e in clears:
        assert seq.range_read(b, e, v) == bat.range_read(b, e, v)
        assert seq.range_read(b, e, v - 1) == bat.range_read(b, e, v - 1)
    if not columnar:
        assert seq._chains == bat._chains
        assert list(seq._touched) == list(bat._touched)


@pytest.mark.slow
@pytest.mark.parametrize("columnar", [False, True])
def test_apply_batch_scales_near_linearly(columnar):
    """The O(n²) guard: 1M fresh keys through apply_batch must land in
    seconds (the seed bisect.insort path took minutes — the r5 bench
    collapse) and scale near-linearly from 100k to 1M."""
    import time

    from foundationdb_tpu.storage.versioned_map import OP_SET

    def load_seconds(n: int, chunk: int = 4096) -> float:
        vm = VersionedMap(columnar=columnar)
        # multiplicative hash → distinct, insertion-order-random keys
        ks = [b"u%010d" % ((i * 2654435761) % (1 << 33)) for i in range(n)]
        t0 = time.perf_counter()
        v = 0
        for s in range(0, n, chunk):
            v += 1
            vm.apply_batch([(v, OP_SET, k, b"x" * 16)
                            for k in ks[s:s + chunk]])
        dt = time.perf_counter() - t0
        assert len(vm.keys()) == len(set(ks))
        return dt

    t_small = load_seconds(100_000)
    t_big = load_seconds(1_000_000)
    # seed path: ~1M O(n) memmove inserts ≈ minutes.  Batched path must
    # stay in seconds (≥50x), and within ~3x of linear 100k→1M scaling.
    assert t_big < 30.0, f"1M-key apply took {t_big:.1f}s"
    assert t_big < max(t_small, 0.05) * 30, \
        f"non-linear scaling: 100k={t_small:.2f}s 1M={t_big:.2f}s"


@pytest.mark.parametrize("columnar", [False, True])
def test_rollback_purges_stale_state(columnar):
    """A rollback must not leave higher-version records (queue entries /
    segment layers) parking compaction or resurrecting rolled-back
    writes (r5 review finding, extended to the columnar layers)."""
    vm = VersionedMap(columnar=columnar)
    vm.set(10, b"a", b"1")
    vm.set(120, b"a", b"2")      # unacked suffix
    vm.set(120, b"b", b"x")
    vm.rollback_after(100)       # recovery cut
    if not columnar:
        assert all(v <= 100 for v, _k in vm._touched)
    assert vm.get2(b"a", 120) == (True, b"1")
    assert vm.get2(b"b", 120) == (False, None)
    # new generation writes at lower-than-rolled-back versions
    vm.set(106, b"b", b"y")
    vm.set(107, b"a", b"3")
    vm.forget_before(106)
    assert vm.get2(b"a", 106) == (True, b"1")
    assert vm.get2(b"a", 107) == (True, b"3")
    vm.forget_before(110)
    assert vm.get2(b"a", 110) == (True, b"3")
    assert vm.get2(b"b", 110) == (True, b"y")
    assert vm.keys() == [b"a", b"b"]
    if not columnar:
        assert vm._chains[b"a"] == [(107, b"3")]
        assert vm._chains[b"b"] == [(106, b"y")]
