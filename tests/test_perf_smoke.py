"""Tier-1 apply-throughput guard (tools/perf_smoke.py as a normal test).

100k fresh keys through StorageServer._apply_batch inside a generous
wall budget: the r5 O(n²) VersionedMap index collapse would blow this by
an order of magnitude, so the next quadratic apply path fails CI here
instead of timing out the north-star bench with no summary line."""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import perf_smoke


def test_apply_throughput_smoke():
    perf_smoke.check(n_keys=100_000, budget_s=perf_smoke.DEFAULT_BUDGET_S)


def test_commit_pipeline_throughput_smoke():
    """The whole in-process commit pipeline (proxy → resolver → TLog →
    storage apply) under concurrent writers must clear a generous floor:
    a quadratic shape ANYWHERE on the commit path — proxy tagging, TLog
    queue accounting, peek re-materialization, durability buffering —
    blows the budget by an order of magnitude (measured ~0.5s against
    the 60s budget on a loaded 2-cpu host)."""
    perf_smoke.check_pipeline(n_txns=perf_smoke.PIPE_TXNS,
                              budget_s=perf_smoke.PIPE_BUDGET_S)


def test_feed_tail_throughput_smoke():
    """The change-feed path on top of the pipeline (ISSUE 4): capture
    hook (per-apply MutationBatch.select), retention scan, stream read
    and the client cursor merge — a live consumer must observe every
    committed mutation inside a generous floor (measured ~1s against
    the 60s budget on a loaded 2-cpu host).  Completeness is asserted
    too: a silently lossy feed is worse than a slow one."""
    perf_smoke.check_feed(budget_s=perf_smoke.FEED_BUDGET_S)


def test_read_path_throughput_smoke():
    """The batched multiget read path (ISSUE 5): rows loaded through
    real commits, a scalar get() loop raced against get_multi at batch
    64 (byte-identical results asserted in situ, >= 3x per-key
    throughput required — measured ~20x on a loaded 2-cpu host), then
    concurrent readers mixing coalesced point reads with multigets
    under the same generous wall floor as the other stages."""
    perf_smoke.check_read(budget_s=perf_smoke.READ_BUDGET_S)


def test_resolve_pipeline_smoke():
    """The device commit pipeline (ISSUE 6): the same randomized batches
    — with snapshots crossing the too-old floor and a ring small enough
    to evict mid-run — through the conflict_np CPU twin and the jax
    backend, both under DevicePipeline with identical deterministic
    grouping, verdicts asserted bit-identical in situ; then the in-run
    A/B where pipelined dispatch must beat the unpipelined per-batch
    sync loop by >= 2x (measured ~6x on a loaded 2-cpu host).  The
    budget doubles as a hard wedge deadline."""
    perf_smoke.check_resolve(budget_s=perf_smoke.RESOLVE_BUDGET_S)


def test_heat_admission_smoke():
    """The shard-heat subsystem (ISSUE 7): under an in-process skewed
    load the heat tracker must rank the hot shard first (with a real
    margin and an interior split point for DD), the ratekeeper's heat
    path must arm a tag throttle for the dominant tag, and the armed
    clamp must shed — tagged admission queues on its bucket while
    untagged work stays fast, all bounded by the standing hard wedge
    deadline (measured ~5s against the 60s budget on a 2-cpu host)."""
    perf_smoke.check_heat(budget_s=perf_smoke.HEAT_BUDGET_S)


def test_backup_restore_smoke():
    """The feed-native backup/restore round trip (ISSUE 8): snapshot +
    whole-db feed tail + restore-to-version into a fresh in-process
    cluster, with the restored user keyspace asserted
    sha256-byte-identical to the source at the target version in situ
    (measured ~5s against the 90s budget on a loaded 2-cpu host; the
    budget doubles as the standing hard wedge deadline)."""
    perf_smoke.check_backup(budget_s=perf_smoke.BACKUP_BUDGET_S)


def test_scan_path_smoke():
    """The columnar range-read path (ISSUE 9): rows loaded through real
    commits onto a durable lsm cluster (several sorted runs), then
    full-table scans A/B'd — CLIENT_PACKED_RANGE_READS off vs on, every
    reply round-tripped through the real wire codec — with results
    asserted byte-identical in situ and a >= 3x packed rows/s floor at
    chunk 512 (measured ~5x on a loaded 2-cpu host).  The budget
    doubles as the standing hard wedge deadline."""
    perf_smoke.check_scan(budget_s=perf_smoke.SCAN_BUDGET_S)


def test_bigkeys_memory_wall_smoke():
    """The memory-wall smoke (ISSUE 11): a 2M-key keyspace built on the
    columnar index vs the legacy list twin with an RSS-per-key ceiling
    (≤40 B/key over raw key bytes; the list path measures ≥2x that),
    then the keyspace applied through real packed commit batches and
    served — point/multiget/scan byte-identical columnar-vs-legacy —
    under the standing hard wedge deadline (measured ~75s against the
    420s budget on a loaded 2-cpu host)."""
    perf_smoke.check_bigkeys(budget_s=perf_smoke.BIG_BUDGET_S)


def test_recover_torn_disk_smoke():
    """The torn-disk recovery smoke (ISSUE 12): acked commits onto a
    durable in-process cluster, a power loss with the hostile-disk
    profile armed (unsynced writes tear at sector granularity, some
    surviving sectors corrupt), then recovery over the damaged disk —
    the user keyspace asserted sha256-byte-identical to the acked
    pre-kill state, under the standing hard wedge deadline."""
    perf_smoke.check_recover(budget_s=perf_smoke.RECOVER_BUDGET_S)


def test_mvcc_window_smoke():
    """The MVCC-window smoke (ISSUE 13): a 2M-key hot set HELD IN THE
    WINDOW under both implementations in one process — byte-identical
    get2_batch/range serving asserted in situ, the columnar
    generational window at ≤50% of the legacy dict-of-chains RSS
    overhead, and the combined apply_packed+get2_batch pipeline ≥2x
    the legacy twin, under the standing hard wedge deadline."""
    perf_smoke.check_mvcc(budget_s=perf_smoke.MVCC_BUDGET_S)


def test_lsm_compact_smoke():
    """The lsm compaction smoke (ISSUE 14): a sustained multi-flush
    ingest replayed on BOTH compaction disciplines in one process —
    leveled background vs the monolithic merge-all twin — with point +
    range serving asserted byte-identical in situ, leveled write
    amplification ≤50% of the monolithic twin's (measured ~0.36x on a
    loaded 2-cpu host), and the leveled commit p99 ≤20% of the
    monolithic twin's worst inline merge (measured ~28ms vs a ~5.8s
    monolithic max — no commit awaits a full-keyspace merge), under
    the standing hard wedge deadline."""
    perf_smoke.check_compact(budget_s=perf_smoke.COMPACT_BUDGET_S)


def test_observe_metrics_plane_smoke():
    """The metrics plane (ISSUE 15): every wired role kind emits
    periodic *Metrics events on the sim-clock cadence through the one
    per-worker registry emitter, the cluster.lag rollup served by the
    real status path is sane under load, metrics_tool reconstructs the
    durability-lag series and the epoch-1 RecoveryState audit from the
    recorded events alone, and the plane-on vs plane-off apply-pipeline
    overhead holds ≤10% (measured ~1.0x on a loaded 2-cpu host)."""
    perf_smoke.check_observe(budget_s=perf_smoke.OBSERVE_BUDGET_S)


def test_mesh_routing_smoke():
    """The routed resolver mesh (ISSUE 16): one 2-resolver live cluster
    per A/B side on the REAL commit path under a partition-skewed
    workload — routed resolution must beat the verbatim broadcast twin
    on aggregate commit txns/s (measured ~1.5x on a loaded 2-cpu host),
    the cold partition must answer most sends with header-only
    version advances (the empty-clip fast path), and the hot partition's
    device pipeline must show live-path group fusion, under the standing
    hard wedge deadline."""
    perf_smoke.check_mesh(budget_s=perf_smoke.MESH_BUDGET_S)


def test_scrub_consistency_smoke():
    """The online consistency scrubber (ISSUE 17): the first full
    replica-audit pass on an honest seeded cluster is CLEAN (zero
    mismatches — the false-positive guard), a single row corrupted on
    one replica via the test-only bit-rot hook is then caught within
    one pass as a key-exact severity-40 ScrubMismatch naming both
    replicas, the catch is visible through cluster.scrub and the
    metrics_tool scrub view alike, the frontier watchdog runs with
    zero violations, and the scrub-on twin sim holds within the
    overhead ceiling of its scrub-off twin (measured ~1.2x on a
    loaded 2-cpu host), under the standing hard wedge deadline."""
    perf_smoke.check_scrub(budget_s=perf_smoke.SCRUB_BUDGET_S)


def test_devplane_smoke():
    """The sharded device plane (ISSUE 18): under tail-localized churn
    the 4-shard read mirror must keep serving batched reads off the
    device (partial refresh via the index change log) at >= 1.5x the
    single-directory twin's device-served batch count on the forced
    multi-device CPU mesh, results byte-identical to the engine on both
    sides; and the verdict-bitmask readback must cut device->host
    verdict bytes/txn >= 4x vs the raw-vector twin with bit-identical
    verdicts and real aborts present, under the standing hard wedge
    deadline."""
    perf_smoke.check_devplane(budget_s=perf_smoke.DEVPLANE_BUDGET_S)


def test_layers_smoke():
    """The layer ecosystem (ISSUE 19): the full client-side layer
    stack (feed consumer, async secondary index, invalidating
    read-through cache, watches) on one seeded recruited sim — the
    zipf-0.99 read tier must hold the cache hit-rate floor with
    sampled reads re-proved non-stale at their claimed valid-through
    versions, a pre-armed watch must fire with its key's commit, the
    consistency checker must reach a zero-divergence verdict on the
    honest stack, a single index row rotted outside the maintenance
    path must be caught key-exactly on the next pass, and the catch
    must surface through cluster.layers, the metrics_tool layers view
    and the raw trace alike, under the standing hard wedge deadline."""
    perf_smoke.check_layers(budget_s=perf_smoke.LAYERS_BUDGET_S)


def test_apply_metrics_surface():
    """The apply path must publish its observability counters — a silent
    regression is the other half of the r5 incident."""
    elapsed, metrics = perf_smoke.storage_apply_seconds(n_keys=5_000)
    assert metrics["mutations_applied"] == 5_000
    assert metrics["apply_batches"] == 3          # ceil(5000/2048)
    assert metrics["apply_batch_size_max"] == 2048
    assert metrics["index_keys"] == 5_000
    assert metrics["apply_batch_p99_ms"] >= 0.0
    assert metrics["mutations_per_sec"] > 0
