"""Recruitment + recovery end to end, on the deterministic simulator.

The round-2 verdict's repro (a recruited cluster whose first GRV call hit
Worker.stop_role because every stub was dialed at the worker's base token)
is the skeleton of the first test: recovery must produce a cluster that
actually serves transactions, survives role kills mid-workload, and
refuses to recover past real data loss.

Reference test model: REF:fdbserver/workloads/Cycle.actor.cpp invariants
under machine kills (SURVEY.md §4).
"""

from __future__ import annotations

import asyncio

import pytest

from foundationdb_tpu.client.transaction import Transaction
from foundationdb_tpu.core.cluster_client import (RecoveredClusterView,
                                                  fetch_cluster_state)
from foundationdb_tpu.core.cluster_controller import (ClusterConfigSpec,
                                                      ClusterController)
from foundationdb_tpu.core.cluster_host import CC_TOKEN_OFFSET, ClusterHost
from foundationdb_tpu.core.coordination import CoordinatedState, Coordinator
from foundationdb_tpu.core.worker import Worker
from foundationdb_tpu.rpc.sim_transport import SimNetwork, SimTransport
from foundationdb_tpu.rpc.stubs import (CoordinatorClient, WorkerClient,
                                        serve_role)
from foundationdb_tpu.rpc.transport import (NetworkAddress,
                                            WLTOKEN_FIRST_AVAILABLE)
from foundationdb_tpu.runtime.errors import FdbError, LogDataLoss
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation

BASE = WLTOKEN_FIRST_AVAILABLE


class SimCluster:
    """Test scaffolding: coordinators + workers + a CC over one SimNetwork."""

    def __init__(self, knobs: Knobs, n_workers: int = 6, n_coord: int = 3):
        self.knobs = knobs
        self.net = SimNetwork(knobs)
        self._port = 6000

        self.coord_addrs = []
        self.coordinators = []
        for i in range(n_coord):
            addr = NetworkAddress(f"10.0.0.{i + 1}", 4000)
            t = SimTransport(self.net, addr)
            co = Coordinator(knobs)
            serve_role(t, "coordinator", co, BASE)
            self.coord_addrs.append(addr)
            self.coordinators.append(co)

        self.worker_addrs = []
        self.workers = []
        for i in range(n_workers):
            addr = NetworkAddress(f"10.0.2.{i + 1}", 5000)
            t = SimTransport(self.net, addr)
            w = Worker(i, knobs, t, self.client_transport, BASE)
            self.worker_addrs.append(addr)
            self.workers.append(w)

    def client_transport(self):
        self._port += 1
        return SimTransport(
            self.net, NetworkAddress(f"10.0.9.{self._port % 250}", self._port))

    def coordinator_stubs(self, transport):
        return [CoordinatorClient(transport, a, BASE) for a in self.coord_addrs]

    def make_cc(self, spec: ClusterConfigSpec) -> ClusterController:
        ct = self.client_transport()
        cstate = CoordinatedState(self.coordinator_stubs(ct), my_id=999)
        registry = {a: WorkerClient(ct, a, BASE) for a in self.worker_addrs}
        return ClusterController(self.knobs, ct, cstate, registry, spec, BASE)

    async def client_view(self) -> RecoveredClusterView:
        ct = self.client_transport()
        state = await fetch_cluster_state(self.coordinator_stubs(ct))
        return RecoveredClusterView(self.knobs, ct, state)


async def commit_kv(view, items: dict[bytes, bytes]) -> None:
    tr = Transaction(view)
    while True:
        try:
            for k, v in items.items():
                tr.set(k, v)
            await tr.commit()
            return
        except FdbError as e:
            await tr.on_error(e)


async def read_kv(view, keys) -> dict:
    tr = Transaction(view)
    while True:
        try:
            return {k: await tr.get(k) for k in keys}
        except FdbError as e:
            await tr.on_error(e)


def test_recovered_cluster_serves_transactions():
    """recover_once builds a cluster that serves GRV, commit and reads —
    the exact flow the round-2 repro showed dying in Worker.stop_role."""
    async def main():
        k = Knobs()
        sim = SimCluster(k)
        cc = sim.make_cc(ClusterConfigSpec())
        _, prev = await cc.cstate.read()
        state = await cc.recover_once(prev)
        assert state["epoch"] == 1
        view = await sim.client_view()
        items = {b"k%02d" % i: b"v%02d" % i for i in range(20)}
        await commit_kv(view, items)
        got = await read_kv(view, items)
        assert got == items
        # ratekeeper was recruited and is reachable through the GRV path
        assert state["ratekeeper"]["token"] > BASE
        await cc.stop()
    run_simulation(main())


@pytest.mark.parametrize("kill_role", ["resolver", "tlog"])
def test_role_kill_triggers_rerecovery(kill_role):
    """Kill the worker hosting a txn role mid-workload; cc.run() must
    detect it, run a new epoch, and the cluster must serve transactions
    again WITH pre-kill data intact (peeked across generations)."""
    async def main():
        k = Knobs()
        sim = SimCluster(k)
        cc = sim.make_cc(ClusterConfigSpec())
        cc_task = asyncio.get_running_loop().create_task(cc.run())

        # wait for epoch 1
        ct = sim.client_transport()
        stubs = sim.coordinator_stubs(ct)
        while True:
            try:
                state = await fetch_cluster_state(stubs)
                if state["epoch"] >= 1:
                    break
            except FdbError:
                pass
            await asyncio.sleep(0.2)

        view = await sim.client_view()
        items = {b"pre%02d" % i: b"val%02d" % i for i in range(10)}
        await commit_kv(view, items)

        # find the worker hosting the target role and kill its machine
        if kill_role == "resolver":
            victim = NetworkAddress(*state["resolvers"][0]["addr"])
        else:
            # tlog[1] (w2): tlog[0] shares w1 with a storage replica
            victim = NetworkAddress(*state["log_cfg"][-1]["tlogs"][1])
        # the test design keeps storage off this worker (placement is
        # deterministic: sequencer w0+storage0, tlog w1+storage1, tlog w2,
        # resolver w3) — killing w2/w3 loses no storage replica
        storage_workers = {tuple(s["worker"]) for s in state["storage"]}
        assert (victim.ip, victim.port) not in storage_workers, \
            "test placement assumption broken"
        sim.net.kill(victim)

        # wait for the next epoch
        while True:
            try:
                state2 = await fetch_cluster_state(stubs)
                if state2["epoch"] >= 2:
                    break
            except FdbError:
                pass
            await asyncio.sleep(0.2)

        view2 = await sim.client_view()
        assert view2.epoch >= 2
        # old data survived the recovery (rolled/peeked across generations)
        got = await read_kv(view2, items)
        assert got == items
        # and the new epoch accepts commits
        items2 = {b"post%02d" % i: b"v2%02d" % i for i in range(10)}
        await commit_kv(view2, items2)
        got2 = await read_kv(view2, items2)
        assert got2 == items2

        cc_task.cancel()
        await asyncio.gather(cc_task, return_exceptions=True)
        await cc.stop()
    run_simulation(main())


def test_recovery_refuses_on_data_loss():
    """log_replication=1: killing the only log hosting a tag must make
    recovery raise LogDataLoss instead of serving a gap."""
    async def main():
        k = Knobs()
        sim = SimCluster(k)
        spec = ClusterConfigSpec(log_replication=1)
        cc = sim.make_cc(spec)
        _, prev = await cc.cstate.read()
        state = await cc.recover_once(prev)
        view = await sim.client_view()
        await commit_kv(view, {b"a": b"1", b"b": b"2"})
        # tag 0 lives only on tlog 0 (replication 1): kill its machine
        victim = NetworkAddress(*state["log_cfg"][-1]["tlogs"][0])
        sim.net.kill(victim)
        # let the failure monitor notice
        await asyncio.sleep(k.FAILURE_TIMEOUT * 3)
        _, prev2 = await cc.cstate.read()
        with pytest.raises(LogDataLoss):
            await cc.recover_once(prev2)
        await cc.stop()
    run_simulation(main())


def test_election_cc_and_worker_registration():
    """Full control plane: hosts elect a CC, followers register, the CC
    recovers a working cluster; killing the leader's machine elects a new
    CC which recovers the next epoch and keeps serving."""
    async def main():
        k = Knobs()
        sim = SimCluster(k, n_workers=0)   # hosts below, not bare workers
        hosts = []

        def machine_transport_factory(ip):
            port = [5200]

            def make():
                port[0] += 1
                return SimTransport(sim.net, NetworkAddress(ip, port[0]))
            return make

        for i in range(4):
            ip = f"10.0.3.{i + 1}"
            t = SimTransport(sim.net, NetworkAddress(ip, 5100))
            factory = machine_transport_factory(ip)
            h = ClusterHost(i, k, t, factory, BASE,
                            sim.coordinator_stubs(factory()),
                            ClusterConfigSpec(min_workers=4, replication=2))
            hosts.append(h)
            h.start()

        ct = sim.client_transport()
        stubs = sim.coordinator_stubs(ct)
        while True:
            try:
                state = await fetch_cluster_state(stubs)
                if state.get("epoch", 0) >= 1:
                    break
            except FdbError:
                pass
            await asyncio.sleep(0.25)

        view = await sim.client_view()
        items = {b"e%02d" % i: b"x%02d" % i for i in range(8)}
        await commit_kv(view, items)

        # kill the elected leader's MACHINE: its server transport AND all
        # its outbound client transports go dark at once
        leader = next(h for h in hosts if h._leading)
        sim.net.kill_ip(leader.address.ip)

        # a new leader must take over and publish a fresh epoch
        while True:
            try:
                state2 = await fetch_cluster_state(stubs)
                if state2["epoch"] >= 2:
                    break
            except FdbError:
                pass
            await asyncio.sleep(0.25)

        new_leader = None
        while new_leader is None:
            new_leader = next((h for h in hosts
                               if h._leading and h is not leader), None)
            if new_leader is None:
                await asyncio.sleep(0.25)
        view2 = await sim.client_view()
        got = await read_kv(view2, items)
        assert got == items
        items2 = {b"f%02d" % i: b"y%02d" % i for i in range(8)}
        await commit_kv(view2, items2)

        for h in hosts:
            if h is leader:
                continue    # dead machine: its loop hangs on the network
            await h.stop()
        leader._stopped = True
        if leader._task is not None:
            leader._task.cancel()
            await asyncio.gather(leader._task, return_exceptions=True)
    run_simulation(main())


def test_status_json_reflects_role_health():
    """The status aggregator reports every recruited role, pulls metrics,
    and flags dead roles after a kill (REF:fdbserver/Status.actor.cpp)."""
    async def main():
        from foundationdb_tpu.core.status import cluster_status
        k = Knobs()
        sim = SimCluster(k)
        cc = sim.make_cc(ClusterConfigSpec())
        _, prev = await cc.cstate.read()
        state = await cc.recover_once(prev)
        view = await sim.client_view()
        await commit_kv(view, {b"s1": b"x"})

        ct = sim.client_transport()
        stubs = sim.coordinator_stubs(ct)
        doc = await cluster_status(k, ct, stubs)
        assert doc["cluster"]["epoch"] == 1
        assert doc["cluster"]["database_available"] is True
        by_role = {}
        for r in doc["roles"]:
            by_role.setdefault(r["role"], []).append(r)
        assert set(by_role) == {"sequencer", "log", "resolver", "storage",
                                "commit_proxy", "grv_proxy", "ratekeeper"}
        assert all(r["reachable"] for r in doc["roles"])
        # storage metrics came over RPC
        assert all("metrics" in r for r in by_role["storage"])
        assert by_role["ratekeeper"][0]["tps_limit"] > 0
        # kill a resolver: status must degrade
        victim = NetworkAddress(*state["resolvers"][0]["addr"])
        sim.net.kill(victim)
        doc2 = await cluster_status(k, ct, stubs)
        assert doc2["cluster"]["database_available"] is False
        assert any(d["role"] == "resolver"
                   for d in doc2["cluster"]["degraded_roles"])
        await cc.stop()
    run_simulation(main())


def test_deposed_sequencer_refuses_grv():
    """Epoch fencing: after recovery locks the old sequencer, a stale GRV
    proxy pointing at it can no longer hand out read versions."""
    async def main():
        from foundationdb_tpu.rpc.stubs import GrvProxyClient
        k = Knobs()
        sim = SimCluster(k)
        cc = sim.make_cc(ClusterConfigSpec())
        _, prev = await cc.cstate.read()
        state = await cc.recover_once(prev)
        view = await sim.client_view()
        await commit_kv(view, {b"g": b"1"})
        ct = sim.client_transport()
        old_grv = GrvProxyClient(
            ct, NetworkAddress(*state["grv_proxies"][0]["addr"]),
            state["grv_proxies"][0]["token"])
        assert await old_grv.get_read_version() > 0
        # next epoch: kill a resolver so recovery has a reason, then recover
        sim.net.kill(NetworkAddress(*state["resolvers"][0]["addr"]))
        await asyncio.sleep(k.FAILURE_TIMEOUT * 3)
        _, prev2 = await cc.cstate.read()
        await cc.recover_once(prev2)
        # the old grv proxy's sequencer is now locked: stale reads refused
        with pytest.raises(FdbError):
            await old_grv.get_read_version()
        await cc.stop()
    run_simulation(main())


def test_conf_keys_take_effect_next_recovery():
    """\\xff/conf/ writes through an ordinary transaction reconfigure the
    cluster at the next recovery (system keyspace -> txnStateStore read ->
    recruitment, REF:fdbclient/SystemData.cpp)."""
    async def main():
        k = Knobs()
        sim = SimCluster(k)
        cc = sim.make_cc(ClusterConfigSpec())
        _, prev = await cc.cstate.read()
        state = await cc.recover_once(prev)
        assert len(state["resolvers"]) == 1
        view = await sim.client_view()
        await commit_kv(view, {b"\xff/conf/resolvers": b"2",
                               b"\xff/conf/logs": b"3",
                               b"data": b"x"})
        # let storage apply the conf mutations
        await asyncio.sleep(1.0)
        _, prev2 = await cc.cstate.read()
        state2 = await cc.recover_once(prev2)
        assert len(state2["resolvers"]) == 2
        assert len(state2["log_cfg"][-1]["tlogs"]) == 3
        # the reconfigured cluster serves transactions, old data intact
        view2 = await sim.client_view()
        got = await read_kv(view2, [b"data"])
        assert got == {b"data": b"x"}
        items = {b"after%d" % i: b"y%d" % i for i in range(6)}
        await commit_kv(view2, items)
        assert await read_kv(view2, items) == items
        await cc.stop()
    run_simulation(main())


def test_excluded_worker_gets_no_txn_roles():
    """ManagementAPI exclusion: an excluded worker must receive no
    transaction-subsystem recruit at the next recovery
    (REF:fdbclient/ManagementAPI.actor.cpp excludeServers)."""
    async def main():
        from foundationdb_tpu.core.management import exclude_servers

        k = Knobs()
        sim = SimCluster(k)
        cc = sim.make_cc(ClusterConfigSpec())
        _, prev = await cc.cstate.read()
        state = await cc.recover_once(prev)
        view = await sim.client_view()
        await commit_kv(view, {b"x": b"1"})

        victim = sim.worker_addrs[3]        # hosts the resolver in epoch 1
        assert [victim.ip, victim.port] == state["resolvers"][0]["addr"]

        class _Db:
            async def run(self, fn):
                await commit_kv_fn(view, fn)
        async def commit_kv_fn(view, fn):
            tr = Transaction(view)
            while True:
                try:
                    await fn(tr)
                    await tr.commit()
                    return
                except FdbError as e:
                    await tr.on_error(e)
        await exclude_servers(_Db(), [f"{victim.ip}:{victim.port}"])
        await asyncio.sleep(1.0)            # let storage apply

        _, prev2 = await cc.cstate.read()
        state2 = await cc.recover_once(prev2)
        placed = {tuple(state2["sequencer"]["addr"])}
        placed |= {tuple(a) for a in state2["log_cfg"][-1]["tlogs"]}
        placed |= {tuple(r["addr"]) for r in state2["resolvers"]}
        placed |= {tuple(p["addr"]) for p in
                   state2["commit_proxies"] + state2["grv_proxies"]}
        placed.add(tuple(state2["ratekeeper"]["addr"]))
        assert (victim.ip, victim.port) not in placed, placed
        # the cluster still serves and old data is intact
        view2 = await sim.client_view()
        assert await read_kv(view2, [b"x"]) == {b"x": b"1"}
        await cc.stop()
    run_simulation(main())


def test_role_endpoint_loss_on_live_process_triggers_recovery():
    """A role can die while its process stays reachable (crash +
    supervisor respawn between recruitment and now, or a stopped role):
    address-level failure detection never fires, every TLog push gets
    endpoint_not_found, and without role-endpoint probing the cluster
    wedges forever.  The controller's role probe must notice and
    recover (REF: waitFailureClient on role interfaces)."""
    import asyncio

    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.runtime.simloop import run_simulation
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        sim = SimulatedCluster(Knobs(), n_machines=5,
                               spec=ClusterConfigSpec(min_workers=5))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        db = await sim.database()

        async def w(tr):
            tr.set(b"pre-loss", b"1")
        await db.run(w)

        # surgically stop ONE recruited TLog ROLE on its (live) host:
        # the machine keeps answering pings, only the endpoints vanish
        gen = state1["log_cfg"][-1]
        tlog_addr, tlog_tok = gen["tlogs"][0], gen["token"][0]
        victim = next(m for m in sim.machines
                      if m.alive and m.host is not None
                      and m.ip == tlog_addr[0]
                      and tlog_tok in m.host.worker.roles)
        assert await victim.host.worker.stop_role(tlog_tok)

        # the controller must notice the dead ENDPOINT and recover
        state2 = await sim.wait_epoch(state1["epoch"] + 1)
        assert state2["epoch"] > state1["epoch"]

        # and the recovered cluster serves: reads AND writes
        while True:
            tr = db.create_transaction()
            try:
                tr.set(b"post-loss", b"2")
                await tr.commit()
                break
            except Exception as e:   # noqa: BLE001 — retry through recovery
                await tr.on_error(e)
        tr = db.create_transaction()
        while True:
            try:
                assert await tr.get(b"pre-loss") == b"1"
                assert await tr.get(b"post-loss") == b"2"
                break
            except Exception as e:   # noqa: BLE001
                await tr.on_error(e)
        await sim.stop()
    run_simulation(main())
