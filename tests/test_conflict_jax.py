"""JAX kernel vs NumPy twin: bit-identical verdicts and state."""

import numpy as np
import pytest

from foundationdb_tpu.ops.batch import TxnRequest, encode_batch
from foundationdb_tpu.ops.conflict_jax import JaxConflictSet
from foundationdb_tpu.ops.conflict_np import NumpyConflictSet
from foundationdb_tpu.ops.oracle import OracleConflictSet
from foundationdb_tpu.runtime import DeterministicRandom

W = 16
B, R = 8, 4


def rand_key(rng, maxlen, alphabet=3):
    n = rng.random_int(1, maxlen + 1)
    return bytes(rng.random_int(0, alphabet) for _ in range(n))


def rand_range(rng, maxlen):
    a, b = rand_key(rng, maxlen), rand_key(rng, maxlen)
    if a == b:
        b = a + b"\x00"
    return (min(a, b), max(a, b))


def rand_txn(rng, snap_lo, snap_hi, maxlen):
    return TxnRequest(
        read_ranges=[rand_range(rng, maxlen) for _ in range(rng.random_int(0, R + 1))],
        write_ranges=[rand_range(rng, maxlen) for _ in range(rng.random_int(0, R + 1))],
        read_snapshot=rng.random_int(snap_lo, snap_hi),
    )


@pytest.mark.parametrize("seed,maxlen", [(0, W), (1, W), (2, 3 * W), (3, 3 * W)])
def test_jax_numpy_bit_parity(seed, maxlen):
    """Full trace: verdicts AND ring state identical every batch, including
    ring wraparound (small capacity) and set_oldest_version churn."""
    rng = DeterministicRandom(seed)
    capacity = B * R * 2   # force frequent wraparound
    twin = NumpyConflictSet(capacity, W)
    kern = JaxConflictSet(capacity, W)
    version = 100
    for step in range(40):
        nt = rng.random_int(1, B + 1)
        txns = [rand_txn(rng, max(0, version - 50), version + 1, maxlen) for _ in range(nt)]
        version += rng.random_int(1, 20)
        eb = encode_batch(txns, B, R, W)
        tv = twin.resolve_encoded(eb, version)
        jv = kern.resolve_encoded(eb, version)
        np.testing.assert_array_equal(tv, jv, err_msg=f"verdicts diverge at step {step}")
        # state parity over the canonical ring (twin is row-major [C, L],
        # kernel lane-major [L, C])
        np.testing.assert_array_equal(twin.hb, np.asarray(kern.state.hb).T)
        np.testing.assert_array_equal(twin.he, np.asarray(kern.state.he).T)
        np.testing.assert_array_equal(twin.hver, np.asarray(kern.state.hver))
        assert twin.oldest_version == kern.oldest_version
        if rng.coinflip(0.2):
            oldest = version - rng.random_int(10, 60)
            twin.set_oldest_version(oldest)
            kern.set_oldest_version(oldest)


def test_jax_oracle_parity_short_keys():
    """Against ground truth directly (keys <= W: kernel is exact)."""
    rng = DeterministicRandom(77)
    kern = JaxConflictSet(4096, W)
    oracle = OracleConflictSet()
    version = 100
    for _ in range(25):
        nt = rng.random_int(1, B + 1)
        txns = [rand_txn(rng, max(0, version - 50), version + 1, W) for _ in range(nt)]
        version += rng.random_int(1, 20)
        jv = kern.resolve_encoded(encode_batch(txns, B, R, W), version)[:nt].tolist()
        ov = oracle.resolve_batch(txns, version)
        assert jv == ov


def test_requires_x64(monkeypatch):
    import jax
    if jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", False)
        try:
            with pytest.raises(RuntimeError, match="JAX_ENABLE_X64"):
                JaxConflictSet(64, W)
        finally:
            jax.config.update("jax_enable_x64", True)


@pytest.mark.parametrize("seed,window", [(10, 8), (11, 32), (12, 64)])
def test_windowed_fast_path_parity(seed, window):
    """The windowed kernel (fast path + lax.cond fallback) must match the
    full-scan twin bit-for-bit: old snapshots force the fallback, recent
    ones ride the window — both paths get exercised here."""
    rng = DeterministicRandom(seed)
    capacity = B * R * 4
    twin = NumpyConflictSet(capacity, W)
    kern = JaxConflictSet(capacity, W, window=window)
    assert kern.window == window
    version = 100
    for step in range(30):
        nt = rng.random_int(1, B + 1)
        # mix: some snapshots far in the past (fallback), some recent
        lo = 0 if rng.coinflip(0.3) else max(0, version - 30)
        txns = [rand_txn(rng, lo, version + 1, W) for _ in range(nt)]
        version += rng.random_int(1, 20)
        eb = encode_batch(txns, B, R, W)
        tv = twin.resolve_encoded(eb, version)
        jv = kern.resolve_encoded(eb, version)
        np.testing.assert_array_equal(tv, jv, err_msg=f"step {step}")
        np.testing.assert_array_equal(twin.hver, np.asarray(kern.state.hver)[:capacity])


def test_group_submit_matches_serial():
    """resolve_group_submit (hot/cold fused scan + bucket padding) vs
    one-batch-at-a-time submission: verdicts AND ring state must match
    bit for bit — pad batches are dropped at the final append, so a
    padded group advances the ring by exactly its real slabs, like the
    serial chain.  (The fused floor advances once per dispatch instead
    of once per batch; with snapshots inside retained history — the only
    regime the parity gate covers — the end-of-dispatch floor is
    identical.)"""
    rng = DeterministicRandom(21)
    capacity = B * R * 64    # ample: snapshots never near the floor edge
    window = B * R * 4
    serial = JaxConflictSet(capacity, W, window=window)
    grouped = JaxConflictSet(capacity, W, window=window)
    version = 100
    for round_, k in enumerate([1, 2, 4, 3, 5, 6, 8]):
        ebs, cvs = [], []
        for _ in range(k):
            nt = rng.random_int(1, B + 1)
            txns = [rand_txn(rng, max(0, version - 50), version + 1, W)
                    for _ in range(nt)]
            version += rng.random_int(1, 20)
            ebs.append(encode_batch(txns, B, R, W))
            cvs.append(version)
        sv = [serial.resolve_encoded(eb, cv) for eb, cv in zip(ebs, cvs)]
        gv = np.asarray(grouped.resolve_group_submit(ebs, cvs))
        for i in range(k):
            np.testing.assert_array_equal(
                sv[i], gv[i], err_msg=f"round {round_} batch {i}")
        np.testing.assert_array_equal(np.asarray(serial.state.hver),
                                      np.asarray(grouped.state.hver),
                                      err_msg=f"round {round_}")
        np.testing.assert_array_equal(np.asarray(grouped.state.hb),
                                      np.asarray(serial.state.hb))
        assert int(serial.state.floor) == int(grouped.state.floor)


def test_point_equality_kernel_parity():
    """All-point groups over an all-point ring take the equality-rule
    kernel (r5); verdicts must stay bit-identical to the numpy twin's
    interval path — including keys at the truncation boundary (exactly
    W bytes vs longer keys sharing the W-byte prefix)."""
    from foundationdb_tpu.ops.conflict_jax import _eb_is_point

    rng = DeterministicRandom(31)
    capacity = B * R * 16
    twin = NumpyConflictSet(capacity, W)
    kern = JaxConflictSet(capacity, W, window=B * R * 4)

    def point(k):
        return (k, k + b"\x00")

    pool = [b"p%02d" % i for i in range(10)]
    pool += [b"x" * W, b"x" * W + b"tail", b"x" * W + b"liat",
             b"x" * (W - 1), b"y" * (W + 4)]
    version = 100
    for step in range(30):
        nt = rng.random_int(1, B + 1)
        txns = []
        for _ in range(nt):
            reads = [point(pool[rng.random_int(0, len(pool))])
                     for _ in range(rng.random_int(0, R + 1))]
            writes = [point(pool[rng.random_int(0, len(pool))])
                      for _ in range(rng.random_int(0, R + 1))]
            txns.append(TxnRequest(reads, writes,
                                   rng.random_int(max(0, version - 50),
                                                  version + 1)))
        version += rng.random_int(1, 20)
        eb = encode_batch(txns, B, R, W)
        assert _eb_is_point(eb, W)
        tv = twin.resolve_encoded(eb, version)
        jv = kern.resolve_encoded(eb, version)
        np.testing.assert_array_equal(tv, jv, err_msg=f"step {step}")
        np.testing.assert_array_equal(twin.hver, np.asarray(kern.state.hver))
    assert kern._ring_all_point     # the fast path actually engaged


def test_range_dispatch_clears_point_ring_flag():
    kern = JaxConflictSet(B * R * 8, W)
    pt = encode_batch([TxnRequest([(b"a", b"a\x00")], [(b"a", b"a\x00")],
                                  90)], B, R, W)
    kern.resolve_encoded(pt, 100)
    assert kern._ring_all_point
    rg = encode_batch([TxnRequest([(b"a", b"c")], [(b"a", b"c")], 105)],
                      B, R, W)
    assert int(kern.resolve_encoded(rg, 110)[0]) == 0   # committed
    assert not kern._ring_all_point
    # still correct afterwards (interval path resumes)
    v = kern.resolve_encoded(encode_batch(
        [TxnRequest([(b"b", b"b\x00")], [], 105)], B, R, W), 120)
    assert int(v[0]) == 1       # read b at snap 105 vs range write at 110
    kern.reset_ring(0)
    assert kern._ring_all_point
