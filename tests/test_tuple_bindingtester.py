"""Tuple layer + bindingtester stack machine + multi-version client.

Reference test models: REF:bindings/bindingtester (same instruction
stream through two implementations, byte-identical results) and the
tuple layer's defining property (byte order of pack == semantic order).
"""

from __future__ import annotations

import random
import uuid

import pytest

from foundationdb_tpu.client import tuple as fdbtuple
from foundationdb_tpu.client.tuple import Versionstamp


# --- tuple layer ---

def _rand_item(rng: random.Random):
    kind = rng.randrange(7)
    if kind == 0:
        return None
    if kind == 1:
        return bytes(rng.randrange(256) for _ in range(rng.randrange(6)))
    if kind == 2:
        return "".join(rng.choice("abé中") for _ in range(rng.randrange(5)))
    if kind == 3:
        return rng.randrange(-(1 << 60), 1 << 60)
    if kind == 4:
        return rng.uniform(-1e9, 1e9)
    if kind == 5:
        return rng.random() < 0.5
    return tuple(_rand_item(rng) for _ in range(rng.randrange(3)))


def test_tuple_roundtrip_random():
    rng = random.Random(7)
    for _ in range(500):
        t = tuple(_rand_item(rng) for _ in range(rng.randrange(5)))
        packed = fdbtuple.pack(t)
        assert fdbtuple.unpack(packed) == t, t


def test_tuple_roundtrip_specials():
    t = (None, b"", b"a\x00b", "", "é\x00x", 0, 1, -1, 255, 256,
         -255, -256, (1 << 60), -(1 << 60), 0.0, -1.5, 2.5,
         True, False, (None, (b"n",), 3), uuid.UUID(int=0x1234),
         Versionstamp(b"\x01" * 10, 7))
    assert fdbtuple.unpack(fdbtuple.pack(t)) == t


def _order_key(item):
    """Semantic sort key mirroring the spec's cross-type order."""
    if item is None:
        return (0,)
    if isinstance(item, bytes):
        return (1, item)
    if isinstance(item, str):
        return (2, item.encode())
    if isinstance(item, tuple):
        return (5, tuple(_order_key(x) for x in item))
    if isinstance(item, bool):
        return (38, item)
    if isinstance(item, int):
        return (20, item)
    if isinstance(item, float):
        return (33, item)
    raise TypeError(item)


def test_tuple_pack_preserves_order():
    """The defining property: byte comparison of packs == semantic
    comparison of tuples (REF:bindings tuple spec)."""
    rng = random.Random(11)
    tuples = [tuple(_rand_item(rng) for _ in range(rng.randrange(1, 4)))
              for _ in range(400)]
    packed = sorted(tuples, key=lambda t: fdbtuple.pack(t))
    semantic = sorted(tuples, key=lambda t: tuple(_order_key(x) for x in t))
    for a, b in zip(packed, semantic):
        assert tuple(_order_key(x) for x in a) == \
            tuple(_order_key(x) for x in b), (a, b)


def test_tuple_int_boundaries():
    for v in (0, 1, -1, 0xFF, 0x100, -0xFF, -0x100, (1 << 64) - 1,
              -((1 << 64) - 1)):
        assert fdbtuple.unpack(fdbtuple.pack((v,))) == (v,)
    with pytest.raises(ValueError):
        fdbtuple.pack((1 << 64,))


def test_tuple_range():
    b, e = fdbtuple.range_of((b"app",))
    inside = fdbtuple.pack((b"app", 3))
    assert b <= inside < e
    assert not b <= fdbtuple.pack((b"apq",)) < e


# --- bindingtester stack machine: native client vs model ---

def test_stack_machine_native_vs_model():
    """The bindingtester property: the same seeded instruction stream
    through the native client (on a sim cluster) and the brute-force
    model must leave byte-identical stacks and databases."""
    import asyncio

    from bindings.bindingtester.stack_tester import (ModelDatabase,
                                                     StackMachine,
                                                     generate_program)
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.core.data import SYSTEM_PREFIX
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.runtime.simloop import run_simulation
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        sim = SimulatedCluster(Knobs(), n_machines=4,
                               spec=ClusterConfigSpec(min_workers=4))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        for seed in (1, 2):
            program = generate_program(seed, n_ops=250)
            native = StackMachine(db)
            model = StackMachine(ModelDatabase())
            await native.run(program)
            await model.run(program)
            assert native.stack == model.stack, (
                f"seed {seed}: stack diverged at "
                f"{next(i for i, (a, b) in enumerate(zip(native.stack, model.stack)) if a != b)}"
            )
            tr = db.create_transaction()
            while True:
                try:
                    rows = await tr.get_range(b"", SYSTEM_PREFIX, limit=0)
                    break
                except Exception as e:  # noqa: BLE001
                    await tr.on_error(e)
            assert dict(rows) == model.db.data, f"seed {seed}: db diverged"
            # wipe between seeds

            async def wipe(t):
                t.clear_range(b"", SYSTEM_PREFIX)
            await db.run(wipe)
        await sim.stop()
    run_simulation(main())


# --- multi-version client ---

def test_multiversion_api_gating():
    from foundationdb_tpu.client import multiversion as mv
    mv._reset_api_version_for_tests()
    with pytest.raises(mv.ApiVersionUnset):
        mv.MultiVersionDatabase("native", object())
    with pytest.raises(mv.ApiVersionInvalid):
        mv.api_version(100)
    mv.api_version(710)
    mv.api_version(710)            # idempotent re-select of the same
    with pytest.raises(mv.ApiVersionAlreadySet):
        mv.api_version(520)
    assert mv.selected_api_version() == 710
    mv._reset_api_version_for_tests()


def test_multiversion_versionstamp_gate():
    import asyncio

    from foundationdb_tpu.client import multiversion as mv
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.runtime.simloop import run_simulation
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        mv._reset_api_version_for_tests()
        mv.api_version(300)        # pre-versionstamp era
        sim = SimulatedCluster(Knobs(), n_machines=4,
                               spec=ClusterConfigSpec(min_workers=4))
        await sim.start()
        await sim.wait_epoch(1)
        db = mv.MultiVersionDatabase("native", await sim.database())
        tr = db.create_transaction()
        tr.set(b"plain", b"ok")    # ordinary surface unaffected
        with pytest.raises(mv.ApiVersionInvalid):
            tr.set_versionstamped_key(b"k\x00\x00\x00\x00", b"v")
        await tr.commit()
        assert await db.get(b"plain") == b"ok"
        mv._reset_api_version_for_tests()
        mv.api_version(710)
        db2 = mv.MultiVersionDatabase("native", await sim.database())
        tr = db2.create_transaction()
        tr.set_versionstamped_key(b"vs-0123456789" + b"\x00" * 2 +
                                  b"\x03\x00\x00\x00", b"v")
        await tr.commit()
        rows = await db2.get_range(b"vs-", b"vs-\xff")
        assert len(rows) == 1 and rows[0][1] == b"v"
        mv._reset_api_version_for_tests()
        await sim.stop()
    run_simulation(main())


def test_stack_machine_directory_ops_native_vs_model():
    """Directory-layer bindingtester: the same seeded DIRECTORY_* stream
    through the native client and the brute-force model must leave
    byte-identical stacks AND byte-identical databases (both layers draw
    allocator candidates from identically-seeded RNGs)."""
    from bindings.bindingtester.stack_tester import (
        ModelDatabase, StackMachine, generate_directory_program)
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.core.data import SYSTEM_PREFIX
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.runtime.simloop import run_simulation
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        sim = SimulatedCluster(Knobs(), n_machines=4,
                               spec=ClusterConfigSpec(min_workers=4))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        for seed in (4, 9):
            program = generate_directory_program(seed, n_ops=50)
            native = StackMachine(db, dir_seed=1000 + seed)
            model = StackMachine(ModelDatabase(), dir_seed=1000 + seed)
            await native.run(program)
            await model.run(program)
            assert native.stack == model.stack, (
                f"seed {seed}: stack diverged at index "
                f"{next(i for i, (a, b) in enumerate(zip(native.stack, model.stack)) if a != b)}"
            )
            tr = db.create_transaction()
            while True:
                try:
                    rows = await tr.get_range(b"", SYSTEM_PREFIX, limit=0)
                    break
                except Exception as e:  # noqa: BLE001
                    await tr.on_error(e)
            assert dict(rows) == model.db.data, f"seed {seed}: db diverged"

            async def wipe(t):
                t.clear_range(b"", SYSTEM_PREFIX)
            await db.run(wipe)
        await sim.stop()
    run_simulation(main())
