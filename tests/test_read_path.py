"""Batched multiget read path (ISSUE 5): equivalence + fence tests.

The contract under test everywhere here: the batched surfaces —
``VersionedMap.get2_batch``, the engines' ``get_batch``,
``StorageServer.get_values``, ``Transaction.get_multi`` and the
same-tick coalescer behind ``Transaction.get`` — return BYTE-IDENTICAL
results to the scalar one-key-at-a-time paths they replace, on
randomized workloads including RYW overlays, cleared ranges,
too-old/future-version keys mid-batch, relinquished ranges and shard
boundaries.  Plus the 714 protocol fence.
"""

from __future__ import annotations

import asyncio
import random

import pytest

from foundationdb_tpu.core.data import (GV_FOUND, GV_FUTURE_VERSION,
                                        GV_MISSING, GV_TOO_OLD,
                                        GV_WRONG_SHARD, GetValuesReply,
                                        GetValuesRequest, KeyRange, Mutation)
from foundationdb_tpu.runtime.knobs import Knobs


def krand(rng: random.Random) -> bytes:
    return b"k%04d" % rng.randrange(600)


# --- wire structs ---

def test_get_values_wire_roundtrip():
    from foundationdb_tpu.rpc.wire import decode, encode
    req = GetValuesRequest.from_keys([b"a", b"bb", b"", b"ccc"], 99)
    got = decode(encode(req))
    assert got == req
    assert list(got.iter_keys()) == [b"a", b"bb", b"", b"ccc"]
    assert [got.key(i) for i in range(4)] == [b"a", b"bb", b"", b"ccc"]
    rep = GetValuesReply.build(bytearray([0, 1, 2, 0]),
                               [b"v0", None, None, b""])
    got = decode(encode(rep))
    assert got.value(0) == b"v0" and got.value(3) == b""
    assert got.codes == bytes([0, 1, 2, 0])
    uni = GetValuesReply.uniform(GV_TOO_OLD, 3)
    assert len(uni) == 3 and set(uni.codes) == {GV_TOO_OLD}
    assert uni.value(1) == b""


# --- the protocol fence (713 peer must be refused) ---

def test_version_gate_fences_713_peer():
    from foundationdb_tpu.core.cluster_client import RecoveredClusterView
    from foundationdb_tpu.runtime.errors import ClusterVersionChanged
    new = Knobs()
    assert new.PROTOCOL_VERSION >= 714   # 714 introduced the multiget structs
    old = new.override(PROTOCOL_VERSION=713)
    state = {"epoch": 1, "seq": 0, "protocol": new.PROTOCOL_VERSION}
    with pytest.raises(ClusterVersionChanged):
        RecoveredClusterView(old, None, state)


# --- VersionedMap.get2_batch ---

def test_get2_batch_matches_scalar():
    from foundationdb_tpu.storage.versioned_map import VersionedMap
    rng = random.Random(7)
    vm = VersionedMap()
    version = 0
    for _ in range(40):
        version += rng.randrange(1, 3)
        ops = []
        for _ in range(rng.randrange(1, 30)):
            if rng.random() < 0.15:
                b = krand(rng)
                ops.append((version, 1, b, b + b"\xff"))
            else:
                ops.append((version, 0, krand(rng),
                            b"v%d" % rng.randrange(1000)))
        vm.apply_batch(ops)
    probes = sorted({krand(rng) for _ in range(200)} | {b"zz-missing"})
    for v in (0, 1, version // 2, version, version + 5):
        assert vm.get2_batch(probes, v) == [vm.get2(k, v) for k in probes]


# --- engine get_batch (memory / lsm / btree) ---

def _engine_workload(rng: random.Random):
    """Ordered op batches + the final expected dict."""
    batches = []
    for r in range(12):
        ops = []
        for _ in range(rng.randrange(5, 60)):
            if rng.random() < 0.1:
                b = krand(rng)
                ops.append((1, b, b + b"\xff"))
            else:
                ops.append((0, krand(rng), b"val%05d" % rng.randrange(9999)))
        batches.append(ops)
    return batches


@pytest.mark.parametrize("engine_name", ["memory", "lsm", "btree"])
def test_engine_get_batch_matches_scalar(engine_name, monkeypatch):
    import foundationdb_tpu.storage.lsm as lsm_mod
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.storage import engine_class
    if engine_name == "lsm":
        # small thresholds: force flushes + several runs so the batched
        # probe actually walks the sorted-run indexes
        monkeypatch.setattr(lsm_mod, "_MEMTABLE_BYTES", 1500)
        monkeypatch.setattr(lsm_mod, "_BLOCK_BYTES", 128)

    async def main():
        rng = random.Random(13 + len(engine_name))
        fs = SimFileSystem()
        kv = await engine_class(engine_name).open(fs, f"db/{engine_name}")
        for i, ops in enumerate(_engine_workload(rng)):
            await kv.commit(ops, {"durable_version": i})
        probes = sorted({krand(rng) for _ in range(300)}
                        | {b"", b"zzzz", b"k0000"})
        assert kv.get_batch(probes) == [kv.get(k) for k in probes]
        # and after reopen (runs/tree recovered from disk)
        await kv.close()
        kv2 = await engine_class(engine_name).open(fs, f"db/{engine_name}")
        assert kv2.get_batch(probes) == [kv2.get(k) for k in probes]
        await kv2.close()

    asyncio.run(main())


# --- StorageServer.get_values ---

def _apply_random(ss, rng: random.Random, versions: int = 20) -> int:
    version = ss.version
    for _ in range(versions):
        version += rng.randrange(1, 3)
        muts = []
        for _ in range(rng.randrange(1, 25)):
            if rng.random() < 0.12:
                b = krand(rng)
                muts.append(Mutation.clear_range(b, b + b"\xff"))
            else:
                muts.append(Mutation.set(krand(rng),
                                         b"v%05d" % rng.randrange(9999)))
        ss._apply_batch([(version, muts)])
    return version


def test_storage_get_values_matches_scalar():
    from foundationdb_tpu.core.storage_server import StorageServer
    from foundationdb_tpu.core.tlog import TLog

    async def main():
        rng = random.Random(23)
        knobs = Knobs()
        ss = StorageServer(knobs, 0, KeyRange(b"", b"\xff"), TLog(knobs))
        tip = _apply_random(ss, rng)
        probes = sorted({krand(rng) for _ in range(150)} | {b"nope"})
        for v in (tip, tip - 3, ss.oldest_version):
            rep = await ss.get_values(GetValuesRequest.from_keys(probes, v))
            for i, k in enumerate(probes):
                scalar = await ss.get_value(k, v)
                if rep.codes[i] == GV_FOUND:
                    assert rep.value(i) == scalar, (k, v)
                else:
                    assert rep.codes[i] == GV_MISSING and scalar is None

    asyncio.run(main())


def test_storage_get_values_engine_fallthrough():
    """Keys whose chains left the MVCC window resolve through the
    engine's batched probe — same bytes as scalar get_value."""
    from foundationdb_tpu.core.storage_server import StorageServer
    from foundationdb_tpu.core.tlog import TLog
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.storage.kv_store import MemoryKVStore

    async def main():
        rng = random.Random(31)
        fs = SimFileSystem()
        eng = await MemoryKVStore.open(fs, "db/ss-eng")
        # durable rows below the window
        await eng.commit([(0, b"k%04d" % i, b"durable%04d" % i)
                          for i in range(0, 600, 2)],
                         {"durable_version": 0})
        knobs = Knobs()
        ss = StorageServer(knobs, 0, KeyRange(b"", b"\xff"), TLog(knobs),
                           engine=eng)
        tip = _apply_random(ss, rng, versions=10)
        probes = sorted({b"k%04d" % rng.randrange(620) for _ in range(200)})
        rep = await ss.get_values(GetValuesRequest.from_keys(probes, tip))
        for i, k in enumerate(probes):
            scalar = await ss.get_value(k, tip)
            got = rep.value(i) if rep.codes[i] == GV_FOUND else None
            assert got == scalar, (k, got, scalar)

    asyncio.run(main())


def test_storage_get_values_per_key_fences():
    """A batch mixing healthy keys with relinquished-range keys gets
    per-key wrong_shard codes — the good keys still answer; and
    batch-wide too-old / future-version mark every key without failing
    the RPC."""
    from foundationdb_tpu.core.storage_server import StorageServer
    from foundationdb_tpu.core.tlog import TLog

    async def main():
        knobs = Knobs().override(STORAGE_FUTURE_VERSION_WAIT=0.05)
        ss = StorageServer(knobs, 0, KeyRange(b"b", b"y"), TLog(knobs))
        ss._apply_batch([(5, [Mutation.set(b"c1", b"v1"),
                              Mutation.set(b"m1", b"v2"),
                              Mutation.set(b"p1", b"v3")])])
        ss._drop_shard(6, b"m", b"n")   # live-move handoff of [m, n)
        ss._apply_batch([(7, [Mutation.set(b"c2", b"v4")])])
        probes = [b"a0", b"c1", b"m1", b"p1", b"z0"]
        # above the drop version: m1 fenced, shard-outside keys fenced,
        # the rest healthy
        rep = await ss.get_values(GetValuesRequest.from_keys(probes, 7))
        assert list(rep.codes) == [GV_WRONG_SHARD, GV_FOUND, GV_WRONG_SHARD,
                                   GV_FOUND, GV_WRONG_SHARD]
        assert rep.value(1) == b"v1" and rep.value(3) == b"v3"
        # at-or-below the drop version the range still serves history
        rep = await ss.get_values(GetValuesRequest.from_keys([b"m1"], 6))
        assert list(rep.codes) == [GV_FOUND] and rep.value(0) == b"v2"
        # batch-wide too-old
        ss.oldest_version = 7
        rep = await ss.get_values(GetValuesRequest.from_keys(probes, 3))
        assert set(rep.codes) == {GV_TOO_OLD}
        # batch-wide future version (nothing ever applies version 99)
        rep = await ss.get_values(GetValuesRequest.from_keys(probes, 99))
        assert set(rep.codes) == {GV_FUTURE_VERSION}

    asyncio.run(main())


# --- replica failover on wholesale can't-serve replies ---

def test_get_values_fails_over_lagged_and_compacted_replicas():
    """A replica answering WHOLESALE future_version (lags its team) or
    WHOLESALE too_old (MVCC floor compacted past the read) is skipped
    for a teammate that can serve — the batched twin of the scalar
    path's retryable-exception failover — and only when EVERY replica
    refuses does the client see the per-key code."""
    from foundationdb_tpu.core.load_balance import ReplicaGroup

    class _Stub:
        tag = 0

        def __init__(self, reply):
            self._reply = reply

        async def get_values(self, req):
            return self._reply

    async def main():
        good = GetValuesReply.build(bytes([GV_FOUND]), [b"served"])
        for bad_code in (GV_FUTURE_VERSION, GV_TOO_OLD):
            bad = GetValuesReply.uniform(bad_code, 1)
            req = GetValuesRequest.from_keys([b"k"], 10)
            shard = KeyRange(b"", b"\xff")
            # whichever order the score picks, the serving replica wins
            g = ReplicaGroup(shard, [_Stub(bad), _Stub(good)])
            rep = await g.get_values(req)
            assert list(rep.codes) == [GV_FOUND] and rep.value(0) == b"served"
            # every replica refusing surfaces the code per key
            g2 = ReplicaGroup(shard, [_Stub(bad), _Stub(bad)])
            rep2 = await g2.get_values(req)
            assert set(rep2.codes) == {bad_code}

    asyncio.run(main())


# --- Transaction.get_multi / coalescing ---

def _seed_cluster(knobs=None, shards: int = 3):
    from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
    return Cluster(ClusterConfig(storage_servers=shards),
                   knobs or Knobs())


async def _load(cluster, rows: dict[bytes, bytes]) -> None:
    from foundationdb_tpu.client.transaction import Transaction
    tr = Transaction(cluster)
    for k, v in rows.items():
        tr.set(k, v)
    await tr.commit()


def _overlay(tr, rng: random.Random) -> None:
    """A randomized RYW overlay: sets, clears, atomic stacks."""
    for _ in range(25):
        tr.set(krand(rng), b"ryw%04d" % rng.randrange(999))
    b = krand(rng)
    tr.clear_range(b, b + b"\x80")
    for _ in range(6):
        tr.add(krand(rng), (rng.randrange(1, 200)).to_bytes(4, "little"))


def test_get_multi_matches_get_loop():
    from foundationdb_tpu.client.transaction import Transaction

    async def main():
        cluster = _seed_cluster()
        cluster.start()
        rng = random.Random(41)
        await _load(cluster, {krand(rng): b"base%04d" % i
                              for i in range(300)})
        for snapshot in (False, True):
            tr_a = Transaction(cluster)
            tr_b = Transaction(cluster)
            rng2 = random.Random(43)
            _overlay(tr_a, random.Random(99))
            _overlay(tr_b, random.Random(99))
            probes = [krand(rng2) for _ in range(120)] + [b"zz-missing"]
            batched = await tr_a.get_multi(probes, snapshot=snapshot)
            scalar = [await tr_b.get(k, snapshot=snapshot) for k in probes]
            assert batched == scalar
            # conflict bookkeeping per key must match the scalar loop's
            assert sorted(tr_a._read_conflicts) == \
                sorted(tr_b._read_conflicts)
        await cluster.stop()

    asyncio.run(main())


def test_concurrent_gets_coalesce_and_match():
    from foundationdb_tpu.client.transaction import Transaction

    async def main():
        cluster = _seed_cluster(shards=2)
        cluster.start()
        rows = {b"c%04d" % i: b"v%04d" % i for i in range(100)}
        await _load(cluster, rows)
        tr = Transaction(cluster)
        keys = sorted(rows) + [b"missing1", b"missing2"]
        conc = await asyncio.gather(*(tr.get(k, snapshot=True)
                                      for k in keys))
        assert conc == [rows.get(k) for k in keys]
        co = cluster._read_coalescer
        assert co.max_batch > 1, "concurrent gets never formed a batch"
        # the knob-off scalar path returns the same bytes
        k2 = Knobs().override(CLIENT_COALESCE_READS=False)
        c2 = _seed_cluster(knobs=k2, shards=2)
        c2.start()
        await _load(c2, rows)
        tr2 = Transaction(c2)
        seq = await asyncio.gather(*(tr2.get(k, snapshot=True)
                                     for k in keys))
        assert seq == conc
        assert getattr(c2, "_read_coalescer", None) is None
        await c2.stop()
        await cluster.stop()

    asyncio.run(main())


def test_get_multi_spans_shard_boundaries():
    from foundationdb_tpu.client.transaction import Transaction

    async def main():
        cluster = _seed_cluster(shards=4)
        cluster.start()
        rows = {bytes([b]) + b"-key": bytes([b]) * 3
                for b in range(1, 250, 7)}
        await _load(cluster, rows)
        tr = Transaction(cluster)
        probes = sorted(rows) + [b"\x00nope", b"\xfe\xfe"]
        got = await tr.get_multi(probes)
        assert got == [rows.get(k) for k in probes]
        # the fan-out really touched several shards
        touched = {id(cluster.storage_for_key(k)) for k in rows}
        assert len(touched) > 1
        await cluster.stop()

    asyncio.run(main())


# --- batched change-feed capture (ROADMAP PR 4 (c)) ---

def _naive_capture(feeds, version, batch, shard):
    """The pre-ISSUE-5 per-feed scan, kept as the reference model."""
    from foundationdb_tpu.core.change_feed import _filter_excluded
    out = {}
    for fid, f in feeds.items():
        if version <= f.register_version or version <= f.popped_version:
            continue
        if f.fence is not None and version > f.fence:
            continue
        rb, re_ = f.range.begin, f.range.end
        if shard is not None:
            rb, re_ = max(rb, shard.begin), min(re_, shard.end)
            if rb >= re_:
                continue
        ops = list(batch.iter_ops())
        idxs = [i for i, (t, p1, p2) in enumerate(ops)
                if (rb <= p1 < re_ if t == 0 else (p1 < re_ and rb < p2))]
        if idxs:
            clip = list(f.excluded)
            if rb > b"":
                clip.append((0, b"", rb))
            clip.append((0, re_, b"\xff\xff\xff\xff"))
            sub = _filter_excluded(batch.select(idxs), clip)
            if sub:
                out[fid] = [sub.mutation(i) for i in range(len(sub))]
    return out


def test_capture_interval_pass_matches_per_feed_scan():
    from foundationdb_tpu.core.change_feed import ChangeFeedStore
    from foundationdb_tpu.core.data import MutationBatchBuilder
    rng = random.Random(59)
    store = ChangeFeedStore()
    # overlapping, nested and disjoint feeds, one excluded subrange
    feeds = [(b"f1", b"k01", b"k40"), (b"f2", b"k20", b"k80"),
             (b"f3", b"k25", b"k30"), (b"f4", b"k70", b"k99"),
             (b"f5", b"", b"\xff")]
    for fid, b, e in feeds:
        store.register(fid, b, e, 0)
    store.feeds[b"f2"].excluded = [(1, b"k55", b"k60")]
    shard = KeyRange(b"k0", b"k9")
    for version in range(1, 15):
        bld = MutationBatchBuilder()
        for _ in range(rng.randrange(1, 25)):
            if rng.random() < 0.3:
                lo = rng.randrange(95)
                # cap at 99: two-digit keys keep the range lexicographic
                # (a real client can never commit an inverted clear)
                hi = min(lo + rng.randrange(1, 20), 99)
                bld.add(1, b"k%02d" % lo, b"k%02d" % hi)
            else:
                bld.add(0, b"k%02d" % rng.randrange(99),
                        b"p%04d" % rng.randrange(999))
        batch = bld.finish()
        expect = _naive_capture(store.feeds, version, batch, shard)
        before = {fid: len(f.versions) for fid, f in store.feeds.items()}
        store.capture(version, batch, shard=shard)
        for fid, f in store.feeds.items():
            grew = len(f.versions) - before[fid]
            if fid in expect:
                assert grew == 1, (version, fid)
                got = [f.batches[-1].mutation(i)
                       for i in range(len(f.batches[-1]))]
                assert got == expect[fid], (version, fid)
            else:
                assert grew == 0, (version, fid)


# --- adaptive range-read chunking (satellite b) ---

def test_snapshot_stream_adaptive_chunk():
    from foundationdb_tpu.client.transaction import Transaction

    async def main():
        knobs = Knobs().override(CLIENT_RANGE_CHUNK_ROWS=16)
        cluster = _seed_cluster(knobs=knobs, shards=1)
        cluster.start()
        rows = {b"r%05d" % i: b"x" * 20 for i in range(700)}
        await _load(cluster, rows)
        tr = Transaction(cluster)
        seen_limits: list[int] = []
        group = cluster.storage_for_key(b"r00000")
        inner = group.get_key_values_packed

        async def spy(req):
            seen_limits.append(req.limit)
            return await inner(req)

        group.get_key_values_packed = spy
        got = await tr.get_range(b"r", b"s")
        assert got == sorted(rows.items())
        # the knob seeds the first fetch; later fetches doubled
        assert seen_limits[0] == 16
        assert seen_limits[1] == 32 and max(seen_limits) >= 128
        # huge rows pin the chunk at the byte budget
        knobs2 = Knobs().override(CLIENT_RANGE_CHUNK_ROWS=4,
                                  CLIENT_RANGE_CHUNK_BYTES=4000)
        c2 = _seed_cluster(knobs=knobs2, shards=1)
        c2.start()
        await _load(c2, {b"big%03d" % i: b"y" * 900 for i in range(40)})
        tr2 = Transaction(c2)
        limits2: list[int] = []
        g2 = c2.storage_for_key(b"big000")
        inner2 = g2.get_key_values_packed

        async def spy2(req):
            limits2.append(req.limit)
            return await inner2(req)

        g2.get_key_values_packed = spy2
        got2 = await tr2.get_range(b"big", b"bih")
        assert len(got2) == 40
        assert max(limits2) <= 4000 // 900, \
            "chunk outgrew the reply byte budget"
        await c2.stop()
        await cluster.stop()

    asyncio.run(main())
