"""Coordinator-set change — changeQuorum / MovableCoordinatedState.

Reference: REF:fdbclient/ManagementAPI.actor.cpp::changeQuorum +
REF:fdbserver/Coordination.actor.cpp (MovableCoordinatedState): the
cluster's coordinated state migrates to a new quorum with no split-brain
and no lost state, surviving a mover crash at every phase (VERDICT r4
item 3)."""

import asyncio

import pytest

from foundationdb_tpu.core.coordination import (
    CoordinatedState, Coordinator, CoordinatorsUnreachable,
    change_coordinators, complete_coordinator_move, elect_leader)
from foundationdb_tpu.runtime.errors import CoordinatorsChanged
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


def _addrs(start, n):
    return [[f"10.0.0.{start + i}", 4000] for i in range(n)]


def test_change_moves_state_and_retires_old():
    async def main():
        k = Knobs()
        old = [Coordinator(k) for _ in range(3)]
        new = [Coordinator(k) for _ in range(3)]
        cs = CoordinatedState(old, my_id=1, knobs=k)
        await cs.read()
        await cs.write({"epoch": 7, "seq": 3})

        await change_coordinators(old, new, _addrs(10, 3), k, mover_id=2)

        # the new quorum serves the preserved value
        cs2 = CoordinatedState(new, my_id=3, knobs=k)
        _, val = await cs2.read()
        assert val == {"epoch": 7, "seq": 3}
        # old coordinators refuse register traffic and forward clients
        for c in old:
            with pytest.raises(CoordinatorsChanged):
                await c.read((99, 99))
            assert await c.open_database() == {"__moved_to__": _addrs(10, 3)}
            assert await c.get_forward() == _addrs(10, 3)
        # elections work on the new set; the old set can elect nobody
        won = await elect_leader(new, 5, "a5", k)
        assert won == (5, "a5")
        with pytest.raises((CoordinatorsChanged, CoordinatorsUnreachable)):
            await elect_leader(old, 6, "a6", k)
    run_simulation(main())


def test_old_quorum_value_readers_learn_the_move():
    """A CC-style reader hitting the intent marker gets
    CoordinatorsChanged carrying the target set + preserved value."""
    async def main():
        k = Knobs()
        old = [Coordinator(k) for _ in range(3)]
        cs = CoordinatedState(old, my_id=1, knobs=k)
        await cs.read()
        await cs.write({"epoch": 1})
        # phase 1 only (mover crashed right after the intent write)
        mover = CoordinatedState(old, my_id=2, knobs=k)
        _, cur = await mover.read(raw=True)
        await mover.write({"__moving_to__": _addrs(10, 3), "__value__": cur})

        reader = CoordinatedState(old, my_id=3, knobs=k)
        with pytest.raises(CoordinatorsChanged) as ei:
            await reader.read()
        assert ei.value.moving_to == _addrs(10, 3)
        assert ei.value.inner_value == {"epoch": 1}

        # any party can complete the move from the intent
        new = [Coordinator(k) for _ in range(3)]
        await complete_coordinator_move(old, new, ei.value.moving_to,
                                        ei.value.inner_value, k, mover_id=4)
        _, val = await CoordinatedState(new, my_id=5, knobs=k).read()
        assert val == {"epoch": 1}
        assert all(c.moved_to for c in old)
    run_simulation(main())


def test_change_crash_after_copy_before_retire():
    """Mover dies between phase 2 and phase 3: re-running the completion
    (what a ClusterHost does) must converge with no value loss."""
    async def main():
        k = Knobs()
        old = [Coordinator(k) for _ in range(3)]
        new = [Coordinator(k) for _ in range(3)]
        cs = CoordinatedState(old, my_id=1, knobs=k)
        await cs.read()
        await cs.write({"epoch": 9})
        # phase 1 + 2, no retire
        mover = CoordinatedState(old, my_id=2, knobs=k)
        _, cur = await mover.read(raw=True)
        await mover.write({"__moving_to__": _addrs(10, 3), "__value__": cur})
        csn = CoordinatedState(new, my_id=2, knobs=k)
        await csn.read(raw=True)
        await csn.write({"epoch": 9})

        # completion is idempotent and must NOT clobber the copy
        await complete_coordinator_move(old, new, _addrs(10, 3),
                                        {"epoch": 9}, k, mover_id=6)
        _, val = await CoordinatedState(new, my_id=7, knobs=k).read()
        assert val == {"epoch": 9}
        assert all(c.moved_to for c in old)
    run_simulation(main())


def test_completion_skips_copy_when_forward_visible():
    """A LATE completer (raced by a finished move + a new-set writer)
    must not clobber newer state written into the new quorum."""
    async def main():
        k = Knobs()
        old = [Coordinator(k) for _ in range(3)]
        new = [Coordinator(k) for _ in range(3)]
        await change_coordinators(old, new, _addrs(10, 3), k, mover_id=1)
        # a new-set CC writes NEWER state
        csn = CoordinatedState(new, my_id=8, knobs=k)
        await csn.read()
        await csn.write({"epoch": 99})
        # the late completer replays with the STALE preserved value
        await complete_coordinator_move(old, new, _addrs(10, 3),
                                        {"epoch": 1}, k, mover_id=9)
        _, val = await CoordinatedState(new, my_id=10, knobs=k).read()
        assert val == {"epoch": 99}, "late completion clobbered new state"
    run_simulation(main())


def test_partial_retire_cannot_split_brain():
    """Only one old coordinator retired (mover died mid-phase-3): the
    old set must never again assemble an electing majority once any
    forward is visible and a host runs the follow-forward path."""
    async def main():
        k = Knobs()
        old = [Coordinator(k) for _ in range(3)]
        new = [Coordinator(k) for _ in range(3)]
        cs = CoordinatedState(old, my_id=1, knobs=k)
        await cs.read()
        await cs.write({"epoch": 2})
        mover = CoordinatedState(old, my_id=2, knobs=k)
        _, cur = await mover.read(raw=True)
        await mover.write({"__moving_to__": _addrs(10, 3), "__value__": cur})
        csn = CoordinatedState(new, my_id=2, knobs=k)
        await csn.read(raw=True)
        await csn.write(cur.get("__value__") if isinstance(cur, dict)
                        and "__moving_to__" in cur else cur)
        await old[0].move(_addrs(10, 3))    # phase 3 died after one

        # the un-retired old majority holds the intent marker, so an old
        # CC cannot recover (cstate.read raises) — and once ANY host sees
        # the forward it retires the rest (ClusterHost._follow_forward's
        # retire-then-repoint), after which old elections are impossible
        for c in old[1:]:
            await c.move(_addrs(10, 3))     # what _follow_forward does
        with pytest.raises((CoordinatorsChanged, CoordinatorsUnreachable)):
            await elect_leader(old, 7, "a7", k)
        won = await elect_leader(new, 7, "a7", k)
        assert won == (7, "a7")
    run_simulation(main())
