"""Columnar MVCC window (ISSUE 13) — segment lifecycle units plus the
randomized columnar-vs-legacy equivalence that makes ``columnar=False``
a real A/B twin: identical observable state under interleaved packed
applies, clears, atomics (through the storage role), compaction floors,
rollbacks, and a durable reopen.

The legality envelope matches the role contract: one floor consumer per
map (engine-less -> forget_before, engine-backed -> drop_before) and
rollback targets at or above the readable floor — the storage server
never rolls back below the MVCC window (the rollback target is always a
recovered version inside it).  Outside that envelope the legacy twin
itself has divergent quirks (see test_versioned_map's model notes)."""

import asyncio

import pytest

from foundationdb_tpu.core.data import MutationBatchBuilder
from foundationdb_tpu.runtime.rng import DeterministicRandom
from foundationdb_tpu.storage.versioned_map import (
    ColumnarVersionedMap, LegacyVersionedMap, OP_CLEAR, OP_SET,
    VersionedMap)


def _keys():
    return [b"k%02d" % i for i in range(14)]


def _check(col, leg, keys, version, ctx):
    assert col.keys() == leg.keys(), (ctx, col.keys(), leg.keys())
    for probe in range(max(col.oldest_version, 0), version + 2):
        for k in keys:
            assert col.get2(k, probe) == leg.get2(k, probe), \
                (ctx, k, probe, col.get2(k, probe), leg.get2(k, probe))
    assert col.get2_batch(keys, version) == \
        [leg.get2(k, version) for k in keys], ctx
    assert [col.get_latest(k) for k in keys] == \
        [leg.get_latest(k) for k in keys], ctx
    assert col.range_rows(b"", b"z", version) == \
        leg.range_rows(b"", b"z", version), ctx
    assert col.range_read(b"", b"z", version, limit=4, reverse=True) == \
        leg.range_read(b"", b"z", version, limit=4, reverse=True), ctx


@pytest.mark.parametrize("consumer", ["forget", "drop", "mixed_rollback"])
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_columnar_vs_legacy_randomized(seed, consumer):
    """The A/B equivalence: tiny seal budget so a 250-step workload
    exercises direct seals, tip seals, tiered compaction, folds,
    whole-segment drops, dead markers and rollback truncation — every
    observable (point reads at every live version, batched probes,
    forward/reverse ranges, keys(), get_latest) must match the legacy
    twin exactly."""
    import foundationdb_tpu.storage.versioned_map as vmod
    old_min = vmod._DIRECT_SEAL_MIN
    vmod._DIRECT_SEAL_MIN = 6       # exercise direct seals at toy sizes
    try:
        rng = DeterministicRandom(seed)
        col = ColumnarVersionedMap(seal_ops=9, seal_bytes=1 << 30,
                                   seal_versions=1 << 40)
        leg = LegacyVersionedMap()
        keys = _keys()
        version = 0
        for step in range(250):
            version += rng.random_int(1, 4)
            mode = rng.random_int(0, 10)
            if mode < 4:
                b = MutationBatchBuilder()
                for _ in range(rng.random_int(1, 12)):
                    b.add(0, keys[rng.random_int(0, len(keys))],
                          b"v%d" % rng.random_int(0, 1000))
                mb = b.finish()
                col.apply_packed(version, mb)
                leg.apply_packed(version, mb)
            elif mode < 6:
                b = MutationBatchBuilder()
                for _ in range(rng.random_int(1, 8)):
                    if rng.random_int(0, 4) == 0:
                        lo = rng.random_int(0, len(keys))
                        hi = rng.random_int(lo, len(keys) + 1)
                        b.add(1, keys[lo] if lo < len(keys) else b"z",
                              keys[hi] if hi < len(keys) else b"z")
                    else:
                        b.add(0, keys[rng.random_int(0, len(keys))],
                              b"v%d" % step)
                mb = b.finish()
                col.apply_packed(version, mb)
                leg.apply_packed(version, mb)
            elif mode < 8:
                ops = []
                v = version
                for _ in range(rng.random_int(1, 10)):
                    if rng.random_int(0, 4) == 0:
                        lo = rng.random_int(0, len(keys))
                        hi = rng.random_int(lo, len(keys) + 1)
                        ops.append((v, OP_CLEAR,
                                    keys[lo] if lo < len(keys) else b"z",
                                    keys[hi] if hi < len(keys) else b"z"))
                    else:
                        ops.append((v, OP_SET,
                                    keys[rng.random_int(0, len(keys))],
                                    b"v%d" % step))
                    v += rng.random_int(0, 2)
                version = v
                col.apply_batch(ops)
                leg.apply_batch(ops)
            elif mode == 8:
                t = version - rng.random_int(0, 10)
                if consumer == "forget" or (consumer == "mixed_rollback"
                                            and rng.random_int(0, 2)):
                    col.forget_before(t)
                    leg.forget_before(t)
                elif consumer == "drop":
                    col.drop_before(t)
                    leg.drop_before(t)
                else:
                    back = max(version - rng.random_int(0, 5),
                               col.oldest_version)
                    col.rollback_after(back)
                    leg.rollback_after(back)
                    version = max(version - 5, leg.latest_version)
            else:
                k = keys[rng.random_int(0, len(keys))]
                col.set(version, k, b"s%d" % step)
                leg.set(version, k, b"s%d" % step)
            _check(col, leg, keys, version, (seed, consumer, step))
        if consumer == "drop":
            col.drop_before(version)
            leg.drop_before(version)
        else:
            col.forget_before(version)
            leg.forget_before(version)
        _check(col, leg, keys, version, (seed, consumer, "final"))
    finally:
        vmod._DIRECT_SEAL_MIN = old_min


def _mb(*ops):
    b = MutationBatchBuilder()
    for t, p1, p2 in ops:
        b.add(t, p1, p2)
    return b.finish()


def test_direct_seal_zero_copy_and_budgets():
    """An all-SET packed batch above the direct-seal threshold becomes
    ONE segment whose value blob IS the batch blob (near-zero-copy);
    the tip seals on each of its three budgets."""
    vm = ColumnarVersionedMap(seal_ops=4, seal_bytes=1 << 30,
                              seal_versions=1 << 40)
    big = _mb(*[(0, b"d%04d" % i, b"v%d" % i) for i in range(600)])
    vm.apply_packed(10, big)
    assert len(vm._segments) == 1 and not vm._tip
    assert vm._segments[0].vblob is big.blob        # zero value copies
    assert vm.get2(b"d0001", 10) == (True, b"v1")
    assert vm.get2(b"d0001", 9) == (False, None)
    # ops budget: 4 tip entries seal
    vm.set(11, b"a", b"1")
    vm.set(12, b"b", b"2")
    vm.set(13, b"c", b"3")
    assert vm._tip
    vm.set(14, b"d", b"4")
    assert not vm._tip              # sealed on the ops budget
    # byte budget
    vm2 = ColumnarVersionedMap(seal_ops=1 << 30, seal_bytes=64,
                               seal_versions=1 << 40)
    vm2.set(1, b"x", b"y" * 100)
    assert not vm2._tip
    # version-span budget
    vm3 = ColumnarVersionedMap(seal_ops=1 << 30, seal_bytes=1 << 30,
                               seal_versions=50)
    vm3.set(1, b"x", b"y")
    assert vm3._tip
    vm3.set(60, b"x", b"z")
    assert not vm3._tip


def test_drop_before_retires_whole_segments():
    """drop_before is O(segments): layers wholly at-or-below the floor
    vanish outright, a straddler stays (its sub-floor entries turn
    invisible via the drop-floor read rule)."""
    vm = ColumnarVersionedMap(seal_ops=2, seal_bytes=1 << 30,
                              seal_versions=1 << 40)
    # a big old layer first so the tiered compaction leaves the small
    # later seals as their own segments (2 * small < big)
    vm.apply_packed(10, _mb(*[(0, b"s%04d" % i, b"v") for i in range(400)]))
    for i in range(4):
        vm.set(20 * (i + 1) + 10, b"t%d" % i, b"v")
        vm.set(20 * (i + 1) + 11, b"u%d" % i, b"v")
    assert len(vm._segments) >= 2
    before = [s for s in vm._segments]
    vm.drop_before(51)
    # layers wholly at-or-below the floor vanished; survivors are the
    # IDENTICAL objects (no rebuild — the O(segments) claim)
    assert all(s.max_version > 51 for s in vm._segments)
    assert all(any(s is b for b in before) for s in vm._segments)
    assert vm.get2(b"s0001", 60) == (False, None)   # dropped
    assert vm.get2(b"t0", 31) == (False, None)      # dropped
    assert vm.get2(b"t3", 91) == (True, b"v")       # still windowed
    # everything below: the window empties completely
    vm.drop_before(200)
    assert not vm._segments
    assert vm.keys() == []


def test_rollback_truncates_tip_and_suffix_segments():
    vm = ColumnarVersionedMap(seal_ops=2, seal_bytes=1 << 30,
                              seal_versions=1 << 40)
    vm.apply_packed(10, _mb(*[(0, b"a%d" % i, b"1") for i in range(300)]))
    vm.apply_packed(20, _mb(*[(0, b"b%d" % i, b"2") for i in range(300)]))
    vm.set(30, b"tip", b"3")
    vm.rollback_after(15)
    assert vm.latest_version == 15
    assert vm.get2(b"a1", 20) == (True, b"1")
    assert vm.get2(b"b1", 25) == (False, None)      # layer rolled back
    assert vm.get2(b"tip", 30) == (False, None)     # tip entry rolled back
    assert all(s.max_version <= 15 for s in vm._segments)


def test_rollback_below_drop_floor_serves_new_generation():
    """Rolling back below the drop floor (the legacy full-walk net —
    never legal from the role layer, kept as defense in depth) must
    void the stale floors: without that, every new-generation write at
    or below the old floor read found=False (engine-dropped) while the
    legacy twin served it — a rejoin silently losing writes until
    versions climbed back past the old floor."""
    vm = ColumnarVersionedMap(seal_ops=2, seal_bytes=1 << 30,
                              seal_versions=1 << 40)
    leg = LegacyVersionedMap()
    for m in (vm, leg):
        m.apply_batch([(40, OP_SET, b"a", b"1"),
                       (90, OP_SET, b"a", b"2")])
        m.drop_before(100)
        m.rollback_after(50)
        m.apply_batch([(60, OP_SET, b"a", b"3")])
    assert vm.get2(b"a", 60) == leg.get2(b"a", 60) == (True, b"3")
    assert vm.get2(b"a", 59) == leg.get2(b"a", 59)      # (False, None):
    #                                     the 40-entry was dropped to
    #                                     the engine before the rollback
    # the floors keep functioning for the new generation
    for m in (vm, leg):
        m.drop_before(60)
    assert vm.get2(b"a", 60) == leg.get2(b"a", 60) == (False, None)


def test_dead_marker_survives_reset_and_retires():
    """The temporal dead rule: a key whose lone tombstone the floor
    crossed stays dead (found=False) even after lingering older values
    would otherwise resurface, and the marker retires once no layer
    reaches that far back."""
    vm = ColumnarVersionedMap(seal_ops=2, seal_bytes=1 << 30,
                              seal_versions=1 << 40)
    leg = LegacyVersionedMap()
    for m in (vm, leg):
        m.apply_batch([(10, OP_SET, b"k", b"v1"),
                       (20, OP_CLEAR, b"k", b"k\x00")])
        m.forget_before(25)         # judged dead here
    assert vm._dead and vm.get2(b"k", 25) == leg.get2(b"k", 25) \
        == (False, None)
    for m in (vm, leg):
        m.apply_batch([(30, OP_SET, b"k", b"v2")])  # re-set after death
    for probe in (25, 29, 30):
        assert vm.get2(b"k", probe) == leg.get2(b"k", probe), probe
    for m in (vm, leg):
        m.forget_before(40)
    # the fold prunes the marked entries; the marker retires once every
    # layer's oldest entry is newer than it
    assert vm.get2(b"k", 40) == leg.get2(b"k", 40) == (True, b"v2")
    assert not vm._dead or all(v >= min(s.min_version
                                        for s in vm._segments)
                               for v in vm._dead.values())


def test_storage_server_ab_with_atomics_and_clears():
    """Role-level A/B: two engine-less storage servers fed the SAME
    mutation stream — plain sets, range clears, and atomics (which the
    role resolves against get_latest before the window sees them) —
    must serve byte-identical point/batched/range reads under both
    window implementations."""
    from foundationdb_tpu.core.data import (GetValuesRequest, KeyRange,
                                            Mutation, MutationType)
    from foundationdb_tpu.core.storage_server import StorageServer
    from foundationdb_tpu.core.tlog import TLog
    from foundationdb_tpu.runtime.knobs import Knobs

    async def main():
        rng = DeterministicRandom(11)
        servers = []
        for columnar in (True, False):
            k = Knobs().override(STORAGE_MVCC_COLUMNAR=columnar,
                                 STORAGE_MVCC_SEAL_OPS=16)
            ss = StorageServer(k, 1, KeyRange(b"", b"\xff"), TLog(k))
            servers.append(ss)
        keys = _keys()
        version = 0
        for step in range(120):
            version += rng.random_int(1, 3)
            muts = []
            for _ in range(rng.random_int(1, 6)):
                r = rng.random_int(0, 10)
                key = keys[rng.random_int(0, len(keys))]
                if r < 5:
                    muts.append(Mutation(MutationType.SET_VALUE, key,
                                         b"v%d" % step))
                elif r < 7:
                    lo = rng.random_int(0, len(keys))
                    hi = rng.random_int(lo, len(keys) + 1)
                    muts.append(Mutation(
                        MutationType.CLEAR_RANGE,
                        keys[lo] if lo < len(keys) else b"z",
                        keys[hi] if hi < len(keys) else b"z"))
                elif r < 9:
                    muts.append(Mutation(MutationType.ADD, key,
                                         (step % 250).to_bytes(1, "little")))
                else:
                    muts.append(Mutation(MutationType.BYTE_MAX, key,
                                         b"m%d" % step))
            b = MutationBatchBuilder()
            for m in muts:
                b.add(m.type.value, m.param1, m.param2)
            mb = b.finish()
            for ss in servers:
                ss._apply_batch([(version, mb)])
            if rng.random_int(0, 5) == 0:
                floor = version - rng.random_int(0, 8)
                for ss in servers:
                    ss.oldest_version = max(ss.oldest_version, floor)
                    ss.vmap.forget_before(floor)
            # byte-identical serving, in situ
            col, leg = servers
            for k2 in keys:
                assert await col.get_value(k2, version) == \
                    await leg.get_value(k2, version), (step, k2)
            req = GetValuesRequest.from_keys(keys, version)
            rc = await col.get_values(req)
            rl = await leg.get_values(req)
            assert [rc.unpack(i) for i in range(len(keys))] == \
                [rl.unpack(i) for i in range(len(keys))], step
            assert await col.get_key_values(b"", b"z", version) == \
                await leg.get_key_values(b"", b"z", version), step
        await asyncio.gather(*(s.stop() for s in servers))

    asyncio.run(main())


def test_durable_reopen_replays_into_columnar_window():
    """kv_store/WAL replay touch: a durable engine-backed server in
    columnar mode — applies drop below the floor into the engine, a
    reopen replays the WAL, and reads above/below the floor stay
    byte-identical to the legacy-window twin through the whole cycle."""
    from foundationdb_tpu.core.data import KeyRange
    from foundationdb_tpu.core.storage_server import StorageServer
    from foundationdb_tpu.core.tlog import TLog
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.storage.kv_store import MemoryKVStore

    async def main():
        results = {}
        for columnar in (True, False):
            fs = SimFileSystem()
            k = Knobs().override(STORAGE_MVCC_COLUMNAR=columnar,
                                 STORAGE_MVCC_SEAL_OPS=8)
            eng = await MemoryKVStore.open(fs, "s0")
            ss = StorageServer(k, 1, KeyRange(b"", b"\xff"), TLog(k),
                               engine=eng)
            b = MutationBatchBuilder()
            for i in range(400):
                b.add(0, b"r%04d" % i, b"v%d" % i)
            ss._apply_batch([(100, b.finish())])
            c = MutationBatchBuilder()
            c.add(1, b"r0000", b"r0100")
            ss._apply_batch([(200, c.finish())])
            # migrate <=150 into the engine (drops the window below)
            ops = await ss._dbuf.peek_through(150)
            await eng.commit(ops, {"durable_version": 150, "tag": 1,
                                   "shard": (b"", b"\xff"), "feeds": []})
            await ss._dbuf.pop_through(150)
            ss.durable_version = 150
            ss.oldest_version = 150
            ss.vmap.drop_before(150)
            rows_live = await ss.get_key_values(b"", b"z", 200)
            rows_old = await ss.get_key_values(b"", b"z", 150)
            await eng.close()
            # reopen: WAL replay rebuilds the engine byte-identically
            eng2 = await MemoryKVStore.open(fs, "s0")
            assert eng2.meta["durable_version"] == 150
            assert eng2.get(b"r0001") == b"v1"
            results[columnar] = (rows_live, rows_old)
            await eng2.close()
            await ss.stop()
        assert results[True] == results[False]
        rows_live, rows_old = results[True]
        assert len(rows_live[0]) == 300     # the clear landed
        assert len(rows_old[0]) == 400      # history below still serves

    asyncio.run(main())


def test_factory_and_stats_surfaces():
    assert isinstance(VersionedMap(), ColumnarVersionedMap)
    assert isinstance(VersionedMap(columnar=False), LegacyVersionedMap)
    vm = VersionedMap(seal_ops=4)
    vm.apply_packed(5, _mb(*[(0, b"k%d" % i, b"v") for i in range(8)]))
    st = vm.index_stats()
    for field in ("keys", "merges", "merge_ms", "segments", "entries",
                  "resident_bytes", "seals"):
        assert field in st, field
    assert st["columnar"] is True
    assert st["entries"] == 8
    assert vm.nbytes > 0
