"""LogSystem: tag replication, cursor failover, generation rollover.

The recovery contract (REF:fdbserver/TagPartitionedLogSystem.actor.cpp):
acked pushes survive any single TLog death because every tag is hosted on
LOG_REPLICATION logs; a locked generation serves history up to its end
version and clamps everything above it; cursors roll across generations.
"""

import asyncio

import pytest

from foundationdb_tpu.core.data import Mutation, MutationType
from foundationdb_tpu.core.log_system import LogGeneration, LogSystem
from foundationdb_tpu.core.tlog import TLog, TLogPushRequest
from foundationdb_tpu.runtime.errors import LogDataLoss, TLogStopped
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


def _m(i):
    return Mutation(MutationType.SET_VALUE, b"k%d" % i, b"v%d" % i)


def _ls(n_logs=3, replication=2, v0=0):
    k = Knobs()
    tlogs = [TLog(k, v0) for _ in range(n_logs)]
    return LogSystem([LogGeneration(epoch=0, begin_version=v0, tlogs=tlogs,
                                    replication=replication)]), tlogs


def test_push_replicates_each_tag():
    async def main():
        ls, tlogs = _ls(n_logs=3, replication=2)
        await ls.push(0, 10, {0: [_m(0)], 1: [_m(1)]})
        hosts0 = ls.current.logs_for_tag(0)
        for i, t in enumerate(tlogs):
            has = 0 in t._log
            assert has == (i in hosts0)
    run_simulation(main())


def test_cursor_fails_over_to_live_replica():
    async def main():
        ls, tlogs = _ls(n_logs=3, replication=2)
        for v in range(1, 6):
            await ls.push(v - 1, v, {0: [_m(v)]})
        # primary replica of tag 0 dies; its data lives on the second host
        dead = ls.current.logs_for_tag(0)[0]
        ls.mark_dead(0, dead)
        cur = ls.cursor(0, 1)
        reply = await cur.next()
        assert [v for v, _ in reply.entries] == [1, 2, 3, 4, 5]
    run_simulation(main())


def test_all_replicas_dead_is_data_loss():
    async def main():
        ls, tlogs = _ls(n_logs=2, replication=2)
        await ls.push(0, 1, {0: [_m(1)]})
        ls.mark_dead(0, 0)
        ls.mark_dead(0, 1)
        with pytest.raises(LogDataLoss):
            await ls.cursor(0, 1).next()
    run_simulation(main())


def test_locked_log_rejects_push_and_reports_tip():
    async def main():
        ls, tlogs = _ls(n_logs=2, replication=2)
        await ls.push(0, 5, {0: [_m(5)]})
        tip = await tlogs[0].lock()
        assert tip == 5
        with pytest.raises(TLogStopped):
            await tlogs[0].push(TLogPushRequest(5, 6, {}))
    run_simulation(main())


def test_generation_rollover_with_clamp():
    """History above a locked generation's end is never served; the cursor
    rolls into the new generation exactly at end+1."""
    async def main():
        ls, old_logs = _ls(n_logs=2, replication=2)
        await ls.push(0, 1, {0: [_m(1)]})
        await ls.push(1, 2, {0: [_m(2)]})
        # a half-pushed batch: only log 0 got version 3 (no ack happened)
        await old_logs[0].push(TLogPushRequest(2, 3, {0: [_m(3)]}))

        # recovery: lock survivors, recovery_version = min tips = 2
        tips = [await t.lock() for t in old_logs]
        rv = min(tips)
        assert rv == 2
        ls.current.end_version = rv
        k = Knobs()
        new_logs = [TLog(k, rv) for _ in range(2)]
        ls.generations.append(LogGeneration(
            epoch=1, begin_version=rv, tlogs=new_logs, replication=2))

        # new generation accepts pushes continuing the chain from rv
        await ls.push(rv, rv + 7, {0: [_m(99)]})

        cur = ls.cursor(0, 1)
        seen = []
        while True:
            reply = await cur.next()
            seen.extend(v for v, _ in reply.entries)
            if rv + 7 in seen:
                break
        # version 3 (unacked, clamped) must never appear
        assert seen == [1, 2, rv + 7]
    run_simulation(main())


def test_cluster_survives_replica_mark_dead():
    """End-to-end: commits applied via the second replica when the first
    host of a storage tag is marked dead after acks."""
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.core.cluster import Cluster, ClusterConfig

    async def main():
        cluster = Cluster(ClusterConfig(logs=3, storage_servers=2))
        async with cluster:
            db = Database(cluster)
            for i in range(10):
                await db.set(b"a%d" % i, b"x")
            # kill the primary replica log of tag 0 (reads keep working
            # because pulls fail over; acked data is on the other host)
            dead = cluster.log_system.current.logs_for_tag(0)[0]
            cluster.log_system.mark_dead(0, dead)
            for i in range(10):
                assert await db.get(b"a%d" % i) == b"x"
    run_simulation(main(), seed=5)
