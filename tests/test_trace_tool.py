"""End-to-end distributed tracing: wire propagation + trace_tool (ISSUE 2).

The tier-1 acceptance test: a small seeded multi-role sim writes its
trace JSONL, tools/trace_tool.py reconstructs per-trace cross-role
timelines from the file alone, and at least one sampled transaction
yields a COMPLETE client→GRV→commit→resolve→TLog→storage chain.
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import trace_tool

from foundationdb_tpu.runtime import span as span_mod
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.runtime.trace import (Severity, TraceLog,
                                            get_trace_log, set_trace_log)


# --- unit: the envelope over the wire ---

def test_span_envelope_wire_roundtrip():
    from foundationdb_tpu.rpc.wire import decode, encode
    env = span_mod.SpanEnvelope(0x2a, 7, 3, [b"payload", 1, None])
    out = decode(encode(env))
    assert isinstance(out, span_mod.SpanEnvelope)
    assert (out.trace_id, out.span_id, out.parent_id) == (0x2a, 7, 3)
    assert out.payload == [b"payload", 1, None]


def test_dispatcher_reactivates_span_context():
    """A sampled payload wrapped by the transport must surface as
    current_span() inside the handler — the receive half of wire
    propagation — and be invisible to unsampled requests."""
    from foundationdb_tpu.rpc.transport import (NetworkAddress,
                                                RequestDispatcher)

    async def main():
        seen = []
        disp = RequestDispatcher()

        async def handler(payload):
            seen.append((payload, span_mod.current_span()))
            return payload
        tok = disp.register(handler)

        ctx = span_mod.SpanContext(9, 2, 1, True)
        env = span_mod.SpanEnvelope(ctx.trace_id, ctx.span_id,
                                    ctx.parent_id, "hello")
        ok, reply = await disp.dispatch(tok, env)
        assert ok and reply == "hello"
        ok, reply = await disp.dispatch(tok, "bare")
        assert ok and reply == "bare"
        assert seen[0][0] == "hello"
        assert seen[0][1] is not None and seen[0][1].trace_id == 9
        assert seen[1][1] is None      # context did not leak across calls
    asyncio.run(main())


def test_transport_attach_only_wraps_sampled():
    from foundationdb_tpu.rpc.transport import Transport
    assert Transport.attach_span("x") == "x"    # no active span: untouched
    tok = span_mod.activate(span_mod.SpanContext(1, 2, 0, True))
    try:
        wrapped = Transport.attach_span("x")
    finally:
        span_mod.deactivate(tok)
    assert isinstance(wrapped, span_mod.SpanEnvelope)
    assert wrapped.payload == "x" and wrapped.trace_id == 1


# --- unit: the analyzer over synthetic events ---

def _ev(t, type_, trace, role, loc, **kw):
    d = {"Time": t, "Severity": 10, "Type": type_, "TraceID": trace,
         "SpanID": 1, "ParentID": 0, "Role": role, "Location": loc}
    d.update(kw)
    return d


def test_trace_tool_reconstruct_and_rank():
    tid = "%016x" % 5
    events = [
        _ev(1.000, "TransactionDebug", tid, "client",
            "NativeAPI.getReadVersion.Before"),
        _ev(1.002, "TransactionDebug", tid, "GrvProxy",
            "GrvProxyServer.reply", Version=100),
        _ev(1.004, "CommitDebug", tid, "CommitProxy",
            "CommitProxyServer.commitBatch.GotCommitVersion", Version=120),
        _ev(1.006, "CommitDebug", tid, "Resolver",
            "Resolver.resolveBatch.After", Version=120),
        _ev(1.009, "CommitDebug", tid, "TLog", "TLog.push.After",
            Version=120),
        _ev(1.010, "CommitDebug", tid, "client", "NativeAPI.commit.After",
            Version=120),
        # a second, faster trace
        _ev(2.000, "TransactionDebug", "%016x" % 6, "client",
            "NativeAPI.getReadVersion.Before"),
        _ev(2.001, "TransactionDebug", "%016x" % 6, "client",
            "NativeAPI.getReadVersion.After", Version=130),
        # a conflicted trace: the proxy's Committed=false verdict must
        # win over the client's LATER generic commit.Error event
        _ev(3.000, "CommitDebug", "%016x" % 7, "CommitProxy",
            "CommitProxyServer.commitBatch.Reply", Version=140,
            Committed=False),
        _ev(3.001, "CommitDebug", "%016x" % 7, "client",
            "NativeAPI.commit.Error", Error="NotCommitted"),
        # async storage apply covering trace 5's commit version
        {"Time": 1.2, "Severity": 5, "Type": "StorageApplyDebug", "Tag": 0,
         "MinVersion": 110, "MaxVersion": 125, "Mutations": 3,
         "DurationMs": 0.4},
        # a stall overlapping trace 5
        {"Time": 1.008, "Severity": 30, "Type": "SlowTask",
         "DurationMs": 5.0},
    ]
    report = trace_tool.analyze(events, top=5)
    assert report["traces"] == 3
    assert report["outcomes"].get("conflict") == 1
    assert report["slowest"][0]["trace_id"] == tid
    assert report["slowest"][0]["outcome"] == "committed"
    assert report["slowest"][0]["commit_version"] == 120
    assert report["slowest"][0]["slow_tasks"] == 1
    assert report["slow_task_correlated"] == 1
    # the storage apply joined by version range completes the chain
    traces = trace_tool.reconstruct(events)
    trace_tool.join_storage_applies(traces, events)
    assert traces[tid]["storage_applies"][0]["Tag"] == 0
    assert trace_tool.is_complete(traces[tid])
    # segments got stats
    assert any(row["n"] for row in report["span_stats"].values())


def test_trace_tool_rolled_paths(tmp_path):
    base = os.path.join(str(tmp_path), "t.jsonl")
    for name in ("t.jsonl", "t.jsonl.1", "t.jsonl.2", "t.jsonl.bak"):
        with open(os.path.join(str(tmp_path), name), "w") as f:
            f.write('{"Type": "X", "Time": 0}\n')
    paths = trace_tool.rolled_paths(base)
    assert paths == [base + ".1", base + ".2", base]
    assert len(trace_tool.load_events(paths)) == 3


# --- the tier-1 acceptance sim (ISSUE 2 CI satellite) ---

def test_sim_trace_reconstructs_cross_role_timeline(tmp_path):
    """Seeded multi-role sim → trace JSONL → trace_tool: at least one
    sampled transaction must reconstruct into a complete
    client→GRV→commit→resolve→TLog→storage timeline, and the status
    rollup must surface the span counters."""
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.core.status import cluster_status
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    path = os.path.join(str(tmp_path), "trace.jsonl")
    # DEBUG severity captures the StorageApplyDebug correlation events;
    # sample rate 1.0 makes the (deterministic, counter-based) sampler
    # fire on every transaction
    log = TraceLog(path=path, min_severity=Severity.DEBUG)
    prev_log = get_trace_log()
    set_trace_log(log)
    span_mod.reset_totals()
    # DURABLE since ISSUE 12: the cluster.degraded rollup needs
    # disk-bearing roles (engines + durable TLogs publish disk health),
    # and the small MVCC window keeps durability ticks flowing so the
    # deliberately slowed disk accumulates measurable latency
    knobs = Knobs().override(CLIENT_LATENCY_PROBE_SAMPLE=1.0,
                             STORAGE_VERSION_WINDOW=100_000,
                             STORAGE_DURABILITY_LAG=0.1,
                             DISK_DEGRADED_LATENCY_MS=5.0)

    async def main():
        from foundationdb_tpu.runtime.rng import DeterministicRandom
        sim = SimulatedCluster(knobs, n_machines=5, durable_storage=True,
                               spec=ClusterConfigSpec(min_workers=5,
                                                      replication=2))
        await sim.start()
        state = await sim.wait_epoch(1)
        db = await sim.database()
        # deliberately slow ONE storage machine's disk (the gray
        # failure): every op stalls 20ms, far past the 5ms threshold
        storage_ips = {s["worker"][0] for s in state["storage"]}
        slow = next(m for m in sim.machines if m.ip in storage_ips)
        slow.fault_profile.arm(DeterministicRandom(9),
                               stall_floor_s=0.02)
        for i in range(4):
            async def body(tr, i=i):
                await tr.get(b"trace-k%d" % i)     # storage read span
                tr.set(b"trace-k%d" % i, b"v%d" % i)
            await db.run(body)
        # let the storage pull loops apply the commits (the async half
        # the analyzer joins by version range) and the durability ticks
        # hit the slowed disk
        await asyncio.sleep(1.5)
        ct = sim.client_transport()
        doc = await cluster_status(sim.knobs, ct, sim.coordinator_stubs(ct))
        await sim.stop()
        return doc, slow.ip

    doc, slow_ip = run_simulation(main(), seed=1234)
    set_trace_log(prev_log)
    log.close()

    events = trace_tool.load_events(trace_tool.rolled_paths(path))
    traces = trace_tool.reconstruct(events)
    trace_tool.join_storage_applies(traces, events)
    assert traces, "no sampled transaction produced span events"
    complete = {tid: tr for tid, tr in traces.items()
                if trace_tool.is_complete(tr)}
    assert complete, (
        "no complete client→GRV→commit→resolve→TLog→storage timeline; "
        "roles seen: %r" % {tid: tr["roles"] for tid, tr in traces.items()})
    # the report runs end-to-end off the file alone
    report = trace_tool.analyze(events)
    assert report["complete"] >= 1
    assert report["span_stats"]
    committed = [tr for tr in complete.values()
                 if tr["outcome"] == "committed"]
    assert committed and committed[0]["commit_version"] is not None
    # span counters surfaced through role metrics into the status rollup
    tracing = doc["cluster"]["tracing"]
    assert tracing["spans_emitted"] > 0
    assert tracing["sampled_txns"] >= 4
    # device-commit-pipeline rollup (ISSUE 6): the resolvers ran the
    # pipeline path and their queue/dispatch counters reached status
    rd = doc["cluster"]["resolver_device"]
    assert rd["pipelined_resolvers"] >= 1
    assert rd["dispatches"] >= 1
    assert rd["enqueued"] >= rd["dispatches"]
    assert rd["poisoned"] == 0
    assert "device_reads" in doc["cluster"]
    # shard-heat rollup (ISSUE 7): every storage role reports decayed
    # heat rates, and the writes above must register on some shard
    sh = doc["cluster"]["shard_heat"]
    assert sh["tracked_servers"] >= 1
    assert len(sh["top_shards"]) >= 1
    assert sh["top_shards"][0]["rw_per_sec"] > 0.0, sh["top_shards"]
    assert sh["top_shards"][0]["rw_per_sec"] >= \
        sh["top_shards"][-1]["rw_per_sec"]
    assert sh["heat_throttled_tags"] == {}      # untagged workload
    assert sh["heat_throttle_activations"] == 0
    # hot-move rollup: present and all-zero (no DD in this sim)
    hm = doc["cluster"]["hot_moves"]
    assert hm == {"splits": 0, "live_moves": 0, "heat_splits": 0,
                  "heat_moves": 0, "last_heat_rw_per_sec": 0.0}
    # cluster.degraded rollup (ISSUE 12): the deliberately slowed disk
    # shows up with its latency and degraded flag; healthy machines do
    # not — the gray failure is observable from `status` alone
    deg = doc["cluster"]["degraded"]
    assert deg["count"] >= 1, deg
    slow_entry = next(e for e in deg["disks"] if e["ip"] == slow_ip)
    assert slow_entry["degraded"], deg
    assert slow_entry["latency_ms"] >= 5.0, slow_entry
    assert all(not e["degraded"] for e in deg["disks"]
               if e["ip"] != slow_ip), deg


# --- backup + fetchKeys span threading (ISSUE 8 satellite; PR 2 (c)) ---

def test_backup_restore_and_fetchkeys_spans_pair(tmp_path):
    """A slow restore must be reconstructable from the trace file alone:
    the backup agent's snapshot/log writers, the restore chunks, DD's
    relocations and the move destinations' fetchKeys all emit PAIRED
    Before/After(.Error) span events trace_tool can group.  The sim
    forces a live DD split under writes while a whole-db backup tails,
    then restores to a version on a fresh in-process cluster."""
    from foundationdb_tpu.backup.agent import BackupAgent
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    path = os.path.join(str(tmp_path), "trace.jsonl")
    log = TraceLog(path=path)
    prev = get_trace_log()
    set_trace_log(log)
    span_mod.reset_totals()
    knobs = Knobs().override(SERVER_SPAN_SAMPLE=1.0, DD_ENABLED=True,
                             DD_INTERVAL=1.0, DD_SHARD_SPLIT_BYTES=6_000,
                             BACKUP_LOG_FLUSH_INTERVAL=0.1)

    async def main():
        sim = SimulatedCluster(knobs, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        n_shards = len(state1["shard_teams"])
        db = await sim.database()
        fs = SimFileSystem()
        agent = BackupAgent(db, fs, "bk-spans")
        await agent.start_continuous()
        committed = []

        async def write(i: int) -> None:
            tr = db.create_transaction()
            while True:
                try:
                    tr.set(b"sp%05d" % i, b"v" * 60)
                    committed.append(await tr.commit())
                    break
                except BaseException as e:
                    from foundationdb_tpu.runtime.errors import \
                        CommitUnknownResult
                    if isinstance(e, CommitUnknownResult):
                        break
                    await tr.on_error(e)

        for i in range(40):
            await write(i)
        await agent.backup()     # a non-empty snapshot: pages emit spans
        for i in range(40, 120):
            await write(i)
        # wait for DD to split the grown shard (fetchKeys + relocate)
        await sim.wait_state(lambda s: s.get("seq", 0) > 0
                             and len(s["shard_teams"]) > n_shards)
        vt = max(committed)
        deadline = asyncio.get_running_loop().time() + 120
        while agent.log_through < vt:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.25)
        await agent.stop_continuous()
        async with Cluster(ClusterConfig(), Knobs().override(
                SERVER_SPAN_SAMPLE=1.0)) as fresh:
            fdb = Database(fresh)
            agent2 = BackupAgent(fdb, fs, "bk-spans")
            await agent2.restore(to_version=vt)
        await sim.stop()

    run_simulation(main(), seed=23)
    set_trace_log(prev)
    log.close()

    events = trace_tool.load_events(trace_tool.rolled_paths(path))

    def pairing(prefix: str) -> tuple[int, int]:
        fam = [e for e in events
               if str(e.get("Location", "")).startswith(prefix)]
        befores = sum(1 for e in fam
                      if e["Location"].endswith(".Before"))
        closes = sum(1 for e in fam
                     if e["Location"].endswith((".After", ".Error")))
        return befores, closes

    for prefix in ("BackupAgent.snapshotFile", "BackupAgent.logFile",
                   "BackupAgent.restore", "StorageServer.fetchKeys",
                   "DataDistributor.relocate"):
        b, c = pairing(prefix)
        assert b > 0, f"no {prefix} span events reached the trace file"
        assert b == c, f"unpaired {prefix} events: {b} Before vs {c} closes"
    # every span event carries a trace id and the analyzer groups them
    backup_events = [e for e in events
                     if str(e.get("Location", "")).startswith("BackupAgent.")]
    assert all(e.get("TraceID") for e in backup_events)
    assert trace_tool.reconstruct(backup_events)
