"""Mutual TLS on the TCP transport: encrypted cluster + rejected strangers."""

from __future__ import annotations

import asyncio
import os
import signal
import subprocess
import sys

import pytest

from foundationdb_tpu.core.cluster_file import ClusterFile
from foundationdb_tpu.rpc.transport import NetworkAddress

from test_server import free_ports

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_certs(d):
    """One CA + one leaf cert shared by the cluster (openssl CLI)."""
    def run(*args):
        subprocess.run(["openssl", *args], check=True, capture_output=True)
    ca_key, ca_crt = d / "ca.key", d / "ca.crt"
    run("req", "-x509", "-newkey", "rsa:2048", "-nodes", "-keyout", str(ca_key),
        "-out", str(ca_crt), "-days", "1", "-subj", "/CN=fdbtpu-test-ca")
    key, csr, crt = d / "node.key", d / "node.csr", d / "node.crt"
    run("req", "-newkey", "rsa:2048", "-nodes", "-keyout", str(key),
        "-out", str(csr), "-subj", "/CN=fdbtpu-node")
    run("x509", "-req", "-in", str(csr), "-CA", str(ca_crt), "-CAkey",
        str(ca_key), "-CAcreateserial", "-out", str(crt), "-days", "1")
    return str(crt), str(key), str(ca_crt)


def test_tls_cluster_serves_and_rejects_plaintext(tmp_path):
    crt, key, ca = make_certs(tmp_path)
    ports = free_ports(3)
    cf = ClusterFile("tls", "t1",
                     [NetworkAddress("127.0.0.1", p) for p in ports])
    cf_path = tmp_path / "fdb.cluster"
    cf.save(str(cf_path))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = [subprocess.Popen(
        [sys.executable, "-m", "foundationdb_tpu.server",
         "-C", str(cf_path), "-l", f"127.0.0.1:{p}",
         "--spec", "min_workers=3",
         "--tls-cert", crt, "--tls-key", key, "--tls-ca", ca],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for p in ports]
    try:
        async def drive():
            from foundationdb_tpu.cli import open_cli
            from foundationdb_tpu.rpc.tcp_transport import TlsConfig
            from foundationdb_tpu.runtime.knobs import Knobs
            tls = TlsConfig(crt, key, ca)
            cli = await open_cli(str(cf_path), Knobs(), timeout=90.0, tls=tls)
            assert await cli.execute("set sk sv") == "Committed"
            assert await cli.execute("get sk") == "`sk' is `sv'"

            # a client WITHOUT certificates must be refused
            from foundationdb_tpu.core.cluster_client import fetch_cluster_state
            from foundationdb_tpu.rpc.stubs import CoordinatorClient
            from foundationdb_tpu.rpc.tcp_transport import TcpTransport
            from foundationdb_tpu.rpc.transport import WLTOKEN_COORDINATOR
            from foundationdb_tpu.runtime.errors import FdbError
            t = TcpTransport(NetworkAddress("127.0.0.1", 0))   # no TLS
            coords = [CoordinatorClient(t, a, WLTOKEN_COORDINATOR)
                      for a in cf.coordinators]
            # either the handshake failure surfaces as a connection
            # error or the stranger simply never gets an answer
            with pytest.raises((FdbError, asyncio.TimeoutError)):
                await asyncio.wait_for(fetch_cluster_state(coords), 15)

        asyncio.run(asyncio.wait_for(drive(), timeout=300.0))
    finally:
        for pr in procs:
            pr.send_signal(signal.SIGTERM)
        for pr in procs:
            try:
                pr.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                pr.kill()
                pr.communicate()
