"""Layer consistency under chaos (ISSUE 19 acceptance).

The scrubber's credibility test, applied to DERIVED state: with machine
attrition, swizzle reboots, random clogging, hostile disks and BUGGIFY
all firing while the zipf read tier and the index churn workloads
drive a live layer stack (feed consumer + async secondary index +
read-through cache + watches), the layer consistency checker must
report ZERO divergences — every refusal is a refusal, never a verdict
— and the zipf tier's inline staleness probes must find zero stale
cached reads.  Then a single index row corrupted OUTSIDE the
maintenance path (a direct write into the index subspace, which the
feed applier ignores because it is outside the primary range) must be
caught by the very next checker pass and named key-exactly in a
severity-40 ``LayerMismatch``.
"""

from __future__ import annotations

import asyncio

import pytest

from foundationdb_tpu.client.subspace import Subspace
from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
from foundationdb_tpu.layers import (LayerConsistencyChecker,
                                     LayerFeedConsumer, ReadThroughCache,
                                     SecondaryIndex, WatchRegistry)
from foundationdb_tpu.runtime.buggify import enable_buggify
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.runtime.trace import (Severity, TraceLog,
                                            get_trace_log, set_trace_log)
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

LAYER_KNOBS = dict(LAYER_FEED_POLL_INTERVAL=0.05,
                   LAYER_PROGRESS_INTERVAL=1.0)

WAIT_S = 240.0  # virtual-clock ceiling per wait phase


@pytest.fixture(autouse=True)
def _buggify_off_after():
    yield
    enable_buggify(False)


@pytest.fixture()
def captured_trace():
    events: list[dict] = []
    sink = TraceLog(min_severity=Severity.INFO)
    sink.sink = events.append
    prev = get_trace_log()
    set_trace_log(sink)
    yield events
    set_trace_log(prev)


async def _wait_for(pred, what: str, ceiling_s: float = WAIT_S):
    for _ in range(int(ceiling_s / 0.25)):
        if pred():
            return
        await asyncio.sleep(0.25)
    raise AssertionError(f"{what} did not happen within "
                         f"{ceiling_s:.0f} virtual seconds")


def test_layer_checker_zero_divergences_under_chaos_then_canary(
        captured_trace):
    from foundationdb_tpu.workloads.workload import run_workloads_on

    events = captured_trace
    enable_buggify(True)
    canary = {"key": b""}

    async def main() -> dict:
        knobs = Knobs().override(DD_ENABLED=True,
                                 BUGGIFY_ENABLED=True,
                                 STORAGE_DURABILITY_LAG=0.1,
                                 **LAYER_KNOBS)
        sim = SimulatedCluster(knobs, n_machines=7, durable_storage=True,
                               spec=ClusterConfigSpec(min_workers=7,
                                                      replication=2))
        await sim.start()
        await asyncio.wait_for(sim.wait_epoch(1), 120)
        db = await sim.database()

        # the layer stack the workloads drive (all on ONE whole-db feed)
        consumer = LayerFeedConsumer(db, name="chaos")
        index = SecondaryIndex(db, Subspace(raw_prefix=b"idx/"),
                               primary_begin=b"churn/",
                               primary_end=b"churn0",
                               mode="async", consumer=consumer)
        cache = ReadThroughCache(db, consumer, capacity=1024)
        watches = WatchRegistry(db, consumer)
        checker = LayerConsistencyChecker(db, index=index, cache=cache,
                                          watches=watches)
        await consumer.start()
        await index.start_async()

        # a few standing watches on churn keys: the churn workload's
        # writes fire some; the checker audits whatever still pends
        watch_futs = [await watches.watch(b"churn/%08d" % i)
                      for i in (0, 3, 7, 250)]

        specs = [
            {"testName": "LayerReadTier", "cache": cache,
             "nodeCount": 200, "opsPerClient": 120,
             "writeFraction": 0.1},
            {"testName": "LayerIndexChurn", "index": index,
             "nodeCount": 120, "opsPerClient": 60},
            {"testName": "MachineAttrition", "sim": sim,
             "machinesToKill": 1},
            {"testName": "Swizzle", "sim": sim, "rounds": 1,
             "secondsBefore": 5.0},
            {"testName": "RandomClogging", "sim": sim,
             "testDuration": 6.0},
            {"testName": "DiskFault", "sim": sim, "testDuration": 8.0},
        ]
        results = await run_workloads_on(db, specs, client_count=2)

        # chaos settled: the feed must catch back up (reconnecting
        # across however many recoveries happened) and a checker pass
        # over every layer must come back with an actual verdict
        tr = db.create_transaction()
        tr.lock_aware = True
        tip = await tr.get_read_version()
        tr.reset()
        await consumer.wait_frontier(tip, timeout=WAIT_S)
        verdict = None
        for _ in range(40):
            verdict = await checker.check()
            if (not verdict["index"]["refused"]
                    and not verdict["cache"]["refused"]
                    and not verdict["watches"]["refused"]):
                break
            await asyncio.sleep(1.0)
        assert verdict is not None and verdict["divergences"] == 0, verdict
        assert not verdict["index"]["refused"], \
            "the index checkpoint never stabilized after chaos"
        results["_verdict"] = verdict
        results["_watches_fired"] = sum(
            1 for f in watch_futs if f.done())

        # the canary: rot ONE index row behind the maintainer's back —
        # a direct write into the index subspace, invisible to the
        # applier (outside the primary range) — and demand the next
        # pass names it exactly
        canary["key"] = index.row_key(b"CANARY", b"churn/99999999")

        async def rot(tr):
            tr.set(canary["key"], b"")
        await db.run(rot)
        caught = await checker.check()
        assert caught["index"]["divergences"] == 1, caught
        await consumer.stop(destroy=True)
        await sim.stop()
        return results

    results = run_simulation(main(), seed=7119)

    # the zipf tier's own inline proof: every cached read it served was
    # byte-compared against an authoritative read pinned at the exact
    # version the cache claimed — zero stale, summed over all clients
    assert results["LayerReadTier"]["stale_reads"] == 0
    assert results["LayerReadTier"]["reads"] > 0
    assert results["LayerIndexChurn"]["committed"] > 0
    assert results["MachineAttrition"]["machines_killed"] >= 1

    # zero divergences before the canary, key-exact catch after: the
    # only LayerMismatch in the whole trace is the canary row
    hits = [e for e in events if e.get("Type") == "LayerMismatch"]
    assert [e.get("Key") for e in hits] == [canary["key"].hex()], (
        f"expected exactly the canary row, got "
        f"{[(e.get('Layer'), e.get('Key')) for e in hits]}")
    assert hits[0].get("Severity") == 40
    assert hits[0].get("Layer") == "index"
    assert hits[0].get("Expected") == "<missing>"
