"""Multi-region replication: satellites, remote replicas, region failover.

Reference: REF:fdbserver/TagPartitionedLogSystem.actor.cpp (satellite
TLogs), REF:fdbclient/DatabaseConfiguration.cpp (regions config) — a
two-region cluster commits synchronously to the primary DC's logs AND a
satellite DC's all-tag logs, while a remote region holds an async
storage replica per shard.  Losing the whole primary DC must lose no
acked commit: recovery locks the satellites, the remote region becomes
primary, and its replicas serve everything that was ever acked.
"""

from __future__ import annotations

import asyncio

from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

# machine layout: coordinators (first 3) span all three DCs so losing
# any one DC keeps a 2/3 quorum
DCIDS = ["dc1", "sat1", "dc2", "dc1", "dc2", "dc2"]
REGIONS = [{"id": "dc1", "priority": 1, "satellite": "sat1",
            "satellite_logs": 1},
           {"id": "dc2", "priority": 0}]


def _regions_spec(**kw) -> ClusterConfigSpec:
    return ClusterConfigSpec(min_workers=6, logs=2, replication=1,
                             regions=[dict(r) for r in REGIONS], **kw)


def _dc_of_addr(addr, sim) -> str:
    ip = addr[0] if isinstance(addr, (list, tuple)) else addr.ip
    idx = int(ip.split(".")[-1]) - 1
    return DCIDS[idx]


def test_region_aware_recruitment():
    """Txn subsystem in the primary DC, satellites in the satellite DC,
    each shard team spanning primary + remote."""
    async def main():
        sim = SimulatedCluster(Knobs(), n_machines=6, dcids=DCIDS,
                               spec=_regions_spec())
        await sim.start()
        state = await sim.wait_epoch(1)
        gen = state["log_cfg"][-1]
        assert all(_dc_of_addr(a, sim) == "dc1" for a in gen["tlogs"])
        assert len(gen["satellites"]) == 1
        assert all(_dc_of_addr(a, sim) == "sat1" for a in gen["satellites"])
        assert _dc_of_addr(state["sequencer"]["addr"], sim) == "dc1"
        for p in state["commit_proxies"] + state["grv_proxies"]:
            assert _dc_of_addr(p["addr"], sim) == "dc1"
        # every shard team: one dc1 replica + one dc2 replica
        by_tag = {s["tag"]: s for s in state["storage"]}
        for team in state["shard_teams"]:
            dcs = sorted(by_tag[t]["dcid"] for t in team)
            assert dcs == ["dc1", "dc2"], dcs
        # each remote (dc2) tag is fed by a log router recruited IN dc2
        routers = {r[0]: r for r in gen.get("routers", [])}
        remote_tags = {s["tag"] for s in state["storage"]
                       if s["dcid"] == "dc2"}
        assert set(routers) == remote_tags, (routers, remote_tags)
        for tag, ip, port, tok in routers.values():
            assert _dc_of_addr([ip, port], sim) == "dc2"
        # smoke: commits flow through the satellite-gated push path
        db = await sim.database()
        for i in range(25):
            await db.set(b"r%03d" % i, b"v%03d" % i)
        assert await db.get(b"r001") == b"v001"
        # the remote replicas really consume through their routers: each
        # router's frontier advanced past recruitment and its single
        # consumer (the remote replica) popped it forward
        await asyncio.sleep(2.0)
        router_objs = [obj for m in sim.machines if m.host is not None
                       for _tok, (role, obj) in m.host.worker.roles.items()
                       if role == "log_router"]
        assert len(router_objs) == len(remote_tags)
        for r in router_objs:
            met = r.metrics()
            assert met["end"] > 1, met
            assert max(met["pops"].values()) > 1, \
                f"remote replica never popped its router: {met}"
        await sim.stop()
    run_simulation(main())


def test_primary_region_loss_no_acked_data_lost():
    """Kill EVERY primary-DC machine mid-write-storm: the secondary
    region must take over (new epoch, txn subsystem in dc2) and serve
    every acked commit — the satellite logs gate acks, so nothing acked
    can be lost with the whole primary DC gone."""
    async def main():
        k = Knobs()
        sim = SimulatedCluster(k, n_machines=6, dcids=DCIDS,
                               spec=_regions_spec())
        await sim.start()
        state1 = await sim.wait_epoch(1)
        db = await sim.database()

        acked: dict[bytes, bytes] = {}
        stop = asyncio.Event()

        async def writer(wid: int) -> None:
            i = 0
            while not stop.is_set():
                key, v = b"reg%02d%05d" % (wid, i), b"v" * 20
                i += 1
                try:
                    async def do(tr, key=key, v=v):
                        tr.set(key, v)
                    await asyncio.wait_for(db.run(do), timeout=30)
                except (Exception, asyncio.TimeoutError):  # noqa: BLE001
                    continue        # unacked: allowed to vanish
                acked[key] = v
                await asyncio.sleep(0.05)

        writers = [asyncio.ensure_future(writer(w)) for w in range(2)]
        await asyncio.sleep(2.0)
        assert len(acked) > 10
        pre_kill = len(acked)

        await sim.kill_dc("dc1")
        # the secondary becomes primary: new epoch accepts commits with
        # its txn subsystem recruited in dc2
        state2 = await sim.wait_state(
            lambda s: s["epoch"] > state1["epoch"]
            and all(_dc_of_addr(a, sim) == "dc2"
                    for a in s["log_cfg"][-1]["tlogs"]))
        await asyncio.sleep(2.0)     # post-failover writes land
        stop.set()
        await asyncio.gather(*writers)
        assert len(acked) > pre_kill, "no commits after failover"

        db2 = await sim.database()
        tr = db2.create_transaction()
        while True:
            try:
                rows = await tr.get_range(b"reg", b"reh", limit=0)
                break
            except Exception as e:  # noqa: BLE001
                await tr.on_error(e)
        got = dict(rows)
        missing = [key for key in acked if key not in got]
        assert not missing, \
            f"{len(missing)} ACKED rows lost after region loss: {missing[:5]}"
        assert all(got[key] == v for key, v in acked.items())
        await sim.stop()
    run_simulation(main())


def test_dd_split_preserves_region_placement():
    """A DataDistribution live split under a multi-region layout must
    keep one replica per region in the new teams (region-preserving
    destination placement), not collapse the shard into the primary."""
    async def main():
        k = Knobs().override(DD_ENABLED=True, DD_INTERVAL=1.0,
                             DD_SHARD_SPLIT_BYTES=6_000)
        sim = SimulatedCluster(k, n_machines=6, dcids=DCIDS,
                               spec=_regions_spec())
        await sim.start()
        state1 = await sim.wait_epoch(1)
        n_before = len(state1["shard_teams"])
        db = await sim.database()
        for i in range(200):
            await db.set(b"hot%05d" % i, b"v" * 40)
        state2 = await sim.wait_state(
            lambda s: len(s["shard_teams"]) > n_before)
        by_tag = {s["tag"]: s for s in state2["storage"]}
        for team in state2["shard_teams"]:
            dcs = sorted(by_tag[t].get("dcid", "?") for t in team
                         if t in by_tag)
            assert dcs == ["dc1", "dc2"], \
                f"split broke region spanning: {dcs}"
        await sim.stop()
    run_simulation(main())


def test_region_failback_when_primary_returns():
    """After failover to dc2, rebooting the dc1 machines must move the
    transaction subsystem BACK to the higher-priority region (automatic
    failback) with no acked data lost across either transition."""
    async def main():
        k = Knobs()
        sim = SimulatedCluster(k, n_machines=6, dcids=DCIDS,
                               spec=_regions_spec())
        await sim.start()
        state1 = await sim.wait_epoch(1)
        db = await sim.database()
        for i in range(15):
            await db.set(b"fb%03d" % i, b"a")
        victims = await sim.kill_dc("dc1")
        state2 = await sim.wait_state(
            lambda s: s["epoch"] > state1["epoch"]
            and s.get("primary_dc") == "dc2")
        db2 = await sim.database()
        for i in range(15, 30):
            while True:
                try:
                    await db2.set(b"fb%03d" % i, b"b")
                    break
                except Exception:  # noqa: BLE001 — follow the failover
                    await asyncio.sleep(0.25)
        for m in victims:
            await m.reboot()
        state3 = await sim.wait_state(
            lambda s: s["epoch"] > state2["epoch"]
            and s.get("primary_dc") == "dc1")
        assert all(_dc_of_addr(a, sim) == "dc1"
                   for a in state3["log_cfg"][-1]["tlogs"])
        db3 = await sim.database()
        tr = db3.create_transaction()
        while True:
            try:
                rows = await tr.get_range(b"fb", b"fc", limit=0)
                break
            except Exception as e:  # noqa: BLE001
                await tr.on_error(e)
        assert len(rows) == 30, f"rows lost across failover+failback: " \
            f"{len(rows)}/30"
        await sim.stop()
    run_simulation(main())


def test_satellite_survives_in_old_generation_peek():
    """After failover, a remote replica's catch-up reads of the OLD
    generation come from the satellite (all main logs dead) — covered
    implicitly above; here we assert the recovery marked the old
    generation's main logs dead but kept a live satellite."""
    async def main():
        k = Knobs()
        sim = SimulatedCluster(k, n_machines=6, dcids=DCIDS,
                               spec=_regions_spec())
        await sim.start()
        state1 = await sim.wait_epoch(1)
        db = await sim.database()
        for i in range(20):
            await db.set(b"sat%03d" % i, b"x" * 10)
        await sim.kill_dc("dc1")
        state2 = await sim.wait_state(lambda s: s["epoch"] > state1["epoch"])
        old_gen = state2["log_cfg"][-2]
        assert len(old_gen["dead"]) == len(old_gen["tlogs"]), \
            "all primary-DC logs should be dead in the locked generation"
        assert len(old_gen.get("sat_dead", [])) < \
            len(old_gen.get("satellites", [])), \
            "a live satellite must back the locked generation"
        db2 = await sim.database()
        tr = db2.create_transaction()
        while True:
            try:
                rows = await tr.get_range(b"sat", b"sau", limit=0)
                break
            except Exception as e:  # noqa: BLE001
                await tr.on_error(e)
        assert len(rows) == 20
        await sim.stop()
    run_simulation(main())
