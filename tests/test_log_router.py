"""LogRouter — one upstream pull, many consumers, min-pop trimming.

Reference test model: REF:fdbserver/LogRouter.actor.cpp — remote
consumers see the identical mutation stream without each loading the
primary TLogs; a lagging consumer pins the router's buffer, not the
primary's disk queue; the pull survives source recoveries.
"""

from __future__ import annotations

import asyncio

from foundationdb_tpu.backup.dr import DRAgent
from foundationdb_tpu.backup.stream import TagStream, commit_tag
from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
from foundationdb_tpu.core.data import SYSTEM_PREFIX
from foundationdb_tpu.core.log_router import LogRouter, RouterStream
from foundationdb_tpu.rpc.wire import encode
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

ROUTER_TAG = (1 << 20) + 7


async def _read_all(db, at_version=None):
    tr = db.create_transaction()
    tr.lock_aware = True
    while True:
        try:
            if at_version is not None:
                tr.set_read_version(at_version)
            rows = await tr.get_range(b"", SYSTEM_PREFIX, limit=0,
                                      snapshot=True)
            return dict(rows)
        except Exception as e:   # noqa: BLE001 — retry loop
            await tr.on_error(e)


async def _drain_stream(stream, until_version):
    """Collect (version, mutations) until the frontier passes a version."""
    out = []
    while stream.frontier < until_version:
        entries, _ = await stream.next()
        out.extend(entries)
        stream.pop(stream.frontier)
    return out


def test_router_fans_out_one_pull_to_two_consumers():
    """Both consumers see the identical stream; the upstream tag is
    popped only past the slower consumer's releases."""
    async def main():
        sim = SimulatedCluster(Knobs(), n_machines=4,
                               spec=ClusterConfigSpec(min_workers=4))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()

        va = await commit_tag(db, "router", encode(ROUTER_TAG))
        router = LogRouter(db, ROUTER_TAG, va + 1, ["a", "b"])
        router.start()
        sa = RouterStream(router, "a", va + 1)
        sb = RouterStream(router, "b", va + 1)

        async def w(tr):
            for i in range(20):
                tr.set(b"rk%03d" % i, b"%d" % i)
        await db.run(w)
        tr = db.create_transaction()
        while True:
            try:
                tr.set(b"marker", b"end")
                vt = await tr.commit()
                break
            except Exception as e:   # noqa: BLE001
                await tr.on_error(e)

        got_a = await _drain_stream(sa, vt)
        # consumer a popped everything; b popped nothing yet — the
        # router's buffer (and the upstream tag) must still hold the
        # stream for b
        got_b = await _drain_stream(sb, vt)
        ka = [(v, bytes(m.param1)) for v, ms in got_a for m in ms]
        kb = [(v, bytes(m.param1)) for v, ms in got_b for m in ms]
        assert ka == kb and len(ka) >= 21, (len(ka), len(kb))
        # both popped through vt: the buffer trims
        assert router.metrics()["buffered"] == 0 or \
            router.metrics()["floor"] > vt
        await commit_tag(db, "router", None)
        await router.stop()
        await sim.stop()
    run_simulation(main())


def test_router_lagging_consumer_pins_router_not_primary():
    """After the fast consumer pops, the slow one still reads the full
    stream from the router's buffer (nothing was lost to an upstream
    pop at the fast consumer's frontier)."""
    async def main():
        sim = SimulatedCluster(Knobs(), n_machines=4,
                               spec=ClusterConfigSpec(min_workers=4))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()

        va = await commit_tag(db, "router", encode(ROUTER_TAG))
        router = LogRouter(db, ROUTER_TAG, va + 1, ["fast", "slow"])
        router.start()
        fast = RouterStream(router, "fast", va + 1)

        async def w(tr):
            for i in range(30):
                tr.set(b"pin%03d" % i, b"x")
        await db.run(w)
        tr = db.create_transaction()
        while True:
            try:
                tr.set(b"marker2", b"end")
                vt = await tr.commit()
                break
            except Exception as e:   # noqa: BLE001
                await tr.on_error(e)

        await _drain_stream(fast, vt)
        m = router.metrics()
        assert m["buffered"] > 0, "buffer trimmed past the slow consumer"
        assert m["floor"] <= va + 1

        slow = RouterStream(router, "slow", va + 1)
        got = await _drain_stream(slow, vt)
        keys = {bytes(mm.param1) for _, ms in got for mm in ms}
        assert all(b"pin%03d" % i in keys for i in range(30))
        assert router.metrics()["buffered"] == 0
        await commit_tag(db, "router", None)
        await router.stop()
        await sim.stop()
    run_simulation(main())


def test_dr_through_router_over_rpc():
    """The headline composition: DR pulls via a LogRouter served over the
    simulated network (LogRouterClient), and the destination converges
    exactly as with a direct pull."""
    from foundationdb_tpu.rpc.sim_transport import SimTransport
    from foundationdb_tpu.rpc.stubs import LogRouterClient, serve_role
    from foundationdb_tpu.rpc.transport import (NetworkAddress,
                                                WLTOKEN_FIRST_AVAILABLE)

    async def main():
        src_sim = SimulatedCluster(Knobs(), n_machines=4,
                                   spec=ClusterConfigSpec(min_workers=4))
        dest_sim = SimulatedCluster(Knobs(), n_machines=4,
                                    spec=ClusterConfigSpec(min_workers=4))
        await src_sim.start(); await dest_sim.start()
        await src_sim.wait_epoch(1); await dest_sim.wait_epoch(1)
        src, dest = await src_sim.database(), await dest_sim.database()

        async def seed(tr):
            for i in range(15):
                tr.set(b"s%03d" % i, b"v%d" % i)
            tr.add(b"c", (4).to_bytes(8, "little"))
        await src.run(seed)

        # the router runs "near the source": its serving transport lives
        # on the source sim's network
        from foundationdb_tpu.backup.dr import DR_TAG
        va = await commit_tag(src, "dr", encode(DR_TAG))
        router = LogRouter(src, DR_TAG, va + 1, ["dr-agent"])
        router.start()
        raddr = NetworkAddress("10.1.0.99", 4500)
        rtrans = SimTransport(src_sim.net, raddr)
        serve_role(rtrans, "log_router", router, WLTOKEN_FIRST_AVAILABLE)
        ctrans = SimTransport(src_sim.net,
                              NetworkAddress("10.1.0.98", 4501))
        rclient = LogRouterClient(ctrans, raddr, WLTOKEN_FIRST_AVAILABLE)

        dr = DRAgent(src, dest, stream_factory=lambda _db, _tag, begin:
                     RouterStream(rclient, "dr-agent", begin))
        await dr.start()

        for j in range(5):
            async def w(tr, j=j):
                tr.set(b"live%d" % j, b"L")
                tr.add(b"c", (3).to_bytes(8, "little"))
            await src.run(w)

        vd = await dr.drain()
        expected = await _read_all(src, at_version=vd)
        got = await _read_all(dest)
        got.pop(b"\xff/dr/applied", None)
        assert expected[b"c"] == (19).to_bytes(8, "little")
        assert got == expected, (
            f"missing={sorted(set(expected) - set(got))[:4]} "
            f"extra={sorted(set(got) - set(expected))[:4]}")
        await dr.abort()
        await router.stop()
        await src_sim.stop(); await dest_sim.stop()
    run_simulation(main())


def test_router_survives_source_recovery():
    """A recovery on the source rolls the router's upstream cursor into
    the new generation; consumers see no gap and no duplicate."""
    async def main():
        sim = SimulatedCluster(Knobs(), n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        db = await sim.database()

        va = await commit_tag(db, "router", encode(ROUTER_TAG))
        router = LogRouter(db, ROUTER_TAG, va + 1, ["c"])
        router.start()
        stream = RouterStream(router, "c", va + 1)

        async def w(tr, tag, n):
            for i in range(n):
                tr.set(b"g%s%03d" % (tag, i), b"v")
        await db.run(lambda tr: w(tr, b"pre", 10))

        victims = await sim.txn_only_machines()
        assert victims
        await victims[0].kill()
        await sim.wait_epoch(state1["epoch"] + 1)

        while True:
            tr = db.create_transaction()
            try:
                await w(tr, b"post", 10)
                tr.set(b"done", b"1")
                vt = await tr.commit()
                break
            except Exception as e:   # noqa: BLE001 — retry through recovery
                await tr.on_error(e)

        got = await _drain_stream(stream, vt)
        versions = [v for v, _ in got]
        assert versions == sorted(set(versions)), "gap/duplicate versions"
        keys = {bytes(m.param1) for _, ms in got for m in ms}
        assert all(b"gpre%03d" % i in keys for i in range(10))
        assert all(b"gpost%03d" % i in keys for i in range(10))
        await commit_tag(db, "router", None)
        await router.stop()
        await sim.stop()
    run_simulation(main())
