"""Special-key-space module framework (VERDICT r4 item 7).

Reference: REF:fdbclient/SpecialKeySpace.actor.cpp — prefix-scoped
modules under \\xff\\xff, management writes gated by the
SPECIAL_KEY_SPACE_ENABLE_WRITES option and rewritten onto real system
keys inside the same transaction."""

import asyncio

import pytest

from foundationdb_tpu.client.special_keys import (ExcludedServersModule,
                                                  SpecialKeySpace)
from foundationdb_tpu.client.transaction import Transaction
from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
from foundationdb_tpu.core.management import EXCLUDED_PREFIX
from foundationdb_tpu.runtime.errors import ClientInvalidOperation
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation

PFX = ExcludedServersModule.prefix


def test_exclusion_roundtrip_via_special_keys():
    """Write an exclusion through \\xff\\xff/management/excluded/, read it
    back through the special range AND the real system key — one txn."""
    async def main():
        cluster = Cluster(ClusterConfig(), Knobs())
        cluster.start()
        tr = Transaction(cluster)
        tr.special_key_space_enable_writes = True
        tr.set(PFX + b"10.0.0.9:4500", b"1")
        await tr.commit()
        tr.reset()

        # special-range read
        rows = await tr.get_range(PFX, PFX + b"\xff")
        assert rows == [(PFX + b"10.0.0.9:4500", b"1")]
        # point read through the module
        assert await tr.get(PFX + b"10.0.0.9:4500") == b"1"
        # the REAL system key was written (what recovery consumes)
        assert await tr.get(EXCLUDED_PREFIX + b"10.0.0.9:4500") == b"1"

        # include (clear) through the special key space
        tr.reset()
        tr.special_key_space_enable_writes = True
        tr.clear(PFX + b"10.0.0.9:4500")
        await tr.commit()
        tr.reset()
        assert await tr.get(PFX + b"10.0.0.9:4500") is None
        assert await tr.get(EXCLUDED_PREFIX + b"10.0.0.9:4500") is None
        await cluster.stop()
    run_simulation(main())


def test_writes_gated_by_option_and_error_message():
    async def main():
        cluster = Cluster(ClusterConfig(), Knobs())
        cluster.start()
        tr = Transaction(cluster)
        with pytest.raises(ClientInvalidOperation):
            tr.set(PFX + b"10.0.0.1:1", b"1")
        # the rejection reason is readable at \xff\xff/error_message
        msg = await tr.get(b"\xff\xff/error_message")
        assert b"SPECIAL_KEY_SPACE_ENABLE_WRITES" in msg
        # read-only modules refuse writes even with the option on
        tr.special_key_space_enable_writes = True
        with pytest.raises(ClientInvalidOperation):
            tr.set(b"\xff\xff/status/json", b"nope")
        msg = await tr.get(b"\xff\xff/error_message")
        assert b"not writable" in msg
        await cluster.stop()
    run_simulation(main())


def test_unknown_special_key_rejected():
    async def main():
        cluster = Cluster(ClusterConfig(), Knobs())
        cluster.start()
        tr = Transaction(cluster)
        with pytest.raises(ClientInvalidOperation):
            await tr.get(b"\xff\xff/no_such_module")
        await cluster.stop()
    run_simulation(main())


def test_cross_module_range_read():
    """A range read spanning several modules returns each module's rows
    in key order (the reference's cross-module read)."""
    async def main():
        cluster = Cluster(ClusterConfig(), Knobs())
        cluster.start()
        tr = Transaction(cluster)
        tr.special_key_space_enable_writes = True
        tr.set(PFX + b"10.0.0.7:1", b"1")
        await tr.commit()
        tr.reset()
        rows = await tr.get_range(b"\xff\xff/", b"\xff\xff/z")
        keys = [k for k, _v in rows]
        assert PFX + b"10.0.0.7:1" in keys
        assert keys == sorted(keys)
        await cluster.stop()
    run_simulation(main())


def test_module_dispatch_longest_prefix():
    sks = SpecialKeySpace()
    m = sks.module_for(PFX + b"1.2.3.4:5")
    assert isinstance(m, ExcludedServersModule)
    assert sks.module_for(b"\xff\xff/status/json") is not None
    assert sks.module_for(b"\xff\xff/bogus") is None


def test_worker_interfaces_module_lists_roles():
    """Against a view-backed client (sim cluster), worker_interfaces
    lists the published role addresses."""
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        sim = SimulatedCluster(n_machines=4, n_coordinators=3)
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        tr = db.create_transaction()
        rows = await tr.get_range(b"\xff\xff/worker_interfaces/",
                                  b"\xff\xff/worker_interfaces/\xff")
        assert rows, "no worker interfaces listed"
        assert all(k.startswith(b"\xff\xff/worker_interfaces/")
                   for k, _ in rows)
        await sim.stop()
    run_simulation(main(), seed=3)
