"""Continuous mutation-log backup + point-in-time restore.

Reference test model: REF:fdbclient/FileBackupAgent.actor.cpp semantics —
snapshot + mutation log compose into restore-to-any-covered-version, with
atomic ops re-evaluated identically and transaction atomicity preserved
at every restore point.
"""

from __future__ import annotations

import asyncio

from foundationdb_tpu.backup.agent import BackupAgent
from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
from foundationdb_tpu.core.data import SYSTEM_PREFIX
from foundationdb_tpu.runtime.files import SimFileSystem
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster


async def _read_all(db, at_version=None):
    tr = db.create_transaction()
    while True:
        try:
            if at_version is not None:
                tr.set_read_version(at_version)
            rows = await tr.get_range(b"", SYSTEM_PREFIX, limit=0,
                                      snapshot=True)
            return dict(rows)
        except Exception as e:   # noqa: BLE001 — retry loop
            await tr.on_error(e)


def test_pitr_restore_to_exact_version():
    """Snapshot mid-stream, keep writing (sets, clears, atomic adds),
    then restore to a version BETWEEN snapshot and the end: the result
    must equal the database's exact historical state at that version."""
    async def main():
        k = Knobs()
        sim = SimulatedCluster(k, n_machines=4,
                               spec=ClusterConfigSpec(min_workers=4))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        agent = BackupAgent(db, SimFileSystem(), "bk")

        await agent.start_continuous()

        # phase A: before the snapshot
        async def phase_a(tr):
            for i in range(25):
                tr.set(b"pa%03d" % i, b"A%d" % i)
            tr.add(b"counter", (5).to_bytes(8, "little"))
        await db.run(phase_a)

        await agent.backup()

        # phase B: after the snapshot, before the restore point
        for j in range(5):
            async def phase_b(tr, j=j):
                tr.set(b"pb%03d" % j, b"B%d" % j)
                tr.clear(b"pa%03d" % (j * 2))
                tr.add(b"counter", (3).to_bytes(8, "little"))
            await db.run(phase_b)
        tr = db.create_transaction()
        while True:
            try:
                tr.set(b"marker", b"at-vt")
                vt = await tr.commit()
                break
            except Exception as e:   # noqa: BLE001
                await tr.on_error(e)
        expected = await _read_all(db, at_version=vt)
        assert expected[b"marker"] == b"at-vt"
        assert expected[b"counter"] == (20).to_bytes(8, "little")

        # phase C: after the restore point — must NOT appear
        async def phase_c(tr):
            for j in range(5):
                tr.set(b"pb%03d" % j, b"C!")
                tr.set(b"pc%03d" % j, b"C")
            tr.clear_range(b"pa", b"pa\xff")
            tr.add(b"counter", (100).to_bytes(8, "little"))
            tr.set(b"marker", b"after-vt")
        await db.run(phase_c)

        await agent.stop_continuous()

        # wipe and point-in-time restore
        async def wipe(tr):
            tr.clear_range(b"", SYSTEM_PREFIX)
        await db.run(wipe)
        await agent.restore(to_version=vt)

        got = await _read_all(db)
        assert got == expected, (
            f"PITR mismatch: {len(expected)} expected vs {len(got)} got; "
            f"missing={sorted(set(expected) - set(got))[:4]} "
            f"extra={sorted(set(got) - set(expected))[:4]}")
        await sim.stop()
    run_simulation(main())


def test_pitr_torn_transaction_consistency():
    """Pairs written atomically must be consistent at ANY restore point:
    restore to a version captured mid-stream and check pair equality."""
    async def main():
        k = Knobs()
        sim = SimulatedCluster(k, n_machines=4,
                               spec=ClusterConfigSpec(min_workers=4))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        agent = BackupAgent(db, SimFileSystem(), "bk2")
        await agent.start_continuous()
        await agent.backup()

        vt = None
        for i in range(20):
            async def pair(tr, i=i):
                tr.set(b"left", b"%04d" % i)
                tr.set(b"right", b"%04d" % i)
            await db.run(pair)
            if i == 11:
                tr = db.create_transaction()
                while True:
                    try:
                        tr.add_write_conflict_range(b"zz", b"zz\x00")
                        vt = await tr.commit()
                        break
                    except Exception as e:   # noqa: BLE001
                        await tr.on_error(e)
        await agent.stop_continuous()

        async def wipe(tr):
            tr.clear_range(b"", SYSTEM_PREFIX)
        await db.run(wipe)
        await agent.restore(to_version=vt)
        got = await _read_all(db)
        assert got[b"left"] == got[b"right"] == b"0011", got
        await sim.stop()
    run_simulation(main())


def test_continuous_backup_survives_recovery():
    """A recovery mid-stream must not lose acked mutations from the log:
    the backup tag re-arms on the new epoch's proxies (seeded from the
    \\xff read) and the agent's cursor rolls across generations."""
    async def main():
        k = Knobs()
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        db = await sim.database()
        agent = BackupAgent(db, SimFileSystem(), "bk3")
        await agent.start_continuous()
        await agent.backup()

        async def put(tr, tag, n):
            for i in range(n):
                tr.set(b"rk%s%03d" % (tag, i), b"v-" + tag)
        await db.run(lambda tr: put(tr, b"pre", 20))

        victims = await sim.txn_only_machines()
        assert victims
        await victims[0].kill()
        await sim.wait_epoch(state1["epoch"] + 1)

        async def post(tr):
            await put(tr, b"post", 20)
            tr.set(b"marker", b"end")
        while True:
            tr = db.create_transaction()
            try:
                await post(tr)
                vt = await tr.commit()
                break
            except Exception as e:   # noqa: BLE001 — retry through recovery
                await tr.on_error(e)
        expected = await _read_all(db, at_version=vt)
        await agent.stop_continuous()

        async def wipe(tr):
            tr.clear_range(b"", SYSTEM_PREFIX)
        await db.run(wipe)
        await agent.restore(to_version=vt)
        got = await _read_all(db)
        assert got == expected, (
            f"missing={sorted(set(expected) - set(got))[:4]} "
            f"extra={sorted(set(got) - set(expected))[:4]}")
        await sim.stop()
    run_simulation(main())


def test_restore_refuses_coverage_hole_below_log():
    """A log armed AFTER the snapshot cannot cover (snapshot, begin]:
    restore must refuse (RestoreError), never silently produce a database
    missing that window's mutations."""
    from foundationdb_tpu.backup.agent import RestoreError

    async def main():
        k = Knobs()
        sim = SimulatedCluster(k, n_machines=4,
                               spec=ClusterConfigSpec(min_workers=4))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        agent = BackupAgent(db, SimFileSystem(), "bk4")

        async def seed(tr):
            tr.set(b"hole0", b"in-snapshot")
        await db.run(seed)
        await agent.backup()                      # snapshot FIRST

        async def in_hole(tr):
            tr.set(b"hole1", b"lost-if-replayed")
        await db.run(in_hole)                     # before the tag arms

        await agent.start_continuous()            # log begins after snapshot

        tr = db.create_transaction()
        while True:
            try:
                tr.set(b"hole2", b"in-log")
                vt = await tr.commit()
                break
            except Exception as e:   # noqa: BLE001
                await tr.on_error(e)
        await agent.stop_continuous()

        try:
            await agent.restore(to_version=vt)
            raise AssertionError("restore served a coverage hole")
        except RestoreError:
            pass
        await sim.stop()
    run_simulation(main())


def test_backup_reactivation_captures_new_stream():
    """stop_continuous must not un-pin the tag forever: a second
    activation in the same generation still captures every mutation (the
    first stop used to pop the tag to MAX_VERSION, letting the TLogs
    discard re-armed frames before the agent pulled them)."""
    async def main():
        k = Knobs()
        sim = SimulatedCluster(k, n_machines=4,
                               spec=ClusterConfigSpec(min_workers=4))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        fs = SimFileSystem()
        agent = BackupAgent(db, fs, "bk5")

        # first activation: arm, write, stop (drained + released)
        await agent.start_continuous()

        async def w1(tr):
            tr.set(b"gen1", b"one")
        await db.run(w1)
        await agent.stop_continuous()

        # second activation in the SAME generation
        agent2 = BackupAgent(db, fs, "bk5")
        await agent2.start_continuous()
        await agent2.backup()                     # snapshot under the log

        async def w2(tr):
            for i in range(10):
                tr.set(b"re%03d" % i, b"second")
        await db.run(w2)
        tr = db.create_transaction()
        while True:
            try:
                tr.set(b"marker", b"re-end")
                vt = await tr.commit()
                break
            except Exception as e:   # noqa: BLE001
                await tr.on_error(e)
        expected = await _read_all(db, at_version=vt)
        await agent2.stop_continuous()

        async def wipe(tr):
            tr.clear_range(b"", SYSTEM_PREFIX)
        await db.run(wipe)
        await agent2.restore(to_version=vt)
        got = await _read_all(db)
        assert got == expected, (
            f"missing={sorted(set(expected) - set(got))[:4]} "
            f"extra={sorted(set(got) - set(got))[:4]}")
        await sim.stop()
    run_simulation(main())
