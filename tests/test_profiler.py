"""Slow-task profiler + TraceBatch latency probes (VERDICT r4 item 8).

Reference: REF:flow/Profiler.actor.cpp (event-loop stall sampling) and
TraceBatch per-transaction stage probes (SURVEY §5.1)."""

import asyncio
import time

from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.profiler import SlowTaskProfiler
from foundationdb_tpu.runtime.trace import TraceLog, get_trace_log, set_trace_log


def _run_real_loop(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_injected_stall_is_caught_and_attributed():
    """A coroutine blocking the loop past SLOW_TASK_THRESHOLD must produce
    a SlowTask trace naming the blocking frame."""
    events = []
    old = get_trace_log()
    log = TraceLog()
    log.sink = events.append
    set_trace_log(log)
    try:
        async def main():
            prof = SlowTaskProfiler(threshold=0.05).start()
            await asyncio.sleep(0.12)       # heartbeat warm
            time.sleep(0.3)                 # the stall: blocks the loop
            await asyncio.sleep(0.12)       # let the watchdog report
            prof.stop()
            return prof

        prof = _run_real_loop(main())
        assert prof.stalls >= 1
        assert prof.last_stall_s >= 0.05
        slow = [e for e in events if e.get("Type") == "SlowTask"]
        assert slow, f"no SlowTask event in {[e.get('Type') for e in events]}"
        assert slow[0]["DurationMs"] >= 50
        # the stack names this test's blocking line
        assert "time.sleep" in slow[0]["Stack"] \
            or "test_profiler" in slow[0]["Stack"]
    finally:
        set_trace_log(old)


def test_profiler_noop_under_simulation():
    from foundationdb_tpu.runtime.simloop import run_simulation

    async def main():
        prof = SlowTaskProfiler(threshold=0.01).start()
        await asyncio.sleep(1.0)    # virtual: instant, no watchdog
        return prof._watchdog is None and prof.stalls == 0

    assert run_simulation(main())


def test_trace_batch_probes_sampled_txns():
    """With sample rate 1.0 every txn emits one TransactionTrace event
    carrying grv/commit stage deltas."""
    from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
    from foundationdb_tpu.client.transaction import Transaction

    events = []
    old = get_trace_log()
    log = TraceLog()
    log.sink = events.append
    set_trace_log(log)
    try:
        async def main():
            k = Knobs().override(CLIENT_LATENCY_PROBE_SAMPLE=1.0)
            cluster = Cluster(ClusterConfig(), k)
            cluster.start()
            tr = Transaction(cluster)
            for i in range(3):
                tr.set(b"probe%d" % i, b"v")
                await tr.commit()
                tr.reset()
            await cluster.stop()

        _run_real_loop(main())
        traces = [e for e in events if e.get("Type") == "TransactionTrace"]
        assert len(traces) == 3, f"expected 3 probes, got {len(traces)}"
        for t in traces:
            assert t["Outcome"] == "committed"
            assert "GrvMs" in t and "CommitDoneMs" in t and "TotalMs" in t
    finally:
        set_trace_log(old)


def test_trace_batch_sampling_rate():
    from foundationdb_tpu.runtime.latency_probe import TraceBatch

    tb = TraceBatch(0.25, clock=time.monotonic)
    sampled = sum(tb.attach(i) for i in range(100))
    assert sampled == 25
    # unsampled ids are no-ops end to end
    tb.event(1, "x")
    assert tb.flush(1) is None
