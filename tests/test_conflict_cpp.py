"""C++ conflict set vs oracle (exact, all key lengths) and vs kernels."""

import numpy as np
import pytest

from foundationdb_tpu.ops.batch import TxnRequest, encode_batch
from foundationdb_tpu.ops.conflict_cpp import CppConflictSet
from foundationdb_tpu.ops.conflict_np import NumpyConflictSet
from foundationdb_tpu.ops.oracle import OracleConflictSet
from foundationdb_tpu.runtime import DeterministicRandom

W = 16
B, R = 8, 4


def rand_key(rng, maxlen, alphabet=3):
    n = rng.random_int(1, maxlen + 1)
    return bytes(rng.random_int(0, alphabet) for _ in range(n))


def rand_range(rng, maxlen):
    a, b = rand_key(rng, maxlen), rand_key(rng, maxlen)
    if a == b:
        b = a + b"\x00"
    return (min(a, b), max(a, b))


def rand_txn(rng, snap_lo, snap_hi, maxlen):
    return TxnRequest(
        read_ranges=[rand_range(rng, maxlen) for _ in range(rng.random_int(0, R + 1))],
        write_ranges=[rand_range(rng, maxlen) for _ in range(rng.random_int(0, R + 1))],
        read_snapshot=rng.random_int(snap_lo, snap_hi),
    )


@pytest.mark.parametrize("seed,maxlen", [(0, W), (1, W), (2, 64), (3, 64), (4, 200)])
def test_cpp_oracle_exact_parity(seed, maxlen):
    """C++ uses raw byte keys: must match the oracle on every input."""
    rng = DeterministicRandom(seed)
    cpp = CppConflictSet()
    oracle = OracleConflictSet()
    version = 100
    for step in range(40):
        nt = rng.random_int(1, B + 1)
        txns = [rand_txn(rng, max(0, version - 50), version + 1, maxlen) for _ in range(nt)]
        version += rng.random_int(1, 20)
        cv = cpp.resolve_batch(txns, version)
        ov = oracle.resolve_batch(txns, version)
        assert cv == ov, f"diverged at step {step}"
        if rng.coinflip(0.2):
            oldest = version - rng.random_int(10, 60)
            cpp.set_oldest_version(oldest)
            oracle.set_oldest_version(oldest)
    assert cpp.segment_count > 1


def test_cpp_numpy_parity_short_keys():
    rng = DeterministicRandom(55)
    cpp = CppConflictSet()
    twin = NumpyConflictSet(4096, W)
    version = 100
    for _ in range(25):
        nt = rng.random_int(1, B + 1)
        txns = [rand_txn(rng, max(0, version - 50), version + 1, W) for _ in range(nt)]
        version += rng.random_int(1, 20)
        cv = cpp.resolve_batch(txns, version)
        tv = twin.resolve_encoded(encode_batch(txns, B, R, W), version)[:nt].tolist()
        assert cv == tv


def test_cpp_empty_batch_and_no_ranges():
    cpp = CppConflictSet()
    assert cpp.resolve_batch([], 10) == []
    t = TxnRequest([], [], 5)
    assert cpp.resolve_batch([t], 10) == [0]


def test_cpp_set_oldest_compaction():
    cpp = CppConflictSet()
    txns = [TxnRequest([], [(bytes([i]), bytes([i, 0]))], 0) for i in range(50)]
    cpp.resolve_batch(txns, 10)
    n_before = cpp.segment_count
    cpp.set_oldest_version(20)  # all history now stale -> compacts to 1 segment
    assert cpp.segment_count < n_before
    assert cpp.resolve_batch([TxnRequest([(b"\x01", b"\x02")], [], 15)], 30) == [2]  # too old
    assert cpp.resolve_batch([TxnRequest([(b"\x01", b"\x02")], [], 25)], 40) == [0]
