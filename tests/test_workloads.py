"""Simulation workload runs — the `fdbserver -r simulation -f spec` analog."""

import pytest

from foundationdb_tpu.core.cluster import ClusterConfig
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.workloads import run_workloads


def multi():
    return ClusterConfig(commit_proxies=2, grv_proxies=2, resolvers=3,
                         logs=2, storage_servers=4)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("config", [None, multi()], ids=["single", "multi"])
def test_cycle(seed, config):
    res = run_workloads([{"testName": "Cycle", "nodeCount": 12,
                          "transactionsPerClient": 15}],
                        seed=seed, config=config, client_count=3)
    assert res["Cycle"]["transactions"] == 45


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_serializability_oracle(seed):
    res = run_workloads([{"testName": "Serializability", "keyCount": 24,
                          "transactionsPerClient": 20}],
                        seed=seed, config=multi(), client_count=4)
    assert res["Serializability"]["committed"] > 0


@pytest.mark.parametrize("backend", ["numpy", "cpp"])
def test_cycle_all_backends(backend):
    knobs = Knobs().override(RESOLVER_CONFLICT_BACKEND=backend)
    run_workloads([{"testName": "Cycle", "nodeCount": 10,
                    "transactionsPerClient": 10}],
                  seed=5, config=multi(), knobs=knobs, client_count=2)


def test_readwrite():
    res = run_workloads([{"testName": "ReadWrite", "nodeCount": 200,
                          "transactionsPerClient": 30}],
                        seed=9, config=multi(), client_count=2)
    assert res["ReadWrite"]["transactions"] == 60


def test_mixed_workloads_concurrent():
    # cycle + readwrite running concurrently against one cluster
    res = run_workloads([
        {"testName": "Cycle", "nodeCount": 8, "transactionsPerClient": 10},
        {"testName": "ReadWrite", "nodeCount": 100, "transactionsPerClient": 20},
    ], seed=11, config=multi(), client_count=2)
    assert res["Cycle"]["transactions"] == 20


def test_workload_determinism():
    def go():
        return run_workloads([{"testName": "Serializability", "keyCount": 16,
                               "transactionsPerClient": 15}],
                             seed=21, config=multi(), client_count=3)
    assert go() == go()


def test_watches_workload():
    """Watch fires reflect real changes; re-arm on storage errors."""
    from foundationdb_tpu.workloads.workload import run_workloads

    results = run_workloads(
        [{"testName": "Watches", "rounds": 3, "nodeCount": 3}],
        seed=5, client_count=2)
    assert results["Watches"]["watch_fires"] >= 6


def test_configure_database_workload_with_cycle():
    """Random role-count churn forcing recoveries mid-run, while Cycle's
    permutation invariant holds (REF:fdbserver/workloads/
    ConfigureDatabase.actor.cpp)."""
    import asyncio

    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.runtime.simloop import run_simulation
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster
    from foundationdb_tpu.workloads.workload import run_workloads_on

    async def main():
        sim = SimulatedCluster(Knobs(), n_machines=5,
                               spec=ClusterConfigSpec(min_workers=5))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        results = await run_workloads_on(db, [
            {"testName": "Cycle", "nodeCount": 10,
             "transactionsPerClient": 25},
            {"testName": "ConfigureDatabase", "sim": sim, "rounds": 2,
             "secondsBetweenChanges": 1.0},
        ], client_count=2)
        await sim.stop()
        return results

    results = run_simulation(main(), seed=12)
    assert results["ConfigureDatabase"]["config_changes"] == 2
    assert results["Cycle"]["transactions"] == 50


@pytest.mark.parametrize("seed", [0, 1])
def test_conflict_range_workload(seed):
    """The resolver's verdicts are CORRECT under contention: no false
    commits (exhaustive history oracle) and snapshot reads never abort
    with not_committed."""
    res = run_workloads([{"testName": "ConflictRange", "nodeCount": 6,
                          "opsPerClient": 20}],
                        seed=seed, config=multi(), client_count=4)
    assert res["ConflictRange"]["commits"] == 80


def test_conflict_range_sees_conflicts():
    """Sanity: with 4 clients hammering 6 keys with range reads, real
    conflicts must actually occur — the oracle isn't vacuous."""
    res = run_workloads([{"testName": "ConflictRange", "nodeCount": 6,
                          "opsPerClient": 25}],
                        seed=11, config=multi(), client_count=4)
    assert res["ConflictRange"]["conflicts"] > 0


def test_histogram_percentiles():
    from foundationdb_tpu.runtime.trace import Histogram
    h = Histogram("T", "X")
    for us in [100] * 98 + [100_000, 200_000]:
        h.sample(us)
    assert h.count == 100
    assert h.percentile(0.5) <= 256          # power-of-two upper bound
    assert h.percentile(0.99) >= 100_000
    h.clear()
    assert h.count == 0 and h.percentile(0.5) == 0.0


def test_increment_and_versionstamp_workloads():
    res = run_workloads([
        {"testName": "Increment", "incrementsPerClient": 12},
        {"testName": "VersionStamp", "stampsPerClient": 10},
    ], seed=7, config=multi(), client_count=3)
    assert res["Increment"]["increments"] == 36
    assert res["VersionStamp"]["stamped"] == 30


def test_api_correctness_workload():
    res = run_workloads([{"testName": "ApiCorrectness", "keyCount": 20,
                          "transactionsPerClient": 15,
                          "opsPerTransaction": 8}],
                        seed=31, client_count=2)
    assert res["ApiCorrectness"]["committed"] == 30
    assert res["ApiCorrectness"]["reads_checked"] > 20


def test_sideband_workload():
    res = run_workloads([{"testName": "Sideband", "messages": 12}],
                        seed=32, client_count=2)
    assert res["Sideband"]["causally_checked"] == 12


def test_bank_transfer_workload():
    res = run_workloads([{"testName": "BankTransfer", "accounts": 8,
                          "transfersPerClient": 12, "scanEvery": 4}],
                        seed=33, client_count=3)
    assert res["BankTransfer"]["transfers"] == 36
    assert res["BankTransfer"]["scans"] >= 9
