"""The layer ecosystem (ISSUE 19): indexes, read-through cache, watches.

Functional coverage on an in-process cluster:

- the shared feed consumer's freshness frontier and fan-out;
- the typed ``feed_destroyed`` terminal error a cursor raises when its
  feed's registration is destroyed mid-drain (vs the transient
  handoff race it retries through) — satellite regression;
- transactional index rows BIT-IDENTICAL to a rebuild-from-scan at a
  pinned version (the mode's acceptance invariant), including
  overwrites, deletes, clear_range and atomic-op folds;
- the async index's freshness frontier: reads never served above it,
  primary-scan fallback when ``at_least`` outruns it;
- cache invalidation: a committed write is never served stale past the
  feed frontier, concurrent fill/invalidate races discard the fill;
- watch edge cases: fire on first mutation at-or-after the watch
  version, fire on a ``clear_range`` covering the key, immediate fire
  when registered past the mutation, survival across a live shard
  split mid-wait;
- the layer consistency checker: clean on honest layers, key-exact
  ``LayerMismatch`` on injected index-row canaries (both flavors:
  phantom row and missing row).
"""

from __future__ import annotations

import asyncio

import pytest

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.client.subspace import Subspace
from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
from foundationdb_tpu.layers import (LayerConsistencyChecker,
                                     LayerFeedConsumer, ReadThroughCache,
                                     SecondaryIndex, WatchRegistry)
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.runtime.trace import (Severity, TraceLog,
                                            get_trace_log, set_trace_log)

# a hot feed-poll cadence so frontier waits settle in sim-milliseconds
LAYER_KNOBS = dict(LAYER_FEED_POLL_INTERVAL=0.01,
                   LAYER_PROGRESS_INTERVAL=0.5)


@pytest.fixture()
def captured_trace():
    events: list[dict] = []
    sink = TraceLog(min_severity=Severity.INFO)
    sink.sink = events.append
    prev = get_trace_log()
    set_trace_log(sink)
    yield events
    set_trace_log(prev)


async def _commit(db, fn) -> int:
    """db.run but returning the COMMIT VERSION (db.run returns fn's
    result) — layer tests constantly need the version."""
    import inspect
    tr = db.create_transaction()
    try:
        while True:
            try:
                r = fn(tr)
                if inspect.isawaitable(r):
                    await r
                return await tr.commit()
            except BaseException as e:
                await tr.on_error(e)
    finally:
        tr.reset()


def _idx(db, **kw) -> SecondaryIndex:
    return SecondaryIndex(db, Subspace(raw_prefix=b"idx/"),
                          primary_begin=b"p/", primary_end=b"q",
                          **kw)


async def _rebuild(db, index, version: int) -> set:
    """Independent rebuild-from-scan of the expected index row-key set
    at a pinned version."""
    tr = db.create_transaction()
    try:
        tr.set_read_version(version)
        rows = await tr.get_range(index.primary_begin, index.primary_end,
                                  snapshot=True)
        expected = set()
        for k, v in rows:
            for iv in index._extract(bytes(k), bytes(v)):
                expected.add(index.row_key(iv, bytes(k)))
        return expected
    finally:
        tr.reset()


async def _index_rows(db, index, version: int | None = None) -> set:
    tr = db.create_transaction()
    try:
        if version is not None:
            tr.set_read_version(version)
        ib, ie = index.index.key(), index.index.range(())[1]
        rows = await tr.get_range(ib, ie, snapshot=True)
        return {bytes(k) for k, _v in rows}
    finally:
        tr.reset()


# --- the feed consumer ---

def test_feed_consumer_frontier_proves_delivery():
    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs().override(**LAYER_KNOBS)) as cluster:
            db = Database(cluster)
            consumer = LayerFeedConsumer(db, name="t1")
            seen: list[tuple[int, bytes]] = []

            class Sink:
                def on_mutations(self, version, batch):
                    for m in batch:
                        seen.append((version, bytes(m.param1)))
            consumer.add_sink(Sink())
            v0 = await consumer.start()
            assert consumer.frontier == v0
            vs = [await _commit(db, lambda tr, i=i:
                                tr.set(b"fk%02d" % i, b"v"))
                  for i in range(5)]
            await consumer.wait_frontier(max(vs))
            # frontier >= tip proves every commit at or below it was
            # dispatched to the sink BEFORE the frontier advanced
            got = sorted(seen)
            assert got == sorted((v, b"fk%02d" % i)
                                 for i, v in enumerate(vs)), got
            assert consumer.stats()["entries"] == 5
            await consumer.stop(destroy=True)
    run_simulation(main(), seed=1901)


def test_cursor_raises_typed_feed_destroyed_mid_drain():
    """Satellite regression: a feed destroyed while a cursor drains it
    surfaces as the TYPED terminal ``feed_destroyed`` error — not as an
    endless change_feed_not_registered retry loop, and not retryable."""
    from foundationdb_tpu.runtime.errors import (ChangeFeedDestroyed,
                                                 FdbError)

    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs()) as cluster:
            db = Database(cluster)
            await db.create_change_feed(b"doomed", b"d", b"e")
            v1 = await _commit(db, lambda tr: tr.set(b"d1", b"v"))
            cur = db.read_change_feed(b"doomed")
            loop = asyncio.get_running_loop()
            entries = await cur.drain_through(v1,
                                              deadline=loop.time() + 60)
            assert [m.param1 for _v, b in entries for m in b] == [b"d1"]
            await db.destroy_change_feed(b"doomed")
            await asyncio.sleep(1.0)       # destroy reaches the storages
            with pytest.raises(ChangeFeedDestroyed) as ei:
                for _ in range(200):
                    await cur.next()
            assert isinstance(ei.value, FdbError)
            assert ei.value.code == 2905
            assert ei.value.name == "feed_destroyed"
            assert not ei.value.retryable, \
                "feed_destroyed must be terminal, not retryable"
    run_simulation(main(), seed=1902)


def test_consumer_goes_terminal_on_destroyed_feed():
    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs().override(**LAYER_KNOBS)) as cluster:
            db = Database(cluster)
            consumer = LayerFeedConsumer(db, name="t2")
            await consumer.start()
            v = await _commit(db, lambda tr: tr.set(b"x1", b"v"))
            await consumer.wait_frontier(v)
            await db.destroy_change_feed(consumer.feed_id)
            await asyncio.sleep(1.0)
            for _ in range(400):
                if consumer.destroyed:
                    break
                await asyncio.sleep(0.05)
            assert consumer.destroyed, \
                "the pull loop kept running against a destroyed feed"
            with pytest.raises(Exception):
                await consumer.wait_frontier(v + 1_000_000, timeout=1.0)
            await consumer.stop()
    run_simulation(main(), seed=1903)


# --- transactional index ---

def test_transactional_index_bit_identical_to_rebuild(captured_trace):
    """The mode's acceptance invariant: after sets, overwrites, atomic
    adds, deletes and a clear_range — all through the commit hook — the
    index subspace at a pinned version is BIT-IDENTICAL to an
    independent rebuild-from-scan of the primary range at the same
    version.  Then the checker agrees (clean), and injected canaries
    (a phantom row AND a removed row) are each caught key-exactly."""
    events = captured_trace
    canary = {}

    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs().override(**LAYER_KNOBS)) as cluster:
            db = Database(cluster)
            index = _idx(db, mode="transactional",
                         extractor=lambda k, v: [v[:4]])

            async def seed(tr):
                for i in range(12):
                    tr.set(b"p/%03d" % i, b"b%02d-val" % (i % 3))
            await index.run(seed)

            async def churn(tr):
                tr.set(b"p/001", b"b99-moved")     # ival change
                tr.clear(b"p/002")                 # delete
                tr.clear_range(b"p/007", b"p/010")  # span delete
                tr.set(b"p/100", b"b42-new")       # insert
                tr.add(b"p/003", b"\x01\x00\x00\x00")
            await index.run(churn)

            tr = db.create_transaction()
            pinned = await tr.get_read_version()
            tr.reset()
            actual = await _index_rows(db, index, pinned)
            expected = await _rebuild(db, index, pinned)
            assert actual == expected and len(actual) == 9, (
                f"index rows diverge from rebuild at pinned {pinned}: "
                f"extra={sorted(actual - expected)} "
                f"missing={sorted(expected - actual)}")

            # lookup serves the contiguous (ival, pkey) range
            pkeys, _v = await index.lookup(b"b42-")
            assert pkeys == [b"p/100"]

            checker = LayerConsistencyChecker(db, index=index)
            verdict = await checker.check()
            assert verdict["divergences"] == 0, verdict
            assert not verdict["index"]["refused"], verdict

            # canaries: a phantom row the primary never justified, and
            # an honest row removed behind the maintainer's back
            phantom = index.row_key(b"b77-", b"p/ghost")
            victim = index.row_key(b"b42-", b"p/100")
            canary["phantom"], canary["victim"] = phantom, victim
            await _commit(db, lambda tr: tr.set(phantom, b""))
            await _commit(db, lambda tr: tr.clear(victim))
            verdict = await checker.check()
            assert verdict["index"]["divergences"] == 2, verdict
    run_simulation(main(), seed=1904)

    hits = {e["Key"] for e in events if e.get("Type") == "LayerMismatch"}
    assert hits == {canary["phantom"].hex(), canary["victim"].hex()}, (
        f"checker named {sorted(hits)}, expected exactly the two "
        f"injected canary rows — triage is not key-exact")


def test_transactional_index_concurrent_writers_conflict():
    """Two transactions racing on the SAME primary key cannot both
    commit stale index math: the hook's pre-write read is conflicted,
    so the loser retries and folds the winner's row."""
    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs().override(**LAYER_KNOBS)) as cluster:
            db = Database(cluster)
            index = _idx(db, mode="transactional")
            await index.run(lambda tr: _set(tr, b"p/k", b"red"))

            async def racer(val: bytes):
                await index.run(lambda tr: _set(tr, b"p/k", val))
            await asyncio.gather(racer(b"green"), racer(b"blue"))

            tr = db.create_transaction()
            pinned = await tr.get_read_version()
            tr.reset()
            actual = await _index_rows(db, index, pinned)
            expected = await _rebuild(db, index, pinned)
            assert actual == expected and len(actual) == 1, (
                f"racing writers left {sorted(actual)} vs {sorted(expected)}")
    run_simulation(main(), seed=1905)


async def _set(tr, k, v):
    tr.set(k, v)


# --- async index ---

def test_async_index_frontier_and_fallback():
    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs().override(**LAYER_KNOBS)) as cluster:
            db = Database(cluster)
            consumer = LayerFeedConsumer(db, name="ai")
            index = _idx(db, mode="async", consumer=consumer)
            v0 = await _commit(db, lambda tr: _fill(tr, 8))
            await consumer.start()
            await index.start_async()
            await consumer.wait_frontier(v0)
            for _ in range(400):
                if index.checkpoint() is not None:
                    break
                await asyncio.sleep(0.05)
            ck = index.checkpoint()
            assert ck is not None, "checkpoint never stabilized"

            # served freshness NEVER exceeds the frontier
            pkeys, served_at = await index.lookup(b"even")
            assert served_at <= consumer.frontier
            assert pkeys == [b"p/%03d" % i for i in range(0, 8, 2)]

            # a write the feed has not delivered yet: at_least above the
            # frontier forces the primary-scan fallback, which sees it
            v1 = await _commit(db, lambda tr: tr.set(b"p/200", b"even"))
            before = index.fallback_scans
            pkeys, served_at = await index.lookup(b"even", at_least=v1 + 1)
            assert index.fallback_scans == before + 1
            assert b"p/200" in pkeys and served_at >= v1

            # once the frontier catches up the index itself serves it
            await consumer.wait_frontier(v1)
            for _ in range(400):
                ck = index.checkpoint()
                if ck is not None and ck[0] >= v1:
                    break
                await asyncio.sleep(0.05)
            pkeys, served_at = await index.lookup(b"even", at_least=v1)
            assert b"p/200" in pkeys and v1 <= served_at \
                <= consumer.frontier

            checker = LayerConsistencyChecker(db, index=index)
            verdict = await checker.check()
            assert verdict["divergences"] == 0, verdict
            assert not verdict["index"]["refused"], verdict
            await consumer.stop(destroy=True)
    run_simulation(main(), seed=1906)


async def _fill(tr, n):
    for i in range(n):
        tr.set(b"p/%03d" % i, b"even" if i % 2 == 0 else b"odd")


def test_async_index_clear_range_and_atomics_converge():
    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs().override(**LAYER_KNOBS)) as cluster:
            db = Database(cluster)
            consumer = LayerFeedConsumer(db, name="ai2")
            index = _idx(db, mode="async", consumer=consumer,
                         extractor=lambda k, v: [v[:1]])
            await consumer.start()
            await index.start_async()
            await _commit(db, lambda tr: _fill(tr, 6))
            v = await _commit(db, lambda tr: _mix(tr))
            await consumer.wait_frontier(v)
            for _ in range(400):
                ck = index.checkpoint()
                if ck is not None and ck[0] >= v:
                    break
                await asyncio.sleep(0.05)
            ck = index.checkpoint()
            assert ck is not None and ck[0] >= v
            actual = await _index_rows(db, index)
            expected = await _rebuild(db, index, ck[0])
            assert actual == expected, (
                f"async rows diverge: extra={sorted(actual - expected)} "
                f"missing={sorted(expected - actual)}")
            await consumer.stop(destroy=True)
    run_simulation(main(), seed=1907)


async def _mix(tr):
    tr.clear_range(b"p/001", b"p/004")
    # the feed carries the atomic OPERAND; the applier must resolve the
    # folded value at the frontier, not index the operand bytes
    tr.add(b"p/004", b"\x01\x00\x00\x00")
    tr.set(b"p/050", b"zz")


# --- cache ---

def test_cache_invalidation_never_serves_stale():
    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs().override(**LAYER_KNOBS)) as cluster:
            db = Database(cluster)
            consumer = LayerFeedConsumer(db, name="c1")
            cache = ReadThroughCache(db, consumer, capacity=64)
            await consumer.start()
            v0 = await _commit(db, lambda tr: tr.set(b"ck", b"one"))
            await consumer.wait_frontier(v0)

            assert await cache.get(b"ck") == b"one"      # miss, fills
            assert await cache.get(b"ck") == b"one"      # hit
            assert (cache.hits, cache.misses) == (1, 1)

            v1 = await _commit(db, lambda tr: tr.set(b"ck", b"two"))
            await consumer.wait_frontier(v1)
            assert cache.invalidations == 1
            value, valid_through = await cache.get_versioned(b"ck")
            assert value == b"two" and valid_through >= v1

            # at_least above the frontier forces a read-through even on
            # a cached entry — the no-stale-read contract
            v2 = await _commit(db, lambda tr: tr.set(b"ck", b"three"))
            value, valid_through = await cache.get_versioned(
                b"ck", at_least=v2)
            assert value == b"three" and valid_through >= v2

            checker = LayerConsistencyChecker(db, cache=cache)
            verdict = await checker.check()
            assert verdict["divergences"] == 0, verdict
            await consumer.stop(destroy=True)
    run_simulation(main(), seed=1908)


def test_cache_clear_range_invalidates_and_lru_bounds():
    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs().override(**LAYER_KNOBS)) as cluster:
            db = Database(cluster)
            consumer = LayerFeedConsumer(db, name="c2")
            cache = ReadThroughCache(db, consumer, capacity=4)
            await consumer.start()
            v = await _commit(db, lambda tr: _fill_ck(tr))
            await consumer.wait_frontier(v)
            for i in range(8):
                await cache.get(b"ck%02d" % i)
            assert len(cache) == 4 and cache.evictions == 4

            v1 = await _commit(
                db, lambda tr: tr.clear_range(b"ck", b"cl"))
            await consumer.wait_frontier(v1)
            assert len(cache) == 0
            assert await cache.get(b"ck05") is None
            await consumer.stop(destroy=True)
    run_simulation(main(), seed=1909)


async def _fill_ck(tr):
    for i in range(8):
        tr.set(b"ck%02d" % i, b"v%02d" % i)


# --- watches (satellite edge cases) ---

def test_watch_fires_on_first_mutation_at_or_after_version():
    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs().override(**LAYER_KNOBS)) as cluster:
            db = Database(cluster)
            consumer = LayerFeedConsumer(db, name="w1")
            watches = WatchRegistry(db, consumer)
            await consumer.start()
            fut = await watches.watch(b"wk")
            assert not fut.done()
            v = await _commit(db, lambda tr: tr.set(b"wk", b"new"))
            fired_at = await asyncio.wait_for(fut, 60)
            assert fired_at == v
            assert watches.fired == 1 and watches.pending_count == 0
            await consumer.stop(destroy=True)
    run_simulation(main(), seed=1910)


def test_watch_fires_when_key_clear_ranged():
    """Edge case: the watched key is destroyed by a clear_range that
    never names it — the span fire must still resolve the watch."""
    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs().override(**LAYER_KNOBS)) as cluster:
            db = Database(cluster)
            consumer = LayerFeedConsumer(db, name="w2")
            watches = WatchRegistry(db, consumer)
            v0 = await _commit(db, lambda tr: tr.set(b"wr5", b"x"))
            await consumer.start()
            await consumer.wait_frontier(v0)
            fut = await watches.watch(b"wr5")
            v = await _commit(db, lambda tr: tr.clear_range(b"wr", b"ws"))
            fired_at = await asyncio.wait_for(fut, 60)
            assert fired_at == v
            await consumer.stop(destroy=True)
    run_simulation(main(), seed=1911)


def test_watch_registered_past_mutation_fires_immediately():
    """Edge case: registration at a version at or below an
    already-delivered mutation must fire on the spot — no new feed
    traffic required."""
    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs().override(**LAYER_KNOBS)) as cluster:
            db = Database(cluster)
            consumer = LayerFeedConsumer(db, name="w3")
            watches = WatchRegistry(db, consumer)
            await consumer.start()
            tr = db.create_transaction()
            old = await tr.get_read_version()
            tr.reset()
            v = await _commit(db, lambda tr: tr.set(b"wi", b"x"))
            await consumer.wait_frontier(v)
            fut = await watches.watch(b"wi", version=old)
            assert fut.done() and fut.result() >= old
            assert watches.immediate_fires == 1
            # and a watch ABOVE the delivered mutation still pends
            fut2 = await watches.watch(b"wi")
            assert not fut2.done()
            await consumer.stop(destroy=True)
    run_simulation(main(), seed=1912)


def test_watch_survives_live_shard_split_mid_wait():
    """Edge case: a DD split relocates the watched key's shard while
    the watch pends; the feed cursor re-routes and the mutation
    committed AFTER the move still fires the watch."""
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        k = Knobs().override(DD_ENABLED=True, DD_INTERVAL=1.0,
                             DD_SHARD_SPLIT_BYTES=6_000, **LAYER_KNOBS)
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        n_shards = len(state1["shard_teams"])
        db = await sim.database()
        consumer = LayerFeedConsumer(db, name="w4")
        watches = WatchRegistry(db, consumer)
        await consumer.start()
        fut = await watches.watch(b"hot-target")
        # write volume around the watched key until DD splits the shard
        stop = asyncio.Event()

        async def writer() -> None:
            i = 0
            while not stop.is_set():
                async def body(tr, i=i):
                    tr.set(b"hot%05d" % i, b"v" * 40)
                await db.run(body)
                i += 1
                await asyncio.sleep(0.02)

        w = asyncio.ensure_future(writer())
        await sim.wait_state(lambda s: s.get("seq", 0) > 0
                             and len(s["shard_teams"]) > n_shards)
        stop.set()
        await w
        assert not fut.done()
        v = 0

        async def fire(tr):
            tr.set(b"hot-target", b"after-move")
        tr = db.create_transaction()
        while True:
            try:
                await fire(tr)
                v = await tr.commit()
                break
            except BaseException as e:
                await tr.on_error(e)
        fired_at = await asyncio.wait_for(fut, 120)
        assert fired_at == v, (fired_at, v)
        await consumer.stop(destroy=True)
        await sim.stop()
    run_simulation(main(), seed=1913)


def test_watch_checker_clean_and_limit():
    from foundationdb_tpu.runtime.errors import ClientInvalidOperation

    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs().override(**LAYER_KNOBS)) as cluster:
            db = Database(cluster)
            consumer = LayerFeedConsumer(db, name="w5")
            watches = WatchRegistry(db, consumer, limit=2)
            await consumer.start()
            await watches.watch(b"wa")
            await watches.watch(b"wb")
            with pytest.raises(ClientInvalidOperation):
                await watches.watch(b"wc")
            checker = LayerConsistencyChecker(db, watches=watches)
            verdict = await checker.check()
            assert verdict["divergences"] == 0, verdict
            await consumer.stop(destroy=True)
    run_simulation(main(), seed=1914)
