"""Columnar range reads (ISSUE 9): equivalence + fence tests.

The contract under test everywhere here: the packed surfaces — the
engines' ``range_runs``, ``VersionedMap.range_rows``,
``StorageServer.get_key_values_packed`` and the client's packed
``get_range`` path — return BYTE-IDENTICAL rows to the scalar
tuple-list paths they replace, on randomized workloads including MVCC
overlays, clears, atomic stacks, RYW overlays, reverse scans,
row/byte limits and post-reopen engines.  Plus the 715 protocol fence,
the per-chunk status codes (incl. across a live DD split), and the
backup container's zero-copy columns + expire-before GC.
"""

from __future__ import annotations

import asyncio
import random

import pytest

from foundationdb_tpu.core.data import (GV_FOUND, GV_FUTURE_VERSION,
                                        GV_TOO_OLD, GV_WRONG_SHARD,
                                        GetRangeReply, GetRangeRequest,
                                        KeyRange, Mutation, PackedRows)
from foundationdb_tpu.runtime.knobs import Knobs


def krand(rng: random.Random) -> bytes:
    return b"k%04d" % rng.randrange(600)


# --- wire structs ---

def test_get_range_wire_roundtrip():
    from foundationdb_tpu.rpc.wire import decode, encode
    req = GetRangeRequest(b"a", b"zz", 42, 100, True, 9000)
    assert decode(encode(req)) == req
    rep = GetRangeReply.from_rows([(b"a", b"v0"), (b"bb", b""),
                                   (b"ccc", b"x" * 70)], True)
    got = decode(encode(rep))
    assert got == rep
    assert got.rows() == [(b"a", b"v0"), (b"bb", b""), (b"ccc", b"x" * 70)]
    assert len(got) == 3 and got.more and got.status == 0
    empty = decode(encode(GetRangeReply.from_rows([], False)))
    assert len(empty) == 0 and empty.rows() == [] and not empty.more
    ref = decode(encode(GetRangeReply.refuse(GV_TOO_OLD)))
    assert ref.status == GV_TOO_OLD and len(ref) == 0


def test_packed_rows_surface():
    rows = [(b"a", b"1"), (b"bcd", b""), (b"e", b"22")]
    p = PackedRows.from_rows(rows)
    assert len(p) == 3 and p.rows() == rows and list(p) == rows
    assert p[0] == rows[0] and p[-1] == rows[-1]
    assert p.key(1) == b"bcd" and p.value(2) == b"22"
    assert p.slice(1, 3).rows() == rows[1:]
    assert p.slice(0, 3) is p
    assert PackedRows.concat([p.slice(0, 2), p.slice(2, 3)]).rows() == rows
    # concat rebases bounds to the exact bytes from_rows would produce
    c = PackedRows.concat([p.slice(0, 1), p.slice(1, 2), p.slice(2, 3)])
    assert (c.key_bounds, c.key_blob, c.val_bounds, c.val_blob) == \
        (p.key_bounds, p.key_blob, p.val_bounds, p.val_blob)


# --- the protocol fence (714 peer must be refused) ---

def test_version_gate_fences_714_peer():
    from foundationdb_tpu.core.cluster_client import RecoveredClusterView
    from foundationdb_tpu.runtime.errors import ClusterVersionChanged
    new = Knobs().override(PROTOCOL_VERSION=715)   # the ISSUE 9 gate
    old = new.override(PROTOCOL_VERSION=714)
    state = {"epoch": 1, "seq": 0, "protocol": new.PROTOCOL_VERSION}
    with pytest.raises(ClusterVersionChanged):
        RecoveredClusterView(old, None, state)


# --- engine range_runs vs range ---

def _engine_workload(rng: random.Random):
    batches = []
    for _ in range(14):
        ops = []
        for _ in range(rng.randrange(5, 60)):
            if rng.random() < 0.12:
                b = krand(rng)
                ops.append((1, b, b + b"\xff"))
            else:
                ops.append((0, krand(rng), b"val%05d" % rng.randrange(9999)))
        batches.append(ops)
    return batches


@pytest.mark.parametrize("engine_name", ["memory", "lsm", "btree"])
def test_engine_range_runs_match_range(engine_name, monkeypatch):
    import foundationdb_tpu.storage.lsm as lsm_mod
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.storage import engine_class
    if engine_name == "lsm":
        # small thresholds: force flushes + several overlapping runs so
        # the segment-wise merge actually runs (and tombstones cross
        # run boundaries)
        monkeypatch.setattr(lsm_mod, "_MEMTABLE_BYTES", 1500)
        monkeypatch.setattr(lsm_mod, "_BLOCK_BYTES", 128)
        monkeypatch.setattr(lsm_mod, "_MEM_RUN_ROWS", 7)

    async def main():
        rng = random.Random(171 + len(engine_name))
        fs = SimFileSystem()
        kv = await engine_class(engine_name).open(fs, f"db/{engine_name}")
        for i, ops in enumerate(_engine_workload(rng)):
            await kv.commit(ops, {"durable_version": i})

        def check(kv):
            bounds = [b"", b"k0000", b"k0100", b"k0300", b"k0599",
                      b"k9999", b"zz"]
            for _ in range(40):
                b, e = rng.choice(bounds), rng.choice(bounds)
                if b > e:
                    b, e = e, b
                # rows are (key, value) sequences — tuples or the block
                # decoder's lists — so compare normalized
                flat = [(r[0], r[1]) for run in kv.range_runs(b, e)
                        for r in run]
                assert flat == list(kv.range(b, e)), (b, e)
                for run in kv.range_runs(b, e):
                    assert run, "range_runs yielded an empty run"

        check(kv)
        await kv.close()
        kv2 = await engine_class(engine_name).open(fs, f"db/{engine_name}")
        check(kv2)
        await kv2.close()

    asyncio.run(main())


# --- VersionedMap.range_rows vs range_read ---

def test_vmap_range_rows_matches_range_read():
    from foundationdb_tpu.storage.versioned_map import VersionedMap
    rng = random.Random(37)
    vm = VersionedMap()
    version = 0
    for _ in range(40):
        version += rng.randrange(1, 3)
        ops = []
        for _ in range(rng.randrange(1, 30)):
            if rng.random() < 0.18:
                b = krand(rng)
                ops.append((version, 1, b, b + b"\xff"))
            else:
                ops.append((version, 0, krand(rng),
                            b"v%d" % rng.randrange(1000)))
        vm.apply_batch(ops)
    bounds = [b"", b"k0050", b"k0200", b"k0400", b"k0600", b"z"]
    for _ in range(60):
        b, e = rng.choice(bounds), rng.choice(bounds)
        if b > e:
            b, e = e, b
        v = rng.choice([0, version // 2, version, version + 3])
        limit = rng.choice([0, 1, 3, 17, 1000])
        byte_limit = rng.choice([0, 0, 10, 200])
        assert vm.range_rows(b, e, v, limit, byte_limit) == \
            vm.range_read(b, e, v, limit, False, byte_limit), \
            (b, e, v, limit, byte_limit)


# --- StorageServer packed vs legacy (all engines + engine-less) ---

def _apply_random(ss, rng: random.Random, versions: int = 20) -> int:
    version = ss.version
    for _ in range(versions):
        version += rng.randrange(1, 3)
        muts = []
        for _ in range(rng.randrange(1, 25)):
            r = rng.random()
            if r < 0.12:
                b = krand(rng)
                muts.append(Mutation.clear_range(b, b + b"\xff"))
            elif r < 0.2:
                # atomic stacks ride the lazy apply path
                from foundationdb_tpu.core.data import MutationType
                muts.append(Mutation(MutationType.ADD, krand(rng),
                                     (rng.randrange(1, 99)).to_bytes(
                                         4, "little")))
            else:
                muts.append(Mutation.set(krand(rng),
                                         b"v%05d" % rng.randrange(9999)))
        ss._apply_batch([(version, muts)])
    return version


async def _packed_vs_legacy(ss, rng: random.Random, tip: int) -> None:
    bounds = [b"", b"k0050", b"k0200", b"k0400", b"k0599", b"z"]
    for _ in range(40):
        b, e = rng.choice(bounds), rng.choice(bounds)
        if b > e:
            b, e = e, b
        v = rng.choice([tip, tip - 2, max(ss.oldest_version, 0)])
        limit = rng.choice([0, 1, 5, 40, 1000])
        byte_limit = rng.choice([0, 0, 64, 900])
        reverse = rng.random() < 0.3
        legacy = await ss.get_key_values(b, e, v, limit, reverse,
                                         byte_limit)
        rep = await ss.get_key_values_packed(
            GetRangeRequest(b, e, v, limit, reverse, byte_limit))
        assert rep.status == 0
        assert rep.rows() == legacy[0], (b, e, v, limit, byte_limit,
                                         reverse)
        # `more` may be conservatively True on the packed side, but a
        # False must never hide rows the legacy path would continue for
        if not rep.more:
            nxt = await ss.get_key_values(
                (legacy[0][-1][0] + b"\x00") if legacy[0] and not reverse
                else b, e if not reverse else
                (legacy[0][-1][0] if legacy[0] else e), v)
            if legacy[0] and (limit or byte_limit):
                assert not nxt[0] or not legacy[1], (b, e, v)
    # full chunked-iteration equivalence: drive BOTH sides' continuation
    # at small limits and compare the totals (the property more exists
    # to serve)
    for reverse in (False, True):
        out_legacy, out_packed = [], []
        b, e = b"", b"z"
        cur_b, cur_e = b, e
        while True:
            rows, more = await ss.get_key_values(cur_b, cur_e, tip, 7,
                                                 reverse)
            out_legacy.extend(rows)
            if not more or not rows:
                break
            if reverse:
                cur_e = rows[-1][0]
            else:
                cur_b = rows[-1][0] + b"\x00"
        cur_b, cur_e = b, e
        while True:
            rep = await ss.get_key_values_packed(
                GetRangeRequest(cur_b, cur_e, tip, 7, reverse))
            rows = rep.rows()
            out_packed.extend(rows)
            if not rep.more or not rows:
                break
            if reverse:
                cur_e = rows[-1][0]
            else:
                cur_b = rows[-1][0] + b"\x00"
        assert out_packed == out_legacy, f"reverse={reverse}"


def test_storage_packed_matches_legacy_engineless():
    from foundationdb_tpu.core.storage_server import StorageServer
    from foundationdb_tpu.core.tlog import TLog

    async def main():
        rng = random.Random(73)
        knobs = Knobs()
        ss = StorageServer(knobs, 0, KeyRange(b"", b"\xff"), TLog(knobs))
        tip = _apply_random(ss, rng)
        await _packed_vs_legacy(ss, rng, tip)

    asyncio.run(main())


@pytest.mark.parametrize("engine_name", ["memory", "lsm", "btree"])
def test_storage_packed_matches_legacy_engine(engine_name, monkeypatch):
    """Durable engine + a live MVCC window on top: the run-wise overlay
    merge must agree with the per-row generator walk — window values
    superseding engine rows, tombstones hiding them, untouched chains
    falling through to durable state."""
    import foundationdb_tpu.storage.lsm as lsm_mod
    from foundationdb_tpu.core.storage_server import StorageServer
    from foundationdb_tpu.core.tlog import TLog
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.storage import engine_class
    if engine_name == "lsm":
        monkeypatch.setattr(lsm_mod, "_MEMTABLE_BYTES", 1500)
        monkeypatch.setattr(lsm_mod, "_BLOCK_BYTES", 256)

    async def main():
        rng = random.Random(97 + len(engine_name))
        fs = SimFileSystem()
        eng = await engine_class(engine_name).open(fs, "db/ss-eng")
        # durable rows below the window, interleaved with the overlay's
        # key space (plus a stretch the window never touches)
        for i in range(4):
            await eng.commit(
                [(0, b"k%04d" % k, b"durable%04d" % k)
                 for k in range(i, 600, 4)]
                + [(0, b"q%04d" % k, b"quiet%04d" % k)
                   for k in range(i, 200, 4)],
                {"durable_version": 0})
        knobs = Knobs()
        ss = StorageServer(knobs, 0, KeyRange(b"", b"\xff"), TLog(knobs),
                           engine=eng)
        tip = _apply_random(ss, rng, versions=15)
        await _packed_vs_legacy(ss, rng, tip)
        # and the quiet stretch (pure engine, empty overlay) in bulk
        rep = await ss.get_key_values_packed(
            GetRangeRequest(b"q", b"r", tip))
        legacy = await ss.get_key_values(b"q", b"r", tip)
        assert rep.rows() == legacy[0] and len(rep) == 200

    asyncio.run(main())


def test_storage_packed_status_codes():
    """Per-chunk status codes: a relinquished range refuses with
    WRONG_SHARD above the drop version (history at-or-below still
    serves), a compacted read refuses TOO_OLD, an unapplied version
    FUTURE_VERSION — never an exception through the RPC."""
    from foundationdb_tpu.core.storage_server import StorageServer
    from foundationdb_tpu.core.tlog import TLog

    async def main():
        knobs = Knobs().override(STORAGE_FUTURE_VERSION_WAIT=0.05)
        ss = StorageServer(knobs, 0, KeyRange(b"b", b"y"), TLog(knobs))
        ss._apply_batch([(5, [Mutation.set(b"c1", b"v1"),
                              Mutation.set(b"m1", b"v2"),
                              Mutation.set(b"p1", b"v3")])])
        ss._drop_shard(6, b"m", b"n")
        ss._apply_batch([(7, [Mutation.set(b"c2", b"v4")])])
        # a scan touching the dropped range refuses wholesale
        rep = await ss.get_key_values_packed(GetRangeRequest(b"c", b"p", 7))
        assert rep.status == GV_WRONG_SHARD and len(rep) == 0
        # at-or-below the drop version the range still serves history
        rep = await ss.get_key_values_packed(GetRangeRequest(b"c", b"p", 6))
        assert rep.status == GV_FOUND
        assert rep.rows() == [(b"c1", b"v1"), (b"m1", b"v2")]
        # a scan clear of the dropped range serves above it
        rep = await ss.get_key_values_packed(GetRangeRequest(b"n", b"q", 7))
        assert rep.status == GV_FOUND and rep.rows() == [(b"p1", b"v3")]
        ss.oldest_version = 7
        rep = await ss.get_key_values_packed(GetRangeRequest(b"c", b"d", 3))
        assert rep.status == GV_TOO_OLD
        rep = await ss.get_key_values_packed(GetRangeRequest(b"c", b"d", 99))
        assert rep.status == GV_FUTURE_VERSION

    asyncio.run(main())


def test_replica_group_fails_over_refused_packed_chunks():
    """A replica refusing a chunk wholesale (lagging: FUTURE_VERSION;
    compacted: TOO_OLD) is penalized and its teammate tried — only when
    every replica refuses does the caller see the status code."""
    from foundationdb_tpu.core.load_balance import ReplicaGroup

    class _Stub:
        tag = 0

        def __init__(self, reply):
            self._reply = reply

        async def get_key_values_packed(self, req):
            return self._reply

    async def main():
        good = GetRangeReply.from_rows([(b"k", b"served")], False)
        for bad_code in (GV_FUTURE_VERSION, GV_TOO_OLD, GV_WRONG_SHARD):
            bad = GetRangeReply.refuse(bad_code)
            req = GetRangeRequest(b"", b"\xff", 10)
            shard = KeyRange(b"", b"\xff")
            g = ReplicaGroup(shard, [_Stub(bad), _Stub(good)])
            rep = await g.get_key_values_packed(req)
            assert rep.status == 0 and rep.rows() == [(b"k", b"served")]
            g2 = ReplicaGroup(shard, [_Stub(bad), _Stub(bad)])
            rep2 = await g2.get_key_values_packed(req)
            assert rep2.status == bad_code

    asyncio.run(main())


# --- Transaction.get_range: packed vs legacy, RYW overlays ---

def _seed_cluster(knobs=None, shards: int = 3):
    from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
    return Cluster(ClusterConfig(storage_servers=shards), knobs or Knobs())


async def _load(cluster, rows: dict[bytes, bytes]) -> None:
    from foundationdb_tpu.client.transaction import Transaction
    tr = Transaction(cluster)
    for k, v in rows.items():
        tr.set(k, v)
    await tr.commit()


def _overlay(tr, rng: random.Random) -> None:
    for _ in range(25):
        tr.set(krand(rng), b"ryw%04d" % rng.randrange(999))
    b = krand(rng)
    tr.clear_range(b, b + b"\x80")
    for _ in range(6):
        tr.add(krand(rng), (rng.randrange(1, 200)).to_bytes(4, "little"))


def test_get_range_packed_knob_equivalence():
    """Transaction.get_range with CLIENT_PACKED_RANGE_READS on vs off:
    byte-identical rows on randomized ranges with RYW overlays (sets,
    range clears, atomic stacks), reverse scans and limits, across
    shard boundaries."""
    from foundationdb_tpu.client.transaction import Transaction

    async def main():
        rows = {krand(random.Random(7 + i)): b"base%04d" % i
                for i in range(300)}
        clusters = {}
        for packed in (True, False):
            k = Knobs().override(CLIENT_PACKED_RANGE_READS=packed)
            c = _seed_cluster(knobs=k, shards=3)
            c.start()
            await _load(c, rows)
            clusters[packed] = c
        rng = random.Random(51)
        bounds = [b"", b"k0100", b"k0300", b"k0500", b"z"]
        for trial in range(12):
            b, e = rng.choice(bounds), rng.choice(bounds)
            if b > e:
                b, e = e, b
            limit = rng.choice([0, 1, 9, 100])
            reverse = rng.random() < 0.4
            with_overlay = rng.random() < 0.5
            got = {}
            for packed, c in clusters.items():
                tr = Transaction(c)
                if with_overlay:
                    _overlay(tr, random.Random(1000 + trial))
                got[packed] = await tr.get_range(b, e, limit=limit,
                                                 reverse=reverse)
            assert got[True] == got[False], (b, e, limit, reverse,
                                             with_overlay)
        for c in clusters.values():
            await c.stop()

    asyncio.run(main())


def test_get_range_packed_columns_api():
    """get_range_packed returns ONE concatenated PackedRows equal to
    get_range's tuple rows; a transaction with overlapping buffered
    writes is refused (the columns path cannot merge RYW)."""
    from foundationdb_tpu.client.transaction import Transaction
    from foundationdb_tpu.runtime.errors import ClientInvalidOperation

    async def main():
        knobs = Knobs().override(CLIENT_RANGE_CHUNK_ROWS=16)
        c = _seed_cluster(knobs=knobs, shards=2)
        c.start()
        rows = {b"p%04d" % i: b"v%04d" % i for i in range(150)}
        await _load(c, rows)
        tr = Transaction(c)
        page = await tr.get_range_packed(b"p", b"q")
        assert page.rows() == sorted(rows.items())
        page2 = await tr.get_range_packed(b"p", b"q", limit=37)
        assert page2.rows() == sorted(rows.items())[:37]
        tr2 = Transaction(c)
        tr2.set(b"p0001", b"x")
        with pytest.raises(ClientInvalidOperation):
            await tr2.get_range_packed(b"p", b"q")
        # a write OUTSIDE the range is fine
        assert (await tr2.get_range_packed(b"p1000", b"q")).rows() == \
            [(k, v) for k, v in sorted(rows.items()) if k >= b"p1000"]
        await c.stop()

    asyncio.run(main())


# --- live DD split: stale-routed packed scans re-route and complete ---

def test_scan_across_live_dd_split():
    """A packed scan running while DD splits the range LIVE: stale-
    routed chunks refuse with WRONG_SHARD (the per-chunk status code),
    the client's retry loop refreshes its map, and the scan completes
    with every committed row exactly once."""
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.runtime.simloop import run_simulation
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        k = Knobs().override(DD_ENABLED=True, DD_INTERVAL=1.0,
                             DD_SHARD_SPLIT_BYTES=6_000)
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        n_shards = len(state1["shard_teams"])
        db = await sim.database()
        committed: dict[bytes, bytes] = {}
        stop = asyncio.Event()

        async def writer(wid: int) -> None:
            i = 0
            while not stop.is_set():
                key = b"sc%02d%05d" % (wid, i)
                val = b"v" * 40
                i += 1
                tr = db.create_transaction()
                while True:
                    try:
                        tr.set(key, val)
                        await tr.commit()
                        committed[key] = val
                        break
                    except BaseException as e:
                        from foundationdb_tpu.runtime.errors import \
                            CommitUnknownResult
                        if isinstance(e, CommitUnknownResult):
                            break
                        await tr.on_error(e)
                await asyncio.sleep(0.05)

        scans = 0

        async def scanner() -> None:
            nonlocal scans
            while not stop.is_set():
                tr = db.create_transaction()
                while True:
                    try:
                        rows = await tr.get_range(b"sc", b"sd",
                                                  snapshot=True)
                        break
                    except BaseException as e:
                        await tr.on_error(e)
                for kk, vv in rows:
                    assert committed.get(kk) == vv
                scans += 1
                await asyncio.sleep(0.1)

        writers = [asyncio.ensure_future(writer(w)) for w in range(2)]
        sc = asyncio.ensure_future(scanner())
        await sim.wait_state(lambda s: s.get("seq", 0) > 0
                             and len(s["shard_teams"]) > n_shards)
        await asyncio.sleep(2.0)          # scans continue post-flip
        stop.set()
        await asyncio.gather(*writers, sc)
        assert scans > 0
        # final scan after the split: exactly the committed keyspace
        tr = db.create_transaction()
        while True:
            try:
                rows = await tr.get_range(b"sc", b"sd", snapshot=True)
                break
            except BaseException as e:
                await tr.on_error(e)
        assert sorted(rows) == sorted(committed.items()), \
            f"{len(rows)} scanned vs {len(committed)} committed"
        await sim.stop()

    run_simulation(main(), seed=11)


# --- backup: zero-copy columns + expire-before GC ---

def test_kvr_bytes_identical_columns_vs_tuples(tmp_path):
    """write_snapshot_page fed the packed replies' columns produces the
    byte-identical .kvr frame the tuple-list path always wrote."""
    from foundationdb_tpu.backup.container import BackupContainer
    from foundationdb_tpu.runtime.files import SimFileSystem

    async def main():
        fs = SimFileSystem()
        rows = [(b"a%03d" % i, b"val%05d" % (i * 7)) for i in range(200)]
        c1 = BackupContainer(fs, "tup")
        c2 = BackupContainer(fs, "col")
        await c1.init()
        await c2.init()
        await c1.write_snapshot_page(9, 0, rows)
        await c2.write_snapshot_page(9, 0, PackedRows.from_rows(rows))
        f1 = fs.open("tup/snap-%020d-%06d.kvr" % (9, 0))
        f2 = fs.open("col/snap-%020d-%06d.kvr" % (9, 0))
        b1 = await f1.read(0, f1.size())
        b2 = await f2.read(0, f2.size())
        assert b1 == b2 and len(b1) > 0
        # and both read back to the same rows
        _v, got = await c2.read_snapshot_page(
            "snap-%020d-%06d.kvr" % (9, 0))
        assert got == rows

    asyncio.run(main())


def test_paged_snapshot_columns_matches_rows():
    from foundationdb_tpu.backup.stream import paged_snapshot
    from foundationdb_tpu.client.database import Database

    async def main():
        c = _seed_cluster(shards=2)
        c.start()
        rows = {b"s%04d" % i: b"v%04d" % i for i in range(250)}
        await _load(c, rows)
        db = Database(c)
        flat_rows, flat_cols = [], []
        async for page, _v in paged_snapshot(db, b"", b"\xff", 64):
            flat_rows.extend(page)
        async for page, _v in paged_snapshot(db, b"", b"\xff", 64,
                                             columns=True):
            assert isinstance(page, PackedRows)
            flat_cols.extend(page)
        assert flat_cols == flat_rows == sorted(rows.items())
        await c.stop()

    asyncio.run(main())


def test_expire_data_before():
    """expire_data_before drops the snapshots + log prefix no target at
    or after ``version`` can need, keeps restore-to-version working
    above it, and refuses when no snapshot anchors the cut."""
    from foundationdb_tpu.backup.container import (BackupContainer,
                                                   ContainerError)
    from foundationdb_tpu.core.data import MutationBatch, MutationBatchBuilder
    from foundationdb_tpu.runtime.files import SimFileSystem

    def batch(k: bytes, v: bytes) -> MutationBatch:
        b = MutationBatchBuilder()
        b.add(0, k, v)
        return b.finish()

    async def main():
        fs = SimFileSystem()
        c = BackupContainer(fs, "bk")
        await c.init()
        # two snapshots at 100 and 500, log files spanning 101..900
        await c.write_snapshot_page(100, 0, [(b"a", b"1")])
        await c.finish_snapshot(100, ["snap-%020d-%06d.kvr" % (100, 0)],
                                1, 10)
        await c.write_snapshot_page(500, 0, [(b"a", b"5"), (b"b", b"2")])
        await c.finish_snapshot(500, ["snap-%020d-%06d.kvr" % (500, 0)],
                                2, 20)
        files = []
        for seq, (first, last) in enumerate([(101, 300), (301, 500),
                                             (501, 700), (701, 900)]):
            name, _n = await c.write_log_file(
                first, last, seq, [(first, batch(b"a", b"x%d" % first)),
                                   (last, batch(b"b", b"y%d" % last))])
            files.append([first, last, name])
        await c.save_log_manifest({"feed": b"f", "begin": 100,
                                   "through": 900, "files": files,
                                   "bytes": 1, "stopped": True})
        # expire before 600: keep snapshot 500; snapshot 100 and log
        # files ending <= 500 go
        r = await c.expire_data_before(600)
        assert r["kept_snapshot"] == 500
        assert r["dropped_snapshots"] == 1 and r["dropped_log_files"] == 2
        snaps = await c.list_snapshots()
        assert [m["version"] for m in snaps] == [500]
        log = await c.load_log_manifest()
        assert [tuple(f[:2]) for f in log["files"]] == [(501, 700),
                                                        (701, 900)]
        assert log["through"] == 900 and log["expired_before"] == 500
        # the kept window still reads back
        ents = await c.read_log_file(str(log["files"][0][2]))
        assert ents[0][0] == 501
        # a second expire below the kept snapshot refuses — it would
        # orphan the only remaining restore anchor
        with pytest.raises(ContainerError):
            await c.expire_data_before(400)
        # idempotent at the same cut: nothing left to drop
        r2 = await c.expire_data_before(600)
        assert r2["dropped_snapshots"] == 0 and r2["dropped_log_files"] == 0

    asyncio.run(main())


def test_expire_on_live_agent_survives_next_flush():
    """Expiring through a LIVE agent prunes its in-memory file mirror
    too: the next flush must NOT resurrect the deleted .mlog names in
    logs.manifest (the agent is the manifest's only writer while
    tailing), and the expired_before marker must survive rewrites."""
    from foundationdb_tpu.backup.agent import BackupAgent
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.client.transaction import Transaction
    from foundationdb_tpu.runtime.errors import FdbError
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.runtime.knobs import Knobs

    async def main():
        fs = SimFileSystem()
        knobs = Knobs().override(BACKUP_LOG_FLUSH_INTERVAL=0.05)
        src = _seed_cluster(knobs=knobs, shards=2)
        src.start()
        db = Database(src)
        agent = BackupAgent(db, fs, "live-exp")

        async def put(lo, hi):
            tr = Transaction(src)
            last = 0
            for i in range(lo, hi):
                tr.set(b"L%05d" % i, b"v%05d" % i)
                if i % 25 == 24:
                    while True:
                        try:
                            last = await tr.commit()
                            break
                        except FdbError as e:
                            await tr.on_error(e)
                    tr.reset()
            return last

        await put(0, 100)
        await agent.start_continuous()
        await agent.backup()
        v1 = await put(100, 200)
        while agent.log_through < v1:
            await asyncio.sleep(0.05)
        snap2 = await agent.backup()          # newer snapshot: the cut
        log_before = await agent.container.load_log_manifest()
        expired = {str(n) for _f, _l, n in log_before["files"]}
        r = await agent.expire_data_before(snap2.version)
        assert r["dropped_log_files"] >= 1
        log_mid = await agent.container.load_log_manifest()
        expired -= {str(n) for _f, _l, n in log_mid["files"]}
        assert expired, "expire dropped no manifest entries"
        # more traffic → the agent flushes → the manifest is rewritten
        v2 = await put(200, 300)
        while agent.log_through < v2:
            await asyncio.sleep(0.05)
        await agent.stop_continuous(drain_timeout=30.0)
        log = await agent.container.load_log_manifest()
        final_named = {str(n) for _f, _l, n in log["files"]}
        assert not (expired & final_named), \
            f"flush resurrected expired manifest entries: {expired & final_named}"
        assert log.get("expired_before") == r["kept_snapshot"]
        for _f, _l, name in log["files"]:
            assert fs.open(f"live-exp/{name}").size() > 0, \
                f"manifest names missing bytes: {name}"
            ents = await agent.container.read_log_file(str(name))
            assert ents, name
        assert all(l > r["kept_snapshot"] for _f, l, _n in log["files"])
        await src.stop()

    asyncio.run(main())


def test_expire_then_restore_still_byte_identical():
    """End-to-end: backup, expire the old snapshot, restore to a target
    above the cut — byte-identical; restore to a target below the cut
    now refuses (its snapshot is gone)."""
    from foundationdb_tpu.backup.agent import BackupAgent, RestoreError
    from foundationdb_tpu.backup.container import keyspace_digest
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.client.transaction import Transaction
    from foundationdb_tpu.core.data import SYSTEM_PREFIX
    from foundationdb_tpu.runtime.errors import FdbError
    from foundationdb_tpu.runtime.files import SimFileSystem

    async def read_all(cluster):
        tr = Transaction(cluster)
        while True:
            try:
                return await tr.get_range(b"", SYSTEM_PREFIX, snapshot=True)
            except FdbError as e:
                await tr.on_error(e)

    async def main():
        fs = SimFileSystem()
        src = _seed_cluster(shards=2)
        src.start()
        db = Database(src)
        agent = BackupAgent(db, fs, "exp-bk")

        async def put(lo, hi):
            tr = Transaction(src)
            last = 0
            for i in range(lo, hi):
                tr.set(b"e%05d" % i, b"v%05d" % i)
                if i % 50 == 49:
                    last = await tr.commit()
                    tr.reset()
            return last

        await put(0, 100)
        await agent.start_continuous()
        snap1 = await agent.backup()
        await put(100, 200)
        mid = await agent.backup()           # second snapshot, newer
        vt = await put(200, 300)
        while agent.log_through < vt:
            await asyncio.sleep(0.05)
        expected = await read_all(src)
        await agent.stop_continuous(drain_timeout=30.0)
        await src.stop()

        r = await agent.container.expire_data_before(mid.version)
        assert r["kept_snapshot"] == mid.version
        assert r["dropped_snapshots"] == 1

        dst = _seed_cluster(shards=2)
        dst.start()
        agent2 = BackupAgent(Database(dst), fs, "exp-bk")
        await agent2.restore(to_version=vt)
        got = await read_all(dst)
        assert keyspace_digest(got) == keyspace_digest(expected)
        # a target below the cut has lost its snapshot
        with pytest.raises(RestoreError):
            await agent2.restore(to_version=snap1.version)
        await dst.stop()

    asyncio.run(main())


# --- packed get_key selector resolution (ISSUE 11, PROTOCOL_VERSION 716) ---

def test_get_key_wire_roundtrip_and_716_fence():
    from foundationdb_tpu.core.cluster_client import RecoveredClusterView
    from foundationdb_tpu.core.data import GetKeyReply, GetKeyRequest
    from foundationdb_tpu.rpc.wire import decode, encode
    from foundationdb_tpu.runtime.errors import ClusterVersionChanged
    req = GetKeyRequest(b"a", b"zz", 42, 7, True)
    assert decode(encode(req)) == req
    rep = GetKeyReply(0, 7, b"found-key")
    assert decode(encode(rep)) == rep
    ref = decode(encode(GetKeyReply(GV_TOO_OLD, 0, b"")))
    assert ref.status == GV_TOO_OLD and ref.count == 0
    new = Knobs()
    # 716 introduced the get_key structs; 717 renumbered the colliding
    # coordination error codes (ISSUE 12) — the fence below only needs
    # "older peer is refused", so pin the floor, not the exact version
    assert new.PROTOCOL_VERSION >= 716
    old = new.override(PROTOCOL_VERSION=715)
    state = {"epoch": 1, "seq": 0, "protocol": new.PROTOCOL_VERSION}
    with pytest.raises(ClusterVersionChanged):
        RecoveredClusterView(old, None, state)


def test_get_key_selector_equivalence_randomized():
    """Packed selector resolution vs a reference computed from the full
    sorted keyspace: every selector family, offsets walking across the
    3-shard split, off-both-ends clamps — and the RYW fallback (buffered
    writes/clears visible, exactly the legacy merge's answers)."""
    from foundationdb_tpu.client.transaction import Transaction
    from foundationdb_tpu.core.data import KeySelector, key_after

    async def main():
        cluster = _seed_cluster(shards=3)
        cluster.start()
        rng = random.Random(77)
        keys = sorted({krand(rng) for _ in range(350)})
        await _load(cluster, {k: b"v" for k in keys})

        def ref(sel, ks):
            k, oe, off = sel.key, sel.or_equal, sel.offset
            if off > 0:
                start = key_after(k) if oe else k
                import bisect as _b
                i = _b.bisect_left(ks, start) + off - 1
                return ks[i] if i < len(ks) else b"\xff"
            stop = key_after(k) if oe else k
            import bisect as _b
            n = 1 - off
            i = _b.bisect_left(ks, stop) - n
            return ks[i] if i >= 0 else b""

        tr = Transaction(cluster)
        sels = [KeySelector.first_greater_or_equal(b""),
                KeySelector.last_less_than(b"\xfe"),
                KeySelector.first_greater_than(keys[-1]),
                KeySelector.last_less_or_equal(keys[0]) - 1,
                KeySelector.first_greater_or_equal(keys[0]) + len(keys)]
        for _ in range(140):
            anchor = rng.choice([rng.choice(keys), krand(rng), b"",
                                 b"k03"])
            sels.append(KeySelector(anchor, rng.random() < 0.5,
                                    rng.randrange(-250, 251)))
        for sel in sels:
            got = await tr.get_key(sel, snapshot=True)
            assert got == ref(sel, keys), sel

        # RYW fallback: buffered writes force the legacy merge
        tr2 = Transaction(cluster)
        tr2.set(b"zz-after-everything", b"w")
        got = await tr2.get_key(KeySelector.last_less_than(b"\xfe"),
                                snapshot=True)
        assert got == b"zz-after-everything"
        tr2.clear_range(keys[0], key_after(keys[2]))
        model = sorted((set(keys) - set(keys[:3]))
                       | {b"zz-after-everything"})
        for sel in sels[:40]:
            got = await tr2.get_key(sel, snapshot=True)
            assert got == ref(sel, model), sel
        await cluster.stop()

    asyncio.run(main())


def test_get_key_replica_failover_on_refusal():
    from foundationdb_tpu.core.data import GetKeyReply, GetKeyRequest
    from foundationdb_tpu.core.load_balance import ReplicaGroup

    class _Stub:
        tag = 0

        def __init__(self, reply):
            self._r = reply

        async def get_key(self, req):
            return self._r

    async def main():
        good = GetKeyReply(0, 3, b"resolved")
        for bad_code in (GV_TOO_OLD, GV_FUTURE_VERSION, GV_WRONG_SHARD):
            bad = GetKeyReply(bad_code, 0, b"")
            shard = KeyRange(b"", b"\xff")
            g = ReplicaGroup(shard, [_Stub(bad), _Stub(good)])
            rep = await g.get_key(GetKeyRequest(b"", b"\xff", 10, 3))
            assert rep.status == 0 and rep.key == b"resolved"
            g2 = ReplicaGroup(shard, [_Stub(bad), _Stub(bad)])
            rep2 = await g2.get_key(GetKeyRequest(b"", b"\xff", 10, 3))
            assert rep2.status == bad_code

    asyncio.run(main())


def test_get_key_storage_counts_and_fences():
    """The storage get_key: exact n-th-live-row counts under an MVCC
    overlay with tombstones, residual counts when the clip runs dry,
    and the wholesale too-old refusal."""
    from foundationdb_tpu.core.data import GetKeyReply, GetKeyRequest
    from foundationdb_tpu.core.storage_server import StorageServer
    from foundationdb_tpu.core.tlog import TLog

    async def main():
        knobs = Knobs()
        ss = StorageServer(knobs, 0, KeyRange(b"", b"\xff"), TLog(knobs))
        ss._apply_batch([(1, __import__(
            "foundationdb_tpu.core.data", fromlist=["MutationBatch"]
        ).MutationBatch.from_mutations(
            [Mutation.set(b"g%03d" % i, b"v") for i in range(20)]))])
        ss._apply_batch([(2, __import__(
            "foundationdb_tpu.core.data", fromlist=["MutationBatch"]
        ).MutationBatch.from_mutations(
            [Mutation.clear_range(b"g005", b"g010")]))])
        live = [b"g%03d" % i for i in range(20) if not 5 <= i < 10]
        # forward: n-th live row
        rep = await ss.get_key(GetKeyRequest(b"", b"\xff", 2, 3, False))
        assert isinstance(rep, GetKeyReply)
        assert (rep.status, rep.count, rep.key) == (0, 3, live[2])
        # reading BELOW the clear still sees the old rows
        rep = await ss.get_key(GetKeyRequest(b"", b"\xff", 1, 7, False))
        assert (rep.count, rep.key) == (7, b"g006")
        # reverse: n-th from the end
        rep = await ss.get_key(GetKeyRequest(b"", b"\xff", 2, 2, True))
        assert (rep.count, rep.key) == (2, live[-2])
        # clip runs dry: count reports the residual, no key
        rep = await ss.get_key(GetKeyRequest(b"g012", b"\xff", 2, 99, False))
        assert (rep.status, rep.count, rep.key) == (0, 8, b"")
        # wholesale too-old refusal
        ss.oldest_version = 10
        rep = await ss.get_key(GetKeyRequest(b"", b"\xff", 2, 1, False))
        assert rep.status == GV_TOO_OLD

    asyncio.run(main())
