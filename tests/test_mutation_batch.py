"""MutationBatch — the packed columnar commit-pipeline wire form.

Encode/decode properties (empty batch, zero-length values, CLEAR_RANGE
ends, versionstamp ops, 64KB+ blobs), the PROTOCOL_VERSION 712 fence, the
packed-apply == per-Mutation-apply equivalence on randomized workloads,
and recovery equivalence across the frame-format change (old tuple/list
frames ↔ new packed frames) for both the TLog DiskQueue and the memory
engine WAL.
"""

import pytest

from foundationdb_tpu.core.data import (KeyRange, Mutation, MutationBatch,
                                        MutationBatchBuilder, MutationType,
                                        as_mutation_batch)
from foundationdb_tpu.rpc.wire import decode, encode
from foundationdb_tpu.runtime import DeterministicRandom
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


def random_mutations(rng, n, atomics=False):
    muts = []
    for _ in range(n):
        k = bytes(rng.random_int(0, 256) for _ in range(rng.random_int(1, 12)))
        roll = rng.random_int(0, 10)
        if roll < 6 or not atomics:
            muts.append(Mutation.set(k, b"v" * rng.random_int(0, 20)))
        elif roll < 8:
            muts.append(Mutation.clear_range(k, k + b"\xff"))
        else:
            muts.append(Mutation(MutationType.ADD, k, b"\x01" * 8))
    return muts


# --- encode/decode properties ---

def test_empty_batch():
    mb = MutationBatch.from_mutations([])
    assert len(mb) == 0 and not mb and mb.nbytes == 0
    assert mb.simple_only
    assert list(mb) == []
    out = decode(encode(mb))
    assert out == mb


def test_zero_length_values_and_keys():
    muts = [Mutation.set(b"k", b""), Mutation.set(b"", b""),
            Mutation.set(b"", b"v")]
    mb = MutationBatch.from_mutations(muts)
    assert list(mb) == muts
    assert decode(encode(mb)) == mb
    assert mb.nbytes == 2
    assert mb.set_payload_bytes() == 2
    assert [mb.param1(i) for i in range(3)] == [b"k", b"", b""]
    assert [mb.param2(i) for i in range(3)] == [b"", b"", b"v"]


def test_clear_range_ends():
    muts = [Mutation.clear_range(b"a", b"b\x00"),
            Mutation.clear_range(b"", b"\xff\xff\xff"),
            Mutation.clear_range(b"x", b"x")]
    mb = MutationBatch.from_mutations(muts)
    assert list(mb) == muts
    assert [mb.param1(i) for i in range(3)] == [b"a", b"", b"x"]
    assert [mb.param2(i) for i in range(3)] == [b"b\x00", b"\xff\xff\xff", b"x"]
    assert mb.simple_only
    assert mb.set_payload_bytes() == 0


def test_versionstamp_and_private_ops_not_simple():
    muts = [Mutation.set(b"a", b"1"),
            Mutation(MutationType.SET_VERSIONSTAMPED_KEY, b"k" * 14, b"v"),
            Mutation(MutationType.PRIVATE_DROP_SHARD, b"a", b"z")]
    mb = MutationBatch.from_mutations(muts)
    assert not mb.simple_only
    assert list(mb) == muts
    assert decode(encode(mb)) == mb
    assert mb[-1].type == MutationType.PRIVATE_DROP_SHARD


def test_large_blob_roundtrip():
    big = bytes(range(256)) * 256 + b"tail"          # > 64KB
    muts = [Mutation.set(b"big%03d" % i, big) for i in range(3)]
    mb = MutationBatch.from_mutations(muts)
    assert mb.nbytes > 3 * (1 << 16)
    assert decode(encode(mb)) == mb
    assert list(decode(encode(mb))) == muts


@pytest.mark.parametrize("seed", range(4))
def test_random_roundtrip_and_accessors(seed):
    rng = DeterministicRandom(seed)
    muts = random_mutations(rng, rng.random_int(1, 120), atomics=True)
    mb = MutationBatch.from_mutations(muts)
    assert len(mb) == len(muts)
    assert mb.nbytes == sum(len(m.param1) + len(m.param2) for m in muts)
    assert mb.set_payload_bytes() == sum(
        len(m.param1) + len(m.param2) for m in muts
        if m.type == MutationType.SET_VALUE)
    for i, m in enumerate(muts):
        assert mb[i] == m
    assert decode(encode(mb)) == mb
    assert list(as_mutation_batch(muts)) == muts
    assert as_mutation_batch(mb) is mb


@pytest.mark.parametrize("seed", range(3))
def test_select_slices(seed):
    rng = DeterministicRandom(100 + seed)
    muts = random_mutations(rng, 60, atomics=True)
    mb = MutationBatch.from_mutations(muts)
    idxs = [i for i in range(len(muts)) if rng.random_int(0, 2)]
    sub = mb.select(idxs)
    assert list(sub) == [muts[i] for i in idxs]
    # selecting everything is the zero-copy identity
    assert mb.select(list(range(len(muts)))) is mb


def test_select_duplicates_are_not_identity():
    """A same-LENGTH index list with duplicates (a backup tag colliding
    with a storage tag) must slice for real — the identity shortcut
    would leak other tags' mutations (incl. PRIVATE_DROP_SHARD) to the
    wrong storage server."""
    muts = [Mutation.set(b"k", b"v"),
            Mutation(MutationType.PRIVATE_DROP_SHARD, b"a", b"z")]
    mb = MutationBatch.from_mutations(muts)
    dup = mb.select([0, 0])
    assert dup is not mb
    assert list(dup) == [muts[0], muts[0]]


def test_builder_indices():
    b = MutationBatchBuilder()
    assert b.add(0, b"k1", b"v1") == 0
    assert b.add(1, b"a", b"z") == 1
    mb = b.finish()
    assert mb[0] == Mutation.set(b"k1", b"v1")
    assert mb[1] == Mutation.clear_range(b"a", b"z")


# --- the protocol fence (711 peer must be refused) ---

def test_version_gate_fences_711_peer():
    from foundationdb_tpu.core.cluster_client import RecoveredClusterView
    from foundationdb_tpu.runtime.errors import ClusterVersionChanged
    new = Knobs()
    # 712 introduced the packed MutationBatch; later protocol bumps
    # (713 change feeds) must keep fencing a pre-712 peer
    assert new.PROTOCOL_VERSION >= 712
    old = new.override(PROTOCOL_VERSION=711)
    state = {"epoch": 1, "seq": 0, "protocol": new.PROTOCOL_VERSION}
    with pytest.raises(ClusterVersionChanged):
        RecoveredClusterView(old, None, state)


# --- packed apply == per-Mutation apply (randomized) ---

def make_storage(knobs):
    from foundationdb_tpu.core.storage_server import StorageServer
    from foundationdb_tpu.core.tlog import TLog
    return StorageServer(knobs, 0, KeyRange(b"", b"\xff"), TLog(knobs))


@pytest.mark.parametrize("seed", range(4))
def test_packed_apply_equivalence(seed):
    """_apply_batch over MutationBatch entries (columnar fast path +
    lazy fallbacks) must produce the identical MVCC state as the same
    entries applied as Mutation lists."""
    async def main():
        rng = DeterministicRandom(seed)
        knobs = Knobs()
        ss_list = make_storage(knobs)
        ss_packed = make_storage(knobs)
        version = 0
        all_entries = []
        for _ in range(12):
            version += rng.random_int(1, 5)
            muts = random_mutations(rng, rng.random_int(1, 40), atomics=True)
            all_entries.append((version, muts))
        for v, muts in all_entries:
            ss_list._apply_batch([(v, list(muts))])
        # packed side: whole reply in one call, like the pull loop
        ss_packed._apply_batch(
            [(v, MutationBatch.from_mutations(muts))
             for v, muts in all_entries])
        assert ss_list.vmap.keys() == ss_packed.vmap.keys()
        for probe_v in (version, version - 2, 1):
            for k in ss_list.vmap.keys():
                assert ss_list.vmap.get2(k, probe_v) == \
                    ss_packed.vmap.get2(k, probe_v), (k, probe_v)
        assert ss_list.bytes_input == ss_packed.bytes_input
        assert ss_list.logical_bytes == ss_packed.logical_bytes
        assert ss_list.version == ss_packed.version
    run_simulation(main())


def test_packed_apply_respects_armed_watches():
    """An armed watch forces the per-item path so it still fires."""
    import asyncio

    async def main():
        ss = make_storage(Knobs())
        ss._apply_batch([(1, MutationBatch.from_mutations(
            [Mutation.set(b"w", b"a")]))])
        fut = asyncio.get_running_loop().create_task(
            ss.watch_value(b"w", b"a", 1))
        await asyncio.sleep(0)
        assert not fut.done()
        ss._apply_batch([(2, MutationBatch.from_mutations(
            [Mutation.set(b"w", b"b")]))])
        await asyncio.sleep(0)
        assert fut.done() and fut.exception() is None
    run_simulation(main())


# --- durability ring slices (satellite: engine receives packed slices) ---

def test_durability_ring_slices_and_rollback():
    from foundationdb_tpu.storage.packed_ops import DurabilityRing
    ring = DurabilityRing()
    ring.append(1, 0, b"a", b"1")
    ring.extend_packed(2, MutationBatch.from_mutations(
        [Mutation.set(b"b", b"2"), Mutation.clear_range(b"c", b"d")]))
    ring.append(3, 0, b"e", b"3")
    assert len(ring) == 4
    ops = ring.peek_memory_through(2)
    assert [(op, p1, p2) for op, p1, p2 in ops] == [
        (0, b"a", b"1"), (0, b"b", b"2"), (1, b"c", b"d")]
    assert ops.nbytes == 6
    # peek is non-destructive (failed engine commit retries the slice)
    assert [(op, p1, p2) for op, p1, p2 in ring.peek_memory_through(2)] == \
        [(0, b"a", b"1"), (0, b"b", b"2"), (1, b"c", b"d")]
    ring.pop_memory_through(2)
    assert [(op, p1, p2) for op, p1, p2 in ring.peek_memory_through(99)] == \
        [(0, b"e", b"3")]
    ring.append(4, 0, b"f", b"4")
    ring.rollback_after(3)
    assert [(op, p1, p2) for op, p1, p2 in ring.peek_memory_through(99)] == \
        [(0, b"e", b"3")]


# --- recovery equivalence: old frames ↔ new frames ---

def test_tlog_recovers_old_format_frames():
    """A DiskQueue written before the 712 packed format (frames holding
    Mutation lists) must recover into the same peekable state as one
    written with packed frames."""
    from foundationdb_tpu.core.tlog import TLog, TLogPushRequest
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.storage.disk_queue import DiskQueue

    async def main():
        knobs = Knobs()
        fs = SimFileSystem()
        muts = {1: [Mutation.set(b"k1", b"v1")],
                2: [Mutation.set(b"k2", b"v2"),
                    Mutation.clear_range(b"a", b"b")]}
        # old-format frames, synthesized exactly as the pre-712 TLog
        # wrote them: {"v": version, "m": {tag: [Mutation, ...]}}
        q, _ = await DiskQueue.open(fs.open("old.dq"))
        for v, ms in muts.items():
            await q.push(encode({"v": v, "m": {0: ms}}))
        await q.commit(meta=2)
        # new-format frames via the live push path
        new = await TLog.open(knobs, fs, "new.dq")
        for v, ms in muts.items():
            await new.push(TLogPushRequest(v - 1, v, {0: list(ms)}))
        old = await TLog.open(knobs, fs, "old.dq")
        r_old = await old.peek(0, 1)
        r_new = await new.peek(0, 1)
        assert [(v, list(ms)) for v, ms in r_old.entries] == \
            [(v, list(ms)) for v, ms in r_new.entries]
        assert old.version == 2
        # spilled re-reads decode old frames too
        old._log[0].evict_below(2)
        r_spill = await old.peek(0, 1)
        assert [(v, list(ms)) for v, ms in r_spill.entries] == \
            [(v, list(ms)) for v, ms in r_new.entries]
    run_simulation(main())


def test_kv_store_recovers_old_and_new_wal_frames():
    """The memory engine must replay pre-712 tuple-list WAL frames and
    712 packed frames to the same recovered state."""
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.storage.disk_queue import DiskQueue
    from foundationdb_tpu.storage.kv_store import MemoryKVStore
    from foundationdb_tpu.storage.packed_ops import DurabilityRing

    ops = [(0, b"k1", b"v1"), (0, b"k2", b"v2"), (1, b"k1", b"k2"),
           (0, b"k3", b"v3")]

    async def main():
        fs = SimFileSystem()
        # old format: hand-write a tuple-list frame into the WAL
        q, _ = await DiskQueue.open(fs.open("old.wal"))
        await q.push(encode({"gen": 0, "ops": ops, "meta": {"dv": 7}}))
        await q.commit()
        old = await MemoryKVStore.open(fs, "old")
        # new format: commit the packed slice through the engine
        ring = DurabilityRing()
        for op, p1, p2 in ops:
            ring.append(7, op, p1, p2)
        new = await MemoryKVStore.open(fs, "new")
        await new.commit(await ring.peek_through(7), {"dv": 7})
        new2 = await MemoryKVStore.open(fs, "new")   # replay packed frame
        for kv in (old, new, new2):
            assert kv.get(b"k1") is None
            assert kv.get(b"k2") == b"v2"
            assert kv.get(b"k3") == b"v3"
            assert list(kv.range(b"", b"\xff")) == [(b"k2", b"v2"),
                                                    (b"k3", b"v3")]
            assert kv.meta == {"dv": 7}
    run_simulation(main())
