"""Machine-level simulation: attrition + clogging + BUGGIFY under invariants.

The pytest face of the seed farm (tools/seed_farm.py runs the wide
version; ``python -m foundationdb_tpu.sim.run_one --seed N`` replays one).
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.runtime.buggify import enable_buggify
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.run_one import simulate


@pytest.fixture(autouse=True)
def _buggify_off_after():
    yield
    enable_buggify(False)


@pytest.mark.parametrize("seed", [0, 3, 17])
def test_attrition_clogging_buggify_invariants(seed):
    """A full chaos run: machine kills (including the CC's machine),
    random clogging/partitions and BUGGIFY rare paths, concurrent with
    Cycle + Serializability invariant workloads.  Any lost/phantom/
    reordered write fails the check phase."""
    results = run_simulation(simulate(seed, kills=2, buggify=True), seed=seed)
    assert results["MachineAttrition"]["machines_killed"] == 2
    assert results["Cycle"]["transactions"] == 60
    assert results["Serializability"]["committed"] > 0


def test_sim_runs_without_buggify():
    results = run_simulation(simulate(101, kills=1, buggify=False), seed=101)
    assert results["MachineAttrition"]["machines_killed"] == 1
