"""Machine-level simulation: attrition + clogging + BUGGIFY under invariants.

The pytest face of the seed farm (tools/seed_farm.py runs the wide
version; ``python -m foundationdb_tpu.sim.run_one --seed N`` replays one).
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.runtime.buggify import enable_buggify
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.run_one import simulate


@pytest.fixture(autouse=True)
def _buggify_off_after():
    yield
    enable_buggify(False)


@pytest.mark.parametrize("seed", [0, 3, 17])
def test_attrition_clogging_buggify_invariants(seed):
    """A full chaos run: machine kills (including the CC's machine),
    random clogging/partitions and BUGGIFY rare paths, concurrent with
    Cycle + Serializability invariant workloads.  Any lost/phantom/
    reordered write fails the check phase."""
    results = run_simulation(simulate(seed, kills=2, buggify=True), seed=seed)
    # at least one kill must land; with DD live moves in the mix the
    # storage placement shifts mid-run and a round may find no eligible
    # victim (storage-hosting machines are protected) — the INVARIANTS
    # are the assertion, not the exact kill count
    assert results["MachineAttrition"]["machines_killed"] >= 1
    assert results["Cycle"]["transactions"] == 60
    assert results["Serializability"]["committed"] > 0


def test_sim_runs_without_buggify():
    results = run_simulation(simulate(101, kills=1, buggify=False), seed=101)
    assert results["MachineAttrition"]["machines_killed"] == 1


def test_storage_machine_reboot_rejoins_with_disk():
    """Durable storage lifecycle: kill a machine hosting a storage
    replica, reboot it, and the controller must ADOPT the on-disk replica
    back (worker reopens engines, reports residency, recovery rejoins) —
    reads keep working throughout via team failover and the restored
    replica converges (ConsistencyCheck-grade equality)."""
    import asyncio

    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.runtime.errors import FdbError
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        k = Knobs().override(STORAGE_DURABILITY_LAG=0.1,
                             STORAGE_VERSION_WINDOW=1000)
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6,
                                                      replication=2),
                               durable_storage=True)
        await sim.start()
        state = await sim.wait_epoch(1)
        db = await sim.database()

        items = {b"dur%03d" % i: b"v%03d" % i for i in range(40)}

        async def fill(tr):
            for key, v in items.items():
                tr.set(key, v)
        await db.run(fill)
        # let a durability tick persist shard meta + data
        await asyncio.sleep(1.0)

        # kill a machine hosting a storage replica (but not a coordinator)
        storage_ips = {s["worker"][0] for s in state["storage"]}
        victim = next(m for m in sim.machines
                      if m.ip in storage_ips and not m.is_coordinator)
        victim_tags = [s["tag"] for s in state["storage"]
                       if s["worker"][0] == victim.ip]
        await victim.kill()

        # reads fail over to the surviving replica meanwhile
        async def read_some(tr):
            return await tr.get(b"dur001")
        assert await db.run(read_some) == b"v001"

        await asyncio.sleep(1.0)
        await victim.reboot()

        # the rebooted worker reports its resident tags; the CC adopts
        # them at the requested recovery
        new_tokens = None
        deadline = asyncio.get_running_loop().time() + 60
        adopted = False
        while asyncio.get_running_loop().time() < deadline:
            new_tokens = dict(victim.host.worker.resident) \
                if victim.host else {}
            st = await sim.wait_epoch(1)
            owners = {s["tag"]: (s["worker"][0], s["token"])
                      for s in st["storage"]}
            if new_tokens and all(
                    owners.get(t) == (victim.ip, new_tokens.get(t))
                    for t in victim_tags):
                adopted = True
                break
            await asyncio.sleep(0.5)
        assert adopted, f"never adopted; owners={owners} res={new_tokens}"

        # write fresh data, then verify BOTH replicas of the victim's team
        # serve identical full content (the restored one caught up)
        items2 = {b"post%03d" % i: b"w%03d" % i for i in range(10)}

        async def fill2(tr):
            for key, v in items2.items():
                tr.set(key, v)
        await db.run(fill2)
        await asyncio.sleep(2.0)

        st = await sim.wait_epoch(1)
        await db.refresh()
        view = db.view
        tr = db.create_transaction()
        while True:
            try:
                version = await tr.get_read_version()
                break
            except Exception as e:  # noqa: BLE001
                await tr.on_error(e)
        for rng, tags in view.shard_map.ranges():
            group = view.storage_for_key(rng.begin)
            replicas = getattr(group, "replicas", [group])
            results = []
            for rep in replicas:
                deadline2 = asyncio.get_running_loop().time() + 30
                while True:
                    try:
                        rows, _ = await rep.get_key_values(
                            rng.begin, rng.end, version, 1000)
                        break
                    except FdbError:
                        # the restored replica is still catching up from
                        # the logs; a fixed-version read waits it out
                        assert asyncio.get_running_loop().time() < deadline2, \
                            f"replica tag {rep.tag} never caught up"
                        await asyncio.sleep(0.5)
                results.append([(bytes(kv[0]), bytes(kv[1])) for kv in rows])
            for other in results[1:]:
                assert other == results[0], f"replica divergence in {tags}"
        await sim.stop()
    run_simulation(main())
