"""Columnar key runs + the memory walls (ISSUE 11).

Four surfaces under test:

- ``KeyRun`` — the shared columnar sorted-run layout — against plain
  sorted-list reference semantics on randomized keyspaces, including
  the adversarial shared-8-byte-prefix shape where the u64 bands
  collapse to the whole run;
- ``PackedKeyIndex`` columnar mode against the retained list mode: the
  SAME randomized op stream must produce identical query results AND
  the identical ``gen``/merge schedule (the device-mirror contract);
- the lsm sparse index on ``KeyRun``: parity after reopen, the merged
  ``packed_index`` directory's block choices (``get_batch_located``
  equal to ``get_batch``), and its gen bumps on run-set changes only;
- ``DurabilityRing`` disk spill: spill→peek→pop round-trips
  bit-identical to the memory-only ring, rejoin rollback over a spilled
  suffix, torn side-file frames (dead frames harmless, live corruption
  LOUD), and the acceptance sim — a storage server whose engine commits
  are throttled below the ingest rate keeps retained ring memory under
  the knob budget via live spill, with the drained keyspace
  byte-identical to the expected rows.
"""

from __future__ import annotations

import asyncio
import bisect
import hashlib
import random

import pytest

from foundationdb_tpu.core.data import Mutation, MutationBatch
from foundationdb_tpu.runtime.files import SimFileSystem
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.storage.disk_queue import DiskQueue
from foundationdb_tpu.storage.key_index import PackedKeyIndex
from foundationdb_tpu.storage.key_runs import KeyRun
from foundationdb_tpu.storage.packed_ops import DurabilityRing


def _rand_keys(rng: random.Random, n: int, shared_prefix: bytes = b""
               ) -> list[bytes]:
    out = {shared_prefix + bytes(rng.randrange(97, 123)
                                 for _ in range(rng.randrange(1, 14)))
           for _ in range(n)}
    return sorted(out)


# --------------------------------------------------------------------------
# KeyRun vs sorted-list reference


@pytest.mark.parametrize("prefix", [b"", b"sameprefix-8plus-"])
def test_key_run_matches_list_reference(prefix):
    """Sequence protocol, bisects, batched bisects, merge and delete all
    agree with the plain sorted list — including when every key shares
    its first 8+ bytes (the u64 prefix bands cover the whole run and
    only the monotone refinement is left)."""
    rng = random.Random(11)
    keys = _rand_keys(rng, 4000, prefix)
    r = KeyRun.from_keys(keys)
    assert len(r) == len(keys)
    assert r.to_list() == keys
    assert list(r) == keys
    assert r == keys
    assert r[7] == keys[7] and r[-1] == keys[-1]
    assert r[13:57] == keys[13:57]
    assert KeyRun.from_keys(keys) == r
    probes = (_rand_keys(rng, 500, prefix) + keys[::17]
              + [b"", b"\xff", keys[0], keys[-1] + b"\x00"])
    for k in probes[:64]:
        assert r.bisect_left(k) == bisect.bisect_left(keys, k)
        assert r.bisect_right(k) == bisect.bisect_right(keys, k)
        assert (k in r) == (k in keys)
    assert r.batch_bisect(probes) == \
        [bisect.bisect_left(keys, k) for k in probes]
    sp = sorted(probes)
    assert r.batch_bisect(sp, sorted_keys=True) == \
        [bisect.bisect_left(keys, k) for k in sp]
    assert r.batch_bisect(sp, "right", sorted_keys=True) == \
        [bisect.bisect_right(keys, k) for k in sp]
    # prefixes match the one keycode home
    import numpy as np

    from foundationdb_tpu.ops.keycode import encode_prefix_u64
    assert np.array_equal(r.prefixes(), encode_prefix_u64(keys))
    # merge and delete
    fresh = sorted(set(_rand_keys(rng, 900, prefix + b"Z")) - set(keys))
    m = r.merge_sorted(fresh)
    assert m.to_list() == sorted(keys + fresh)
    dead = rng.sample(keys, 700) + [prefix + b"zzz-not-there"]
    d, removed = m.delete_keys(dead)
    assert removed == 700
    assert d.to_list() == sorted(set(keys + fresh) - set(dead))
    # immutability: the originals are untouched
    assert r.to_list() == keys
    assert m.to_list() == sorted(keys + fresh)


def test_key_run_empty_and_duplicate_edges():
    e = KeyRun()
    assert len(e) == 0 and not e and e.to_list() == []
    assert e.bisect_left(b"x") == 0
    assert e.merge_sorted([b"a", b"b"]).to_list() == [b"a", b"b"]
    assert e.delete_keys([b"a"]) == (e, 0)
    assert KeyRun.from_keys([]).to_list() == []
    # directory uses keep duplicates (lsm merged sparse index)
    dup = KeyRun.from_keys([b"a", b"b", b"b", b"c"])
    assert dup.to_list() == [b"a", b"b", b"b", b"c"]
    assert dup.bisect_left(b"b") == 1
    assert dup.bisect_right(b"b") == 3


# --------------------------------------------------------------------------
# PackedKeyIndex: columnar vs list mode, one op stream


def _drive_index(columnar: bool, seed: int) -> list:
    rng = random.Random(seed)
    idx = PackedKeyIndex(columnar=columnar)
    model: set[bytes] = set()
    trace: list = []
    for _step in range(250):
        op = rng.randrange(5)
        if op <= 1:
            fresh = sorted({b"ik%06d" % rng.randrange(40000)
                            for _ in range(rng.randrange(1, 300))} - model)
            if op == 0:
                idx.add_many(fresh)
            else:
                for k in fresh:
                    idx.add(k)
            model |= set(fresh)
        elif op == 2 and model:
            dead = rng.sample(sorted(model),
                              min(len(model), rng.randrange(1, 120)))
            idx.discard_many(dead + [b"zz-missing"])
            model -= set(dead)
        elif op == 3:
            b, e = sorted(b"ik%06d" % rng.randrange(40000)
                          for _ in range(2))
            trace.append(tuple(idx.keys_in_range(b, e)))
        else:
            ranges = [tuple(sorted(b"ik%06d" % rng.randrange(40000)
                                   for _ in range(2)))
                      for _ in range(rng.randrange(1, 24))]
            trace.append(tuple(map(tuple, idx.ranges_keys(ranges))))
        trace.append((len(idx), idx.gen, idx.merges,
                      b"ik%06d" % rng.randrange(40000) in idx))
    trace.append(tuple(idx.to_list()))
    trace.append(tuple(idx.base_run()))
    trace.append(tuple(idx.pending_run()))
    return trace


def test_packed_key_index_columnar_equals_list_mode():
    """Identical op stream → identical results, identical gen/merge
    schedule (what the device mirror's staleness contract keys on)."""
    for seed in (1, 2, 3):
        assert _drive_index(True, seed) == _drive_index(False, seed)


def test_packed_key_index_columnar_base_is_key_run():
    idx = PackedKeyIndex()
    idx.add_many([b"k%04d" % i for i in range(3000)])
    idx._merge()
    assert isinstance(idx.base_run(), KeyRun)
    assert idx.stats()["base_bytes"] > 0
    assert idx.stats()["columnar"] is True
    # the legacy twin reports no columnar bytes
    lst = PackedKeyIndex(columnar=False)
    lst.add_many([b"a", b"b"])
    assert lst.stats()["base_bytes"] is None


# --------------------------------------------------------------------------
# lsm sparse index on KeyRun


def test_lsm_sparse_index_parity_after_reopen(monkeypatch):
    import foundationdb_tpu.storage.lsm as lsm_mod
    from foundationdb_tpu.storage.lsm import LSMKVStore
    monkeypatch.setattr(lsm_mod, "_MEMTABLE_BYTES", 1500)
    monkeypatch.setattr(lsm_mod, "_BLOCK_BYTES", 200)
    monkeypatch.setattr(lsm_mod, "_MAX_RUNS", 8)

    async def main():
        fs = SimFileSystem()
        kv = await LSMKVStore.open(fs, "db/lsm")
        rng = random.Random(5)
        model: dict[bytes, bytes] = {}
        for round_ in range(10):
            ops = []
            for _ in range(50):
                k = b"k%04d" % rng.randrange(1500)
                v = b"v%06d" % rng.randrange(10 ** 6)
                ops.append((0, k, v))
                model[k] = v
            if rng.random() < 0.5:
                b, e = sorted(b"k%04d" % rng.randrange(1500)
                              for _ in range(2))
                ops.append((1, b, e))
                for k in [k for k in model if b <= k < e]:
                    del model[k]
            await kv.commit(ops, {"durable_version": round_})
        assert len(kv._runs) >= 2, "workload never flushed multiple runs"
        gen0 = kv.packed_index.gen
        assert gen0 > 0                     # flushes bumped the directory

        probes = sorted({b"k%04d" % rng.randrange(1700)
                         for _ in range(500)})
        expected = [model.get(k) for k in probes]

        def check(store):
            # per-run sparse index is a KeyRun
            for run in store._runs:
                assert isinstance(run.first_keys, KeyRun)
                assert run.first_keys.to_list() == \
                    [bytes(e[0]) for e in store_index(run)]
            assert store.get_batch(probes) == expected
            assert [store.get(k) for k in probes] == expected
            # the merged directory's block choice reproduces get_batch
            merged = store.packed_index.base_run()
            pos = [merged.bisect_right(k) for k in probes]
            assert store.get_batch_located(probes, pos) == expected

        def store_index(run):
            return run.index

        check(kv)
        # memtable-only keys resolve through get_batch_located too (the
        # host-side memtable probe — the pending-overlay twin)
        await kv.commit([(0, b"zz-mem-only", b"mv")], {"durable_version": 99})
        merged = kv.packed_index.base_run()
        qs = probes + [b"zz-mem-only"]
        assert kv.get_batch_located(
            qs, [merged.bisect_right(k) for k in qs]) == expected + [b"mv"]
        await kv.close()

        kv2 = await LSMKVStore.open(fs, "db/lsm")
        check(kv2)
        assert kv2.get(b"zz-mem-only") == b"mv"    # WAL replayed
        await kv2.close()

    run_simulation(main())


def test_lsm_packed_index_gen_tracks_run_set_only(monkeypatch):
    import foundationdb_tpu.storage.lsm as lsm_mod
    from foundationdb_tpu.storage.lsm import LSMKVStore
    monkeypatch.setattr(lsm_mod, "_MEMTABLE_BYTES", 600)
    monkeypatch.setattr(lsm_mod, "_MAX_RUNS", 3)

    async def main():
        fs = SimFileSystem()
        kv = await LSMKVStore.open(fs, "db/lsm")
        g0 = kv.packed_index.gen
        # a small commit stays in the memtable: gen must NOT move
        await kv.commit([(0, b"a", b"1")], {"durable_version": 1})
        assert kv.packed_index.gen == g0
        # enough to flush: gen bumps
        ops = [(0, b"k%03d" % i, b"v" * 30) for i in range(40)]
        await kv.commit(ops, {"durable_version": 2})
        assert kv.packed_index.gen > g0
        g1 = kv.packed_index.gen
        # force a compaction (runs > _MAX_RUNS): gen bumps again — the
        # leveled compactor runs in the BACKGROUND (ISSUE 14), so drain
        # it to a debt-free state before asserting the run shape
        for r in range(3, 9):
            await kv.commit([(0, b"c%03d" % i, b"w" * 40)
                             for i in range(40)], {"durable_version": r})
        await kv.wait_compaction_idle()
        assert len(kv._runs) <= 3 + 1
        assert kv.packed_index.gen > g1
        await kv.close()

    run_simulation(main())


# --------------------------------------------------------------------------
# DurabilityRing disk spill


def _batch(i: int, nbytes: int = 24) -> MutationBatch:
    return MutationBatch.from_mutations(
        [Mutation.set(b"rk%06d" % i, b"x" * nbytes)])


def test_ring_spill_peek_pop_round_trip():
    async def main():
        fs = SimFileSystem()
        q, _ = await DiskQueue.open(fs.open("r.dbuf.dq"))
        ring = DurabilityRing(queue=q, spill_bytes=300)
        plain = DurabilityRing()            # the memory-only reference
        expected = []
        for v in range(1, 61):
            b = _batch(v)
            ring.extend_packed(v, b)
            plain.extend_packed(v, b)
            expected.append((0, b"rk%06d" % v, b"x" * 24))
            if ring.needs_spill:
                await ring.maybe_spill()
        assert ring.mem_bytes <= 300
        assert ring.spilled_bytes > 0 and ring.spills > 0
        assert len(ring) == len(plain) == 60
        for floor in (7, 30, 60, 99):
            got = [(op, p1, p2)
                   for op, p1, p2 in await ring.peek_through(floor)]
            ref = [(op, p1, p2)
                   for op, p1, p2 in await plain.peek_through(floor)]
            assert got == ref == expected[:min(floor, 60)]
        # pop releases the disk prefix; the remainder still reads back
        await ring.pop_through(25)
        await plain.pop_through(25)
        got = [(op, p1, p2) for op, p1, p2 in await ring.peek_through(99)]
        assert got == expected[25:]
        assert ring.stats()["dbuf_spilled_frames"] == len(ring._spilled)
        await ring.pop_through(99)
        assert len(ring) == 0 and ring.spilled_bytes == 0

    run_simulation(main())


def test_ring_spill_rollback_and_torn_frames():
    """Rejoin rollback over a spilled suffix: the rolled-back frames'
    bookkeeping drops, their dead bytes are never decoded again (we
    CORRUPT them on disk to prove it), and a torn LIVE frame raises
    loudly at peek instead of committing a short slice."""
    async def main():
        fs = SimFileSystem()
        q, _ = await DiskQueue.open(fs.open("r.dbuf.dq"))
        ring = DurabilityRing(queue=q, spill_bytes=1)   # spill everything
        for v in range(1, 21):
            ring.extend_packed(v, _batch(v))
        await ring.maybe_spill()
        assert ring.mem_bytes <= 1 and len(ring._spilled) == 20
        # rejoin rollback: versions > 12 came from a dead generation
        dead_spans = [(st, en) for vv, st, en, _nb, _o in ring._spilled
                      if vv > 12]
        ring.rollback_after(12)
        assert [t[0] for t in ring._spilled] == list(range(1, 13))
        # corrupt every rolled-back frame on disk — harmless, the
        # bookkeeping no longer names them
        disk = fs.disks["r.dbuf.dq"]
        for st, en in dead_spans:
            for off in range(st, min(en, len(disk))):
                disk[off] ^= 0xFF
        got = [(op, p1, p2) for op, p1, p2 in await ring.peek_through(99)]
        assert got == [(0, b"rk%06d" % v, b"x" * 24) for v in range(1, 13)]
        # appends after the rollback keep version order across the seam
        ring.extend_packed(13, _batch(13))
        got = [p1 for _op, p1, _p2 in await ring.peek_through(99)]
        assert got == [b"rk%06d" % v for v in range(1, 14)]
        # now corrupt a LIVE frame: peek must raise, not short-serve —
        # since ISSUE 12 the DiskQueue itself raises disk_corrupt from
        # read_frames (loud committed-region discipline), upgrading the
        # ring's old IOError-on-empty fallback
        from foundationdb_tpu.runtime.errors import DiskCorrupt
        st, en, = ring._spilled[3][1], ring._spilled[3][2]
        for off in range(st + 8, min(st + 12, len(disk))):
            disk[off] ^= 0xFF
        with pytest.raises((IOError, DiskCorrupt)):
            await ring.peek_through(99)

    run_simulation(main())


def test_ring_spill_failed_push_leaves_state_intact():
    """The fsync-before-drop discipline: a failing side queue mutates no
    bookkeeping — the memory copy survives and a later pass retries."""
    async def main():
        fs = SimFileSystem()
        q, _ = await DiskQueue.open(fs.open("r.dbuf.dq"))
        ring = DurabilityRing(queue=q, spill_bytes=50)
        for v in range(1, 11):
            ring.extend_packed(v, _batch(v))
        mem0 = ring.mem_bytes

        async def boom(_payload):
            raise OSError("disk full")
        orig_push = q.push
        q.push = boom
        with pytest.raises(OSError):
            await ring.maybe_spill()
        assert ring.mem_bytes == mem0 and not ring._spilled
        got = [p1 for _op, p1, _p2 in await ring.peek_through(99)]
        assert got == [b"rk%06d" % v for v in range(1, 11)]
        q.push = orig_push
        assert await ring.maybe_spill() > 0         # retry succeeds
        got = [p1 for _op, p1, _p2 in await ring.peek_through(99)]
        assert got == [b"rk%06d" % v for v in range(1, 11)]

    run_simulation(main())


def test_ring_pop_failure_leaves_bookkeeping_retryable():
    """pop_through does side-file I/O (pop_to: header write, possibly a
    compaction) — a transient failure must leave EVERY piece of
    bookkeeping untouched so the durability loop's retry discipline
    (which now wraps the pop too) re-pops the identical state."""
    async def main():
        fs = SimFileSystem()
        q, _ = await DiskQueue.open(fs.open("r.dbuf.dq"))
        ring = DurabilityRing(queue=q, spill_bytes=1)
        for v in range(1, 11):
            ring.extend_packed(v, _batch(v))
        await ring.maybe_spill()
        spilled0 = list(ring._spilled)
        bytes0 = ring.spilled_bytes

        async def boom(_off):
            raise OSError("disk trouble")
        orig = q.pop_to
        q.pop_to = boom
        with pytest.raises(OSError):
            await ring.pop_through(6)
        assert ring._spilled == spilled0 and ring.spilled_bytes == bytes0
        got = [p1 for _op, p1, _p2 in await ring.peek_through(99)]
        assert got == [b"rk%06d" % v for v in range(1, 11)]
        q.pop_to = orig
        await ring.pop_through(6)               # retry succeeds
        got = [p1 for _op, p1, _p2 in await ring.peek_through(99)]
        assert got == [b"rk%06d" % v for v in range(7, 11)]

    run_simulation(main())


def test_throttled_engine_spills_and_recovers_bit_identical():
    """THE acceptance sim (ISSUE 11): a storage server whose engine
    commits are throttled below the ingest rate keeps DurabilityRing
    retained memory under the knob budget via LIVE spill, and when the
    durability loop finally drains, the engine holds exactly the
    expected keyspace (sha256 over the rows)."""
    from foundationdb_tpu.core.cluster import Cluster, ClusterConfig

    knobs = Knobs().override(
        STORAGE_VERSION_WINDOW=1_000,       # age versions out fast
        STORAGE_DURABILITY_LAG=0.05,
        STORAGE_DBUF_SPILL_BYTES=4096)      # a deliberately tiny budget

    async def main():
        fs = SimFileSystem()
        cluster = await Cluster.create(ClusterConfig(storage_servers=1),
                                       knobs, fs=fs, data_dir="spill-db")
        cluster.start()
        ss = cluster.storage_servers[0]
        assert ss._dbuf.queue is not None, "spill queue never attached"

        # throttle the ENGINE below the ingest rate
        real_commit = ss.engine.commit
        async def slow_commit(ops, meta):
            await asyncio.sleep(0.25)
            await real_commit(ops, meta)
        ss.engine.commit = slow_commit

        from foundationdb_tpu.client.transaction import Transaction
        from foundationdb_tpu.runtime.errors import FdbError
        tr = Transaction(cluster)
        expected = {}
        mem_peaks = []
        for start in range(0, 4000, 200):
            while True:
                try:
                    for i in range(start, start + 200):
                        k, v = b"sp%06d" % i, b"val%06d" % i
                        tr.set(k, v)
                        expected[k] = v
                    await tr.commit()
                    tr.reset()
                    break
                except FdbError as e:
                    await tr.on_error(e)
            mem_peaks.append(ss._dbuf.mem_bytes)
            await asyncio.sleep(0)
        # live spill held resident ring memory at/under the budget even
        # though the engine lagged the whole load (the pull-loop valve
        # runs between applies; one in-flight reply may overshoot
        # transiently, so the bound allows a single reply's slack)
        assert ss._dbuf.spilled_bytes > 0 or ss._dbuf.spills > 0, \
            "the throttled engine never drove a spill"
        slack = 64 << 10
        assert max(mem_peaks) <= 4096 + slack, max(mem_peaks)

        # un-throttle and drain: every row must land in the engine
        ss.engine.commit = real_commit
        tip = cluster.sequencer.committed_version
        while ss.durable_version < tip:
            await asyncio.sleep(0.05)
        rows = sorted(ss.engine.range(b"sp", b"sq"))
        want = sorted(expected.items())
        h = lambda it: hashlib.sha256(  # noqa: E731
            b"".join(k + b"\x00" + v for k, v in it)).hexdigest()
        assert h(rows) == h(want), (
            f"{len(rows)} engine rows vs {len(want)} expected — spill "
            f"read-back lost or duplicated ops")
        assert ss._dbuf.spilled_bytes == 0      # fully released
        await cluster.stop()

    asyncio.run(main())
