"""Durability tests: DiskQueue, KV engine, crash/restart resume.

The crash model is the reference's AsyncFileNonDurable: a kill loses every
write since the last sync, so recovery must rebuild exactly the synced
prefix (torn tails discarded) and replay the TLog from the storage
engine's durable version.
"""

import pytest

from foundationdb_tpu.client import Database
from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
from foundationdb_tpu.runtime.errors import NotCommitted
from foundationdb_tpu.runtime.files import SimFileSystem
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.storage.disk_queue import DiskQueue
from foundationdb_tpu.storage.kv_store import OP_CLEAR, OP_SET, MemoryKVStore


def durable_knobs():
    # small window so durability happens fast in virtual time
    return Knobs().override(STORAGE_VERSION_WINDOW=100_000,
                            STORAGE_DURABILITY_LAG=0.05)


# --- DiskQueue ---

def test_disk_queue_sync_survives_kill():
    async def main():
        fs = SimFileSystem()
        q, frames = await DiskQueue.open(fs.open("q"))
        assert frames == []
        await q.push(b"one")
        await q.push(b"two")
        await q.commit()            # durable point
        await q.push(b"three")      # never synced
        fs.kill_unsynced()
        q2, frames2 = await DiskQueue.open(fs.open("q"))
        assert [p for p, _ in frames2] == [b"one", b"two"]
        # queue remains usable after recovery
        await q2.push(b"four")
        await q2.commit()
        _, frames3 = await DiskQueue.open(fs.open("q"))
        assert [p for p, _ in frames3] == [b"one", b"two", b"four"]
    run_simulation(main())


def test_disk_queue_pop():
    async def main():
        fs = SimFileSystem()
        q, _ = await DiskQueue.open(fs.open("q"))
        ends = [await q.push(b"p%d" % i) for i in range(5)]
        await q.commit()
        await q.pop_to(ends[2])     # drop first three
        await q.commit()
        _, frames = await DiskQueue.open(fs.open("q"))
        assert [p for p, _ in frames] == [b"p3", b"p4"]
    run_simulation(main())


# --- KV engine ---

def test_kv_store_recovery_and_snapshot():
    async def main():
        fs = SimFileSystem()
        kv = await MemoryKVStore.open(fs, "dir/kv")
        await kv.commit([(OP_SET, b"a", b"1"), (OP_SET, b"b", b"2")],
                        {"durable_version": 10})
        await kv.commit([(OP_CLEAR, b"a", b"a\x00"), (OP_SET, b"c", b"3")],
                        {"durable_version": 20})
        kv2 = await MemoryKVStore.open(fs, "dir/kv")
        assert kv2.get(b"a") is None
        assert kv2.get(b"b") == b"2"
        assert list(kv2.range(b"", b"\xff")) == [(b"b", b"2"), (b"c", b"3")]
        assert kv2.meta == {"durable_version": 20}
        # snapshot + post-snapshot WAL both recover
        await kv2._snapshot()
        await kv2.commit([(OP_SET, b"d", b"4")], {"durable_version": 30})
        kv3 = await MemoryKVStore.open(fs, "dir/kv")
        assert [k for k, _ in kv3.range(b"", b"\xff")] == [b"b", b"c", b"d"]
        assert kv3.meta == {"durable_version": 30}
    run_simulation(main())


def test_kv_store_op_order_within_batch():
    async def main():
        fs = SimFileSystem()
        kv = await MemoryKVStore.open(fs, "kv")
        # set then clear-covering then set again: final state = last set
        await kv.commit([(OP_SET, b"k", b"1"),
                         (OP_CLEAR, b"a", b"z"),
                         (OP_SET, b"k", b"2")], {})
        kv2 = await MemoryKVStore.open(fs, "kv")
        assert kv2.get(b"k") == b"2"
    run_simulation(main())


# --- full-cluster restart ---

def test_cluster_restart_preserves_committed_data():
    async def main():
        fs = SimFileSystem()
        cfg = ClusterConfig(storage_servers=2, logs=2)
        k = durable_knobs()

        cluster = await Cluster.create(cfg, k, fs=fs, data_dir="c1")
        async with cluster:
            db = Database(cluster)
            for i in range(20):
                await db.set(b"key%02d" % i, b"val%d" % i)
            await db.clear_range(b"key00", b"key05")
            # let durability catch up, then crash with unsynced loss
            import asyncio
            await asyncio.sleep(1.0)
        fs.kill_unsynced()

        cluster2 = await Cluster.create(cfg, k, fs=fs, data_dir="c1")
        async with cluster2:
            db2 = Database(cluster2)
            rows = await db2.get_range(b"key", b"kez")
            assert [k_ for k_, _ in rows] == [b"key%02d" % i for i in range(5, 20)]
            # and the restarted cluster accepts new commits
            await db2.set(b"after-restart", b"yes")
            assert await db2.get(b"after-restart") == b"yes"
    run_simulation(main(), seed=3)


def test_cluster_restart_after_immediate_kill():
    """Kill before any durability tick: TLog fsync data must be enough."""
    async def main():
        import asyncio
        fs = SimFileSystem()
        cfg = ClusterConfig(storage_servers=2, logs=1)
        k = durable_knobs().override(STORAGE_DURABILITY_LAG=30.0)  # never ticks

        cluster = await Cluster.create(cfg, k, fs=fs, data_dir="d")
        async with cluster:
            db = Database(cluster)
            await db.set(b"x", b"1")
            await db.set(b"y", b"2")
        fs.kill_unsynced()

        cluster2 = await Cluster.create(cfg, k, fs=fs, data_dir="d")
        async with cluster2:
            db2 = Database(cluster2)
            # engines had nothing durable; replay from the TLog queues
            assert await db2.get(b"x") == b"1"
            assert await db2.get(b"y") == b"2"
    run_simulation(main(), seed=6)


def test_restart_determinism():
    def go(seed):
        async def main():
            fs = SimFileSystem()
            cfg = ClusterConfig(storage_servers=2, logs=2)
            k = durable_knobs()
            cluster = await Cluster.create(cfg, k, fs=fs, data_dir="c")
            async with cluster:
                db = Database(cluster)
                for i in range(10):
                    await db.set(b"k%d" % i, b"v%d" % i)
            fs.kill_unsynced()
            cluster2 = await Cluster.create(cfg, k, fs=fs, data_dir="c")
            async with cluster2:
                return await Database(cluster2).get_range(b"", b"\xff")
        return run_simulation(main(), seed=seed)
    assert go(11) == go(11)


def test_tlog_spill_and_indexed_peek():
    """A lagging tag's retained memory is spilled to the disk queue once
    TLOG_SPILL_THRESHOLD is crossed; peeks below the in-memory floor
    re-read the queue's frames and return bit-identical history
    (REF:fdbserver/TLogServer.actor.cpp spill-by-reference)."""
    from foundationdb_tpu.core.data import Mutation, MutationType
    from foundationdb_tpu.core.tlog import TLog, TLogPushRequest
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.runtime.knobs import Knobs

    async def main():
        k = Knobs().override(TLOG_SPILL_THRESHOLD=20_000)
        fs = SimFileSystem()
        tlog = await TLog.open(k, fs, "spill.dq")
        N = 200
        val = b"x" * 100
        for i in range(1, N + 1):
            m0 = [Mutation(MutationType.SET_VALUE, b"fast%04d" % i, val)]
            m1 = [Mutation(MutationType.SET_VALUE, b"slow%04d" % i, val)]
            await tlog.push(TLogPushRequest(i - 1, i, {0: m0, 1: m1}))
            # tag 0 is consumed promptly; tag 1 lags forever
            tlog.pop(0, i)
        # the laggard forced spills: memory stays bounded under the knob
        assert tlog.mem_bytes <= 20_000, tlog.mem_bytes
        st = tlog._log[1]
        assert st.spilled_below > 1, "nothing was spilled"
        # full-history peek of the laggard: disk prefix + memory suffix
        reply = await tlog.peek(1, 1)
        assert [v for v, _ in reply.entries] == list(range(1, N + 1))
        assert all(ms[0].param1 == b"slow%04d" % v
                   for v, ms in reply.entries)
        # mid-range peek starting inside the spilled region
        mid = st.spilled_below // 2
        reply2 = await tlog.peek(1, mid)
        assert [v for v, _ in reply2.entries] == list(range(mid, N + 1))
        # the fast tag was popped below N: only the tip remains
        reply3 = await tlog.peek(0, N - 5)
        assert [v for v, _ in reply3.entries] == [N]
        # restart from disk: spilled data was durable all along
        tlog2 = await TLog.open(k, fs, "spill.dq")
        reply4 = await tlog2.peek(1, 1)
        assert [v for v, _ in reply4.entries] == list(range(1, N + 1))
    run_simulation(main())


def test_tlog_duplicate_push_is_idempotent():
    """A retried push (ambiguous result / chain repair) must not duplicate
    a version's messages — peeks would serve it twice and downstream
    atomic ops would double-apply (found by ConsistencyCheck at seed 10)."""
    from foundationdb_tpu.core.data import Mutation, MutationType
    from foundationdb_tpu.core.tlog import TLog, TLogPushRequest
    from foundationdb_tpu.runtime.knobs import Knobs

    async def main():
        tlog = TLog(Knobs())
        m = [Mutation(MutationType.ADD, b"ctr", b"\x05\x00\x00\x00\x00\x00\x00\x00")]
        await tlog.push(TLogPushRequest(0, 10, {0: m}))
        await tlog.push(TLogPushRequest(10, 20, {0: m}))
        # the retry of version 10 (same content) must be an idempotent ack
        tip = await tlog.push(TLogPushRequest(0, 10, {0: m}))
        assert tip == 20
        reply = await tlog.peek(0, 1)
        assert [v for v, _ in reply.entries] == [10, 20]
    run_simulation(main())
