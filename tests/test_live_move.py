"""Live shard moves (MoveKeys v2): dual tagging, flip, no recovery.

Reference test model: REF:fdbserver/MoveKeys.actor.cpp semantics — a
shard relocation under live writes must lose no rows, invent none, and
leave readers able to follow the handoff; a crash mid-move must roll
back (dual phase) or forward (flipped) safely.
"""

from __future__ import annotations

import asyncio

from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
from foundationdb_tpu.core.data import Mutation, MutationType
from foundationdb_tpu.core.shard_map import ShardMap, write_team_drops
from foundationdb_tpu.core.system_data import (LAYOUT_KEY,
                                               flip_move_dest_entries,
                                               normalize_layout)
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster


# --- unit: the layout diff that drives ownership handoff ---

def test_write_team_drops_on_flip():
    old = ShardMap([b"\x80", b"\xc0"], [[0], [1, 9], [2]])
    new = ShardMap([b"\x80", b"\xc0"], [[0], [9], [2]])
    assert write_team_drops(old, new) == [(1, b"\x80", b"\xc0")]


def test_write_team_drops_none_on_start():
    old = ShardMap([b"\x80"], [[0], [1]])
    new = ShardMap([b"\x40", b"\x80"], [[0], [0, 5], [1]])  # split+dual
    assert write_team_drops(old, new) == []


def test_write_team_drops_merges_adjacent():
    old = ShardMap([b"\x40", b"\x80"], [[3], [3], [1]])
    new = ShardMap([b"\x40", b"\x80"], [[7], [7], [1]])
    assert write_team_drops(old, new) == [(3, b"", b"\x80")]


def test_normalize_layout_rolls_back_in_flight():
    layout = {"boundaries": [b"\x40", b"\x80"],
              "teams": [[0], [0, 5], [1]],
              "moves": [{"begin": b"\x40", "end": b"\x80", "src": [0],
                         "dest": [5], "state": "in"}]}
    n = normalize_layout(layout)
    assert n == {"boundaries": [b"\x40", b"\x80"], "teams": [[0], [0], [1]]}


def test_normalize_layout_rolls_forward_flip():
    layout = {"boundaries": [b"\x40", b"\x80"],
              "teams": [[0], [5], [1]],
              "moves": [{"begin": b"\x40", "end": b"\x80", "src": [0],
                         "dest": [5], "state": "flip",
                         "dest_info": [{"tag": 5, "worker": ["10.1.0.2", 1],
                                        "addr": ["10.1.0.2", 1], "token": 77,
                                        "begin": b"\x40", "end": b"\x80"}]}]}
    n = normalize_layout(layout)
    assert n["teams"] == [[0], [5], [1]]
    assert [d["tag"] for d in flip_move_dest_entries(layout)] == [5]


# --- unit: storage server ownership drop fencing ---

def test_storage_drop_fences_reads():
    from foundationdb_tpu.core.data import KeyRange
    from foundationdb_tpu.core.storage_server import StorageServer
    from foundationdb_tpu.core.tlog import TLog
    from foundationdb_tpu.runtime.errors import WrongShardServer

    async def main():
        k = Knobs()
        tlog = TLog(k)
        ss = StorageServer(k, 0, KeyRange(b"", b"\xff"), tlog)
        ss._apply(5, [Mutation.set(b"a", b"1"), Mutation.set(b"m", b"2")])
        ss._apply(10, [Mutation(MutationType.PRIVATE_DROP_SHARD,
                                b"m", b"\xff")])
        ss._bump_version(11)
        # below/at the drop version: still served from history
        assert await ss.get_value(b"m", 10) == b"2"
        # above it: refused so a stale-routed client refreshes
        try:
            await ss.get_value(b"m", 11)
            raise AssertionError("expected wrong_shard_server")
        except WrongShardServer:
            pass
        try:
            await ss.get_key_values(b"a", b"z", 11)
            raise AssertionError("expected wrong_shard_server")
        except WrongShardServer:
            pass
        # the kept half is unaffected; the DURABLE shard narrowed (what
        # the next boot declares) while the boot-time range keeps serving
        # old-version history
        assert await ss.get_value(b"a", 11) == b"1"
        assert ss._meta_shard.end == b"m"
        assert ss.shard.end == b"\xff"
    run_simulation(main())


# --- sim: the full live protocol under load ---

def test_live_split_without_recovery():
    """Fill one shard past the split threshold while writes keep flowing:
    the distributor must relocate the hot half LIVE — epoch unchanged —
    with zero lost and zero phantom rows, and both old and fresh client
    views must read correctly afterwards."""
    async def main():
        k = Knobs().override(DD_ENABLED=True, DD_INTERVAL=1.0,
                             DD_SHARD_SPLIT_BYTES=6_000)
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        n_shards_before = len(state1["shard_teams"])
        db = await sim.database()
        stale_db = await sim.database()   # view frozen pre-move
        stale_db.view.update(state1)

        written: dict[bytes, bytes] = {}
        stop = asyncio.Event()

        async def writer(wid: int) -> None:
            i = 0
            while not stop.is_set():
                items = {b"hot%02d%05d" % (wid, i + j): b"v" * 40
                         for j in range(5)}
                i += 5

                async def do(tr, items=items):
                    for key, v in items.items():
                        tr.set(key, v)
                await db.run(do)
                written.update(items)
                await asyncio.sleep(0.05)

        writers = [asyncio.ensure_future(writer(w)) for w in range(2)]
        # wait for the flip's publish: seq advances, epoch must NOT
        state2 = await sim.wait_state(
            lambda s: s.get("seq", 0) > 0
            and len(s["shard_teams"]) > n_shards_before)
        await asyncio.sleep(2.0)          # let writes land post-flip
        stop.set()
        await asyncio.gather(*writers)

        assert state2["epoch"] == state1["epoch"], \
            "live move must not trigger a recovery"
        for fresh in (db, stale_db):
            tr = fresh.create_transaction()
            while True:
                try:
                    rows = await tr.get_range(b"hot", b"hou", limit=0)
                    break
                except Exception as e:   # noqa: BLE001 — follow the move
                    await tr.on_error(e)
            got = dict(rows)
            missing = [key for key in written if key not in got]
            assert not missing, f"{len(missing)} rows lost, e.g. {missing[:3]}"
            wrong = [key for key, v in written.items() if got.get(key) != v]
            assert not wrong, f"{len(wrong)} rows corrupted"
            phantom = [key for key in got if key not in written]
            assert not phantom, f"{len(phantom)} phantoms, e.g. {phantom[:3]}"
        await sim.stop()
    run_simulation(main())


def test_live_split_multi_proxy_multi_resolver():
    """With TWO commit proxies and TWO resolvers, a live move's layout
    change committed through one proxy must reach the other through the
    resolver state stream before it tags any later batch — otherwise the
    second proxy keeps writing to the dropped source and rows vanish."""
    async def main():
        k = Knobs().override(DD_ENABLED=True, DD_INTERVAL=1.0,
                             DD_SHARD_SPLIT_BYTES=6_000)
        sim = SimulatedCluster(
            k, n_machines=6,
            spec=ClusterConfigSpec(min_workers=6, commit_proxies=2,
                                   grv_proxies=2, resolvers=2))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        db = await sim.database()

        written: dict[bytes, bytes] = {}
        stop = asyncio.Event()

        async def writer(wid: int) -> None:
            i = 0
            while not stop.is_set():
                # fresh transactions pick proxies at random, so both
                # proxies carry writes through the move window
                items = {b"hot%02d%05d" % (wid, i + j): b"w" * 40
                         for j in range(5)}
                i += 5

                async def do(tr, items=items):
                    for key, v in items.items():
                        tr.set(key, v)
                await db.run(do)
                written.update(items)
                await asyncio.sleep(0.04)

        writers = [asyncio.ensure_future(writer(w)) for w in range(3)]
        state2 = await sim.wait_state(lambda s: s.get("seq", 0) > 0)
        await asyncio.sleep(2.0)
        stop.set()
        await asyncio.gather(*writers)
        assert state2["epoch"] == state1["epoch"]

        tr = db.create_transaction()
        while True:
            try:
                rows = await tr.get_range(b"hot", b"hou", limit=0)
                break
            except Exception as e:   # noqa: BLE001 — follow the move
                await tr.on_error(e)
        got = dict(rows)
        missing = [key for key in written if key not in got]
        assert not missing, f"{len(missing)} rows lost, e.g. {missing[:3]}"
        phantom = [key for key in got if key not in written]
        assert not phantom, f"{len(phantom)} phantoms, e.g. {phantom[:3]}"
        await sim.stop()
    run_simulation(main())


def test_state_txn_user_read_conflict_rejected():
    """A system-key transaction taking a read conflict on a USER key is
    refused: resolvers' user-key histories are per-partition, so such a
    transaction's verdict could differ across resolvers and fork the
    proxies' metadata history (the verdict-agreement invariant)."""
    async def main():
        from foundationdb_tpu.runtime.errors import ClientInvalidOperation
        k = Knobs()
        sim = SimulatedCluster(k, n_machines=4,
                               spec=ClusterConfigSpec(min_workers=4))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        tr = db.create_transaction()
        await tr.get(b"some-user-key")          # user-range read conflict
        tr.set(LAYOUT_KEY, b"whatever")         # system write -> state txn
        try:
            await tr.commit()
            raise AssertionError("expected client_invalid_operation")
        except ClientInvalidOperation:
            pass
        # snapshot reads take no conflict ranges: allowed
        tr = db.create_transaction()
        await tr.get(b"some-user-key", snapshot=True)
        tr.set(b"\xff/conf/resolvers", b"1")
        await tr.commit()
        await sim.stop()
    run_simulation(main())


def test_recovery_mid_move_rolls_back():
    """A dual-tagged (phase-1) move interrupted by a recovery must roll
    back to the source team with every row intact."""
    async def main():
        from foundationdb_tpu.rpc.wire import decode, encode
        k = Knobs()
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        db = await sim.database()
        rows = {b"mv%04d" % i: b"x" * 20 for i in range(50)}

        async def put(tr):
            for key, v in rows.items():
                tr.set(key, v)
        await db.run(put)

        # hand-commit a startMove layout: dual team + "in" journal, with a
        # destination tag that will never exist
        boundaries = [bytes(b) for b in state1["shard_boundaries"]]
        teams = [list(t) for t in state1["shard_teams"]]
        idx = 0
        src = list(teams[idx])
        dest = [max(s["tag"] for s in state1["storage"]) + 1]
        begin = b""
        end = boundaries[0] if boundaries else b"\xff\xff\xff"
        teams[idx] = src + dest
        layout = {"boundaries": boundaries, "teams": teams,
                  "moves": [{"begin": begin, "end": end, "src": src,
                             "dest": dest, "state": "in"}]}

        async def start_move(tr):
            tr.set(LAYOUT_KEY, encode(layout))
        await db.run(start_move)

        # writes in the dual window reach the (phantom) dest tag AND src
        async def dual(tr):
            for i in range(50, 70):
                tr.set(b"mv%04d" % i, b"y" * 20)
        await db.run(dual)
        rows.update({b"mv%04d" % i: b"y" * 20 for i in range(50, 70)})

        # force a recovery: kill a txn-role machine (not storage/coord)
        victims = await sim.txn_only_machines()
        assert victims, "need a pure txn machine to kill"
        await victims[0].kill()
        state2 = await sim.wait_epoch(state1["epoch"] + 1)
        assert state2["shard_teams"][idx] == src, \
            "recovery must roll the in-flight move back to src"

        got = dict(await db.get_range(b"mv", b"mw", limit=0))
        assert got == rows, (
            f"{len(set(rows) - set(got))} lost / "
            f"{len(set(got) - set(rows))} phantom after rollback")
        await sim.stop()
    run_simulation(main())


def test_source_engine_gc_after_live_split():
    """After a live split's flip, the source replica's ENGINE must shed
    the moved range's rows once the drop version ages past the MVCC
    floor — dropped key space is fenced garbage, not disk freight.
    Every durable engine's contents must end up inside its server's
    narrowed meta shard."""
    async def main():
        k = Knobs().override(DD_ENABLED=True, DD_INTERVAL=1.0,
                             DD_SHARD_SPLIT_BYTES=6_000,
                             STORAGE_DURABILITY_LAG=0.2,
                             STORAGE_VERSION_WINDOW=2000)
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6),
                               durable_storage=True)
        await sim.start()
        state1 = await sim.wait_epoch(1)
        n_shards_before = len(state1["shard_teams"])
        db = await sim.database()

        async def fill(tr, lo, hi):
            for i in range(lo, hi):
                tr.set(b"gc%05d" % i, b"v" * 60)
        for lo in range(0, 200, 50):
            await db.run(lambda tr, lo=lo: fill(tr, lo, lo + 50))
        await sim.wait_state(
            lambda s: len(s["shard_teams"]) > n_shards_before)

        # keep versions flowing so the MVCC floor passes the drop version,
        # then hold until every source server's pending GC has drained —
        # the LAST split can land at the very end of the write traffic,
        # and its GC legitimately needs the floor (hence versions) to
        # advance past the drop version plus one durability tick
        def storage_roles():
            out = []
            for m in sim.machines:
                if not m.alive or m.host is None:
                    continue
                for _tok, (role, obj) in list(m.host.worker.roles.items()):
                    if role == "storage" and obj.engine is not None:
                        out.append(obj)
            return out

        for j in range(200):
            await db.run(lambda tr, j=j: fill(tr, j % 5, j % 5 + 1))
            await asyncio.sleep(0.1)
            if j >= 30 and not any(s._gc_pending for s in storage_roles()):
                break
        else:
            raise AssertionError(
                "pending source-engine GC never drained: " +
                repr([(s.tag, s._gc_pending) for s in storage_roles()
                      if s._gc_pending]))

        checked = 0
        for m in sim.machines:
            if not m.alive or m.host is None:
                continue
            for _tok, (role, obj) in list(m.host.worker.roles.items()):
                if role != "storage" or obj.engine is None:
                    continue
                ms = obj._meta_shard
                outside = [key for key, _v
                           in obj.engine.range(b"", b"\xff\xff")
                           if not (ms.begin <= key < ms.end)]
                checked += 1
                assert not outside, (
                    f"tag {obj.tag}: {len(outside)} engine rows outside "
                    f"meta shard [{ms.begin!r}, {ms.end!r}), "
                    f"e.g. {outside[:3]}")
        assert checked >= 2, "expected multiple durable storage engines"
        await sim.stop()
    run_simulation(main())


def test_live_move_of_system_keyspace_shard():
    """The LAST shard holds the \xff metadata.  Overfilling it forces a
    live split whose right half — including the entire system keyspace —
    moves to a fresh team.  The cluster must keep serving, metadata
    writes must keep working, and a subsequent recovery must read its
    configuration from the NEW team (the recovery-time metadata read
    follows the moved shard)."""
    async def main():
        from foundationdb_tpu.core.management import configure

        k = Knobs().override(DD_ENABLED=True, DD_INTERVAL=1.0,
                             DD_SHARD_SPLIT_BYTES=6_000)
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        n_shards_before = len(state1["shard_teams"])
        last_team_before = list(state1["shard_teams"][-1])
        db = await sim.database()

        # a config value that must survive the metadata move + recovery
        await configure(db, resolvers=1)

        written: dict[bytes, bytes] = {}
        stop = asyncio.Event()

        async def writer(wid: int) -> None:
            i = 0
            while not stop.is_set():
                items = {b"\xf0hot%02d%05d" % (wid, i + j): b"v" * 40
                         for j in range(5)}
                i += 5

                async def do(tr, items=items):
                    for key, v in items.items():
                        tr.set(key, v)
                await db.run(do)
                written.update(items)
                await asyncio.sleep(0.05)

        writers = [asyncio.ensure_future(writer(w)) for w in range(2)]
        state2 = await sim.wait_state(
            lambda s: s.get("seq", 0) > 0
            and len(s["shard_teams"]) > n_shards_before)
        await asyncio.sleep(1.0)
        stop.set()
        await asyncio.gather(*writers)

        assert state2["epoch"] == state1["epoch"], \
            "live move must not trigger a recovery"
        # the system keyspace (last shard) is on a DIFFERENT team now
        assert list(state2["shard_teams"][-1]) != last_team_before, \
            (last_team_before, state2["shard_teams"])

        # metadata writes still work post-move (routed to the new team)
        await configure(db, logs=1)

        # a recovery right after the metadata moved: the controller's
        # \xff read must find the NEW team and recover the conf
        victims = await sim.txn_only_machines()
        assert victims
        await victims[0].kill()
        state3 = await sim.wait_epoch(state2["epoch"] + 1)
        assert len(state3["resolvers"]) == 1, state3["resolvers"]

        tr = db.create_transaction()
        while True:
            try:
                rows = await tr.get_range(b"\xf0hot", b"\xf0hou", limit=0)
                break
            except Exception as e:   # noqa: BLE001 — follow the recovery
                await tr.on_error(e)
        got = dict(rows)
        missing = [key for key in written if key not in got]
        assert not missing, f"{len(missing)} rows lost, e.g. {missing[:3]}"
        phantom = [key for key in got if key not in written]
        assert not phantom, f"{len(phantom)} phantoms"
        await sim.stop()
    run_simulation(main())
