"""Locality API + special-key space.

Reference test model: REF:bindings/python/fdb/locality.py
(get_addresses_for_key / get_boundary_keys) and
REF:fdbclient/SpecialKeySpace.actor.cpp (\\xff\\xff reads answered by
the client).
"""

from __future__ import annotations

import json

from foundationdb_tpu.client.locality import (get_addresses_for_key,
                                              get_boundary_keys)
from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
from foundationdb_tpu.runtime.errors import ClientInvalidOperation
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster


def test_addresses_and_boundaries_match_cluster_state():
    async def main():
        sim = SimulatedCluster(Knobs(), n_machines=5,
                               spec=ClusterConfigSpec(min_workers=5,
                                                      replication=2))
        await sim.start()
        state = await sim.wait_epoch(1)
        db = await sim.database()

        tr = db.create_transaction()
        addrs = await tr.get_addresses_for_key(b"some-key")
        # replication=2: the team serving the key has two distinct
        # replicas, and every address is a real storage address from the
        # published state
        assert len(addrs) == 2 and len(set(addrs)) == 2, addrs
        published = {f"{s['addr'][0]}:{s['addr'][1]}"
                     for s in state["storage"]}
        assert set(addrs) <= published, (addrs, published)
        # the module-level variant agrees
        assert await get_addresses_for_key(tr, b"some-key") == addrs

        # boundary keys cover the whole space and respect the window
        bounds = await get_boundary_keys(db, b"", b"\xff")
        assert bounds and bounds[0] == b""
        assert bounds == sorted(bounds)
        sub = await get_boundary_keys(db, b"m", b"\xff")
        assert all(b"m" <= k < b"\xff" for k in sub)
        await sim.stop()
    run_simulation(main())


def test_status_json_special_key():
    async def main():
        sim = SimulatedCluster(Knobs(), n_machines=4,
                               spec=ClusterConfigSpec(min_workers=4))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()

        async def w(tr):
            tr.set(b"k", b"v")
        await db.run(w)

        tr = db.create_transaction()
        raw = await tr.get(b"\xff\xff/status/json")
        doc = json.loads(raw)
        roles = {r["role"] for r in doc["roles"]}
        assert {"sequencer", "log", "resolver", "storage"} <= roles, roles
        # reading a special key must not poison the transaction: a
        # normal read-write commit still works on the same txn
        tr.set(b"after-status", b"1")
        await tr.commit()

        tr = db.create_transaction()
        try:
            await tr.get(b"\xff\xff/no/such/module")
            raise AssertionError("unknown special key did not raise")
        except ClientInvalidOperation:
            pass
        await sim.stop()
    run_simulation(main())
