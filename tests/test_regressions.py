"""Regression tests for review-found pipeline bugs."""

import pytest

from foundationdb_tpu.client import Database
from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
from foundationdb_tpu.core.shard_map import ShardMap
from foundationdb_tpu.runtime.errors import (ClientInvalidOperation,
                                             TransactionCancelled)
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


def sim(coro_fn, seed=0, config=None):
    async def main():
        async with Cluster(config or ClusterConfig(), Knobs()) as cluster:
            return await coro_fn(Database(cluster))
    return run_simulation(main(), seed=seed)


def test_bad_versionstamp_fails_alone_without_wedging_cluster():
    async def body(db):
        tr = db.create_transaction()
        tr.set_versionstamped_key(b"ab", b"v")   # param too short for offset
        with pytest.raises(ClientInvalidOperation):
            await tr.commit()
        # the cluster must still work: the version chain was not wedged
        await db.set(b"after", b"ok")
        assert await db.get(b"after") == b"ok"
    sim(body)


def test_limited_range_read_with_large_buffered_clear():
    async def body(db):
        async def fill(tr):
            for i in range(200):
                tr.set(b"k%03d" % i, b"v")
        await db.run(fill)
        tr = db.create_transaction()
        tr.clear_range(b"k000", b"k100")
        rows = await tr.get_range(b"", b"\xff", limit=5)
        assert [k for k, _ in rows] == [b"k100", b"k101", b"k102", b"k103", b"k104"]
        rows = await tr.get_range(b"", b"\xff", limit=5, reverse=True)
        assert [k for k, _ in rows] == [b"k199", b"k198", b"k197", b"k196", b"k195"]
    sim(body, config=ClusterConfig(storage_servers=4))


def test_watch_fails_on_reset_instead_of_hanging():
    async def body(db):
        tr = db.create_transaction()
        fut = await tr.watch(b"w")
        tr.reset()
        with pytest.raises(TransactionCancelled):
            await fut
    sim(body)


def test_tlogs_only_retain_hosted_tags():
    async def body(db):
        for i in range(30):
            await db.set(b"k%02d" % i, b"v" * 50)
        cluster = db.cluster
        # push routing sends a tag's data only to its hosting replicas
        # (LOG_REPLICATION of them); other tlogs get empty frames
        gen = cluster.log_system.current
        for ti, tlog in enumerate(cluster.tlogs):
            for tag, entries in tlog._log.items():
                assert ti in gen.logs_for_tag(tag), \
                    f"tlog {ti} retains foreign tag {tag}"
    sim(body, config=ClusterConfig(logs=3, storage_servers=4))


def test_shard_map_boundary_range():
    sm = ShardMap.even(4)
    # range ending exactly on a shard boundary excludes the next shard
    assert sm.tags_for_range(b"\x00", b"\x40") == [0]
    assert sm.tags_for_range(b"\x00", b"\x40\x00") == [0, 1]
    assert sm.tags_for_range(b"\x40", b"\x80") == [1]
    assert sm.tags_for_range(b"a", b"a") == []
    assert sm.tags_for_range(b"", b"\xff") == [0, 1, 2, 3]


def test_shard_map_keyspace_end_threaded():
    sm = ShardMap.even(2, keyspace_end=b"\xff")
    assert sm.ranges()[-1][0].end == b"\xff"


def test_unrepairable_state_batch_fail_stops_proxy():
    """A state-bearing batch that fails AFTER resolution but BEFORE its
    tagging is computed cannot be repaired (an empty substitute push
    would durably erase a committed metadata change every resolver
    already streamed).  The proxy must fail-stop: refuse new commits and
    probe dead on its role-liveness slot — never push the substitute."""
    from foundationdb_tpu.runtime.errors import ClusterVersionChanged

    async def body(db):
        proxy = db.cluster.commit_proxies[0]
        real = proxy._apply_state_entries
        fired = {}

        def boom(entries, own_version=None):
            if entries and not fired:
                fired["x"] = True
                raise RuntimeError("injected post-resolve failure")
            return real(entries, own_version=own_version)

        proxy._apply_state_entries = boom
        tr = db.create_transaction()
        tr.set(b"\xff/conf/test", b"1")   # state txn
        with pytest.raises(Exception):
            await tr.commit()
        assert proxy._failed is not None, "proxy must fail-stop"
        # new commits are refused at the proxy boundary (a real cluster's
        # CC would see the dead role-liveness probe and recover the epoch;
        # this bare Cluster has no CC, so assert at the seam)
        from foundationdb_tpu.core.data import CommitTransactionRequest
        with pytest.raises(ClusterVersionChanged):
            await proxy.commit(CommitTransactionRequest([], [], [], 0))

    sim(body)


def test_pure_user_batch_repairs_without_fail_stop():
    """The same post-resolve failure on a batch with NO state txn is
    safely repaired with an empty substitute: clients hold
    commit_unknown_result and the cluster keeps serving."""
    async def body(db):
        proxy = db.cluster.commit_proxies[0]
        real = proxy._apply_state_entries
        fired = {}

        def boom(entries, own_version=None):
            # only the _commit_batch path passes own_version; an idle
            # empty batch must not consume the injection
            if own_version is not None and not fired:
                fired["x"] = True
                raise RuntimeError("injected post-resolve failure")
            return real(entries, own_version=own_version)

        proxy._apply_state_entries = boom
        with pytest.raises(Exception):
            await db.set(b"victim", b"v")
        assert proxy._failed is None, "user batch must not dead-end epoch"
        proxy._apply_state_entries = real
        await db.set(b"after", b"ok")
        assert await db.get(b"after") == b"ok"

    sim(body)


def test_fat_txn_sidecar_floor_tracks_txn_life_window():
    """The exact sidecar's self-imposed history floor must track the
    txn-life window (MAX_WRITE_TRANSACTION_LIFE_VERSIONS), never the
    storage MVCC window: a tighter floor TooOld-s fat transactions whose
    snapshots the kernel itself would admit, which livelocks any fat-txn
    retry loop whose GRV lags by more than the window (a 6-machine sim
    with STORAGE_VERSION_WINDOW=1000 spun forever on a 20-write txn)."""
    from foundationdb_tpu.ops.backends import make_conflict_backend
    from foundationdb_tpu.ops.batch import TxnRequest
    from foundationdb_tpu.runtime.knobs import Knobs

    knobs = Knobs().override(RESOLVER_CONFLICT_BACKEND="numpy",
                             STORAGE_VERSION_WINDOW=1000,
                             RESOLVER_RANGES_PER_TXN=4)
    backend = make_conflict_backend(knobs)
    writes = [(b"k%03d" % i, b"k%03d\x00" % i) for i in range(20)]
    # birth the sidecar: first fat txn (20 > R=4), snapshot == cv floor
    assert backend.resolve([TxnRequest([], writes, 1)], 10) == [0]
    # a fat txn whose snapshot lags cv by far more than the storage
    # window but well inside the txn-life window must still commit
    cv = 2_000_000
    snapshot = cv - 500_000
    got = backend.resolve([TxnRequest([], writes, snapshot)], cv)
    assert got == [0], f"fat txn TooOld'd inside the txn-life window: {got}"
