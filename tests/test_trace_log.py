"""TraceLog rolling + event-schema serializability (ISSUE 2 satellite).

The trace JSONL substrate is now load-bearing for the distributed
tracing toolkit (tools/trace_tool.py reconstructs timelines from rolled
files alone), so rolling behavior and the JSON-serializability of every
event shape get their own coverage.
"""

from __future__ import annotations

import json
import os
import time

from foundationdb_tpu.runtime.latency_probe import TraceBatch
from foundationdb_tpu.runtime.span import SpanContext, SpanSink
from foundationdb_tpu.runtime.trace import (CounterCollection, Histogram,
                                            Severity, TraceEvent, TraceLog,
                                            get_trace_log, set_trace_log)


def _mklog(tmp_path, **kw) -> tuple[TraceLog, str]:
    path = os.path.join(str(tmp_path), "trace.jsonl")
    return TraceLog(path=path, clock=time.time, **kw), path


def _lines(path: str) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_roll_at_byte_boundary(tmp_path):
    log, path = _mklog(tmp_path, roll_bytes=400)
    for i in range(50):
        TraceEvent("RollProbe", log=log).detail("I", i).log()
    log.close()
    rolls = [p for p in os.listdir(tmp_path) if p.startswith("trace.jsonl.")]
    assert rolls, "no rolled file despite exceeding roll_bytes"
    # every rolled generation is itself valid JSONL and the total event
    # count survives the rolls
    total = sum(len(_lines(os.path.join(str(tmp_path), p)))
                for p in rolls) + len(_lines(path))
    assert total == 50
    # the live file was restarted below the threshold
    assert os.path.getsize(path) < 400


def test_roll_sequence_continues_across_restart(tmp_path):
    """A restarted process must continue the .N sequence past files left
    by its predecessor, never overwrite them."""
    log, path = _mklog(tmp_path, roll_bytes=200)
    for i in range(20):
        TraceEvent("Gen1", log=log).detail("I", i).log()
    log.close()
    gens1 = sorted(int(p.rsplit(".", 1)[1])
                   for p in os.listdir(tmp_path)
                   if p.startswith("trace.jsonl."))
    assert gens1
    first_roll = _lines(os.path.join(str(tmp_path), f"trace.jsonl.{gens1[0]}"))

    # "restart": a fresh TraceLog on the same path
    log2, _ = _mklog(tmp_path, roll_bytes=200)
    for i in range(20):
        TraceEvent("Gen2", log=log2).detail("I", i).log()
    log2.close()
    gens2 = sorted(int(p.rsplit(".", 1)[1])
                   for p in os.listdir(tmp_path)
                   if p.startswith("trace.jsonl."))
    assert gens2[-1] > gens1[-1], "roll sequence did not continue"
    assert len(gens2) == len(set(gens2)), "duplicate roll generation"
    # the predecessor's first rolled file is untouched
    assert _lines(os.path.join(str(tmp_path),
                               f"trace.jsonl.{gens1[0]}")) == first_roll


def test_every_event_shape_is_json_serializable(tmp_path):
    """One of each emitted event family — role events with bytes/error
    details, metrics emissions, latency probes, span events — must
    produce a parseable JSONL line."""
    log, path = _mklog(tmp_path, min_severity=Severity.DEBUG)
    prev = get_trace_log()
    set_trace_log(log)
    try:
        # plain detail chain with awkward value types
        TraceEvent("ShapeProbe").detail("Bytes", b"\x00\xff") \
            .detail("Float", 1.5).detail("NoneV", None) \
            .detail("List", [1, "a"]).log()
        # error enrichment
        TraceEvent("ShapeError").error(ValueError("boom")).log()
        # histogram + counter collection metrics
        h = Histogram("Shape", "Latency")
        h.sample(123.0)
        h.log_metrics(log)
        cc = CounterCollection("Shape", "id0")
        cc.counter("Ops").add(3)
        cc.log_metrics(log)
        # TraceBatch flush (TransactionTrace)
        t = {"v": 0.0}

        def clock():
            t["v"] += 0.01
            return t["v"]
        tb = TraceBatch(1.0, clock=clock)
        assert tb.attach(1)
        tb.event(1, "grv")
        tb.event(1, "commit_done")
        assert tb.flush(1) is not None
        # span events (the distributed-tracing schema)
        sink = SpanSink("test-role")
        ctx = SpanContext(42, 7, 3, True)
        sink.event("TransactionDebug", ctx, "Test.location", Version=9)
        sink.event("CommitDebug", ctx, "Test.other", Error="X",
                   severity=Severity.DEBUG)
        # storage apply correlation event shape
        TraceEvent("StorageApplyDebug", severity=Severity.DEBUG) \
            .detail("Tag", 0).detail("MinVersion", 1) \
            .detail("MaxVersion", 5).detail("Mutations", 10) \
            .detail("DurationMs", 0.5).log()
    finally:
        set_trace_log(prev)
        log.close()
    events = _lines(path)
    types = {e["Type"] for e in events}
    assert {"ShapeProbe", "ShapeError", "HistogramShapeLatency",
            "ShapeMetrics", "TransactionTrace", "TransactionDebug",
            "CommitDebug", "StorageApplyDebug"} <= types
    for e in events:
        assert "Time" in e and "Severity" in e
    spans = [e for e in events if e["Type"] in
             ("TransactionDebug", "CommitDebug")]
    for e in spans:
        assert e["TraceID"] == "%016x" % 42
        assert e["SpanID"] == 7 and e["ParentID"] == 3


def test_trace_batch_live_table_is_bounded():
    """Abandoned sampled probes must not leak: past the cap the oldest
    record is evicted and counted (ISSUE 2 satellite)."""
    tb = TraceBatch(1.0, clock=lambda: 0.0, live_cap=8)
    for i in range(20):
        assert tb.attach(i)
    assert len(tb._live) == 8
    assert tb.evictions == 12
    # the evicted probes are gone (flush is a no-op), the newest survive
    assert tb.flush(0) is None
    assert tb.flush(19) is not None
