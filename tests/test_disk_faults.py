"""Disk-fault chaos end-to-end (ISSUE 12).

The hostile-disk layer (runtime/files.py DiskFaultProfile — torn
writes, kill-time corruption, IO errors, stalls), the LOUD-failure
discipline of every durable consumer (DiskQueue committed-region crc),
and the gray-failure response (degraded detection + DD/CC avoidance).
"""

from __future__ import annotations

import asyncio
import hashlib

import pytest

from foundationdb_tpu.runtime.errors import DiskCorrupt
from foundationdb_tpu.runtime.files import DiskFaultProfile, SimFileSystem
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.rng import DeterministicRandom
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.storage.disk_queue import DiskQueue


# --- unit: the tear model itself ---

def test_torn_kill_never_touches_synced_bytes():
    """Synced content survives every torn/corrupt kill byte-identical;
    only sectors dirtied by unsynced ops may change."""
    async def main():
        prof = DiskFaultProfile()
        prof.arm(DeterministicRandom(7), torn_p=1.0, corrupt_p=1.0,
                 sector=64)
        fs = SimFileSystem(profile=prof)
        f = fs.open("t")
        synced = bytes(range(256)) * 8      # 2KB synced baseline
        await f.write(0, synced)
        await f.sync()
        # dirty a sector in the middle + append past the end
        await f.write(512, b"\xAA" * 64)
        await f.write(2048, b"\xBB" * 300)
        fs.kill_unsynced()
        after = bytes(fs.disks["t"])
        assert prof.torn_kills == 1
        # every byte outside the dirtied regions is untouched
        assert after[:512] == synced[:512]
        assert after[576:2048] == synced[576:2048]
        # the dirty sector either dropped (old), persisted (new), or
        # corrupted — never anything else
        mid = after[512:576]
        assert mid == synced[512:576] or mid == b"\xAA" * 64 \
            or len(mid) == 64
    run_simulation(main())


def test_disarmed_profile_is_all_or_nothing_drop():
    async def main():
        fs = SimFileSystem()                # no profile: legacy semantics
        f = fs.open("t")
        await f.write(0, b"synced")
        await f.sync()
        await f.write(0, b"UNSYNC")
        fs.kill_unsynced()
        assert bytes(fs.disks["t"]) == b"synced"
    run_simulation(main())


def test_io_error_and_stall_injection():
    async def main():
        prof = DiskFaultProfile()
        prof.arm(DeterministicRandom(3), io_error_p=0.5, stall_p=0.5,
                 stall_max_s=0.01)
        fs = SimFileSystem(profile=prof)
        f = fs.open("t")
        from foundationdb_tpu.runtime.errors import IoError
        errors = 0
        for i in range(64):
            try:
                await f.write(i, b"x")
            except IoError:
                errors += 1
        assert errors > 0 and prof.io_errors == errors
        assert prof.stalls > 0
        # stalls feed the health tracker: decayed latency is non-zero
        assert fs.health.latency_ms() > 0.0
        # quiesce stops live injection but keeps kill semantics armed
        prof.quiesce()
        before = prof.io_errors
        for i in range(32):
            await f.write(i, b"y")
        assert prof.io_errors == before
        assert prof.armed      # kill-time semantics stay armed
    run_simulation(main())


# --- DiskQueue: torn tail vs corrupt committed region (the satellite
#     recovery bugfix) ---

def test_disk_queue_mid_file_corruption_raises_loudly():
    """Bad crc BEFORE the durable frontier must raise DiskCorrupt, not
    silently truncate committed frames (the pre-ISSUE-12 behavior
    treated any bad crc as a torn tail)."""
    async def main():
        fs = SimFileSystem()
        q, _ = await DiskQueue.open(fs.open("q"))
        ends = []
        for i in range(4):
            ends.append(await q.push(b"payload-%d" % i * 20))
            await q.commit()
        await q.commit()        # records the durable frontier at the end
        # corrupt one byte in the SECOND committed frame
        disk = fs.disks["q"]
        mid = (ends[0] + ends[1]) // 2
        disk[mid] ^= 0xFF
        with pytest.raises(DiskCorrupt):
            await DiskQueue.open(fs.open("q"))
    run_simulation(main())


def test_disk_queue_torn_tail_still_discards_silently():
    """Bad crc AT/PAST the frontier is a crash's torn tail — recovered
    around exactly as before."""
    async def main():
        fs = SimFileSystem()
        q, _ = await DiskQueue.open(fs.open("q"))
        await q.push(b"one")
        await q.commit()
        await q.commit()                    # frontier covers frame one
        await q.push(b"never-synced")       # torn by the kill
        fs.kill_unsynced()
        q2, frames = await DiskQueue.open(fs.open("q"))
        assert [p for p, _ in frames] == [b"one"]
        # ...and garbage appended past the frontier is discarded too
        fs.disks["q"].extend(b"\x99" * 40)
        _, frames2 = await DiskQueue.open(fs.open("q"))
        assert [p for p, _ in frames2] == [b"one"]
    run_simulation(main())


def test_disk_queue_truncated_header_page_raises_loudly():
    """ROADMAP 6 (d): a LENGTH regression of the header page itself —
    the file cut below the 4KB header page while a surviving header
    slot records committed frames — must raise DiskCorrupt, never
    silently re-init the queue.  A torn kill can never shorten synced
    bytes, so this shape is always external damage."""
    async def main():
        fs = SimFileSystem()
        q, _ = await DiskQueue.open(fs.open("q"))
        for i in range(3):
            await q.push(b"committed-%d" % i * 10)
            await q.commit()
        await q.commit()            # frontier covers every frame
        # cut the file to 600 bytes: both 512B-strided header slots
        # survive (44B each at offsets 0 and 512) but every committed
        # frame past the header page is gone
        del fs.disks["q"][600:]
        with pytest.raises(DiskCorrupt):
            await DiskQueue.open(fs.open("q"))
    run_simulation(main())


def test_disk_queue_short_fresh_file_still_reinits():
    """The length-regression check must NOT fire on a legitimately
    short file: a kill tearing the very first header write (no durable
    frontier ever recorded) still recovers as an empty queue."""
    async def main():
        fs = SimFileSystem()
        q, _ = await DiskQueue.open(fs.open("q"))
        del q
        # never synced: the kill may shorten or destroy the header page
        fs.kill_unsynced()
        q2, frames = await DiskQueue.open(fs.open("q"))
        assert frames == []
        await q2.push(b"fresh")
        await q2.commit()
        _, frames2 = await DiskQueue.open(fs.open("q"))
        assert [p for p, _ in frames2] == [b"fresh"]
    run_simulation(main())


def test_disk_queue_read_frames_raises_on_corrupt_live_frame():
    async def main():
        fs = SimFileSystem()
        q, _ = await DiskQueue.open(fs.open("q"))
        end1 = await q.push(b"a" * 100)
        await q.push(b"b" * 100)
        await q.commit()
        fs.disks["q"][end1 + 20] ^= 0x55    # corrupt frame b in place
        with pytest.raises(DiskCorrupt):
            await q.read_frames(end1)
    run_simulation(main())


def test_disk_queue_survives_torn_header_write():
    """A kill tearing the in-flight header write must fall back to the
    other slot — never lose front/meta to a legitimate crash."""
    async def main():
        prof = DiskFaultProfile()
        prof.arm(DeterministicRandom(11), torn_p=1.0, corrupt_p=1.0,
                 sector=512)
        fs = SimFileSystem(profile=prof)
        q, _ = await DiskQueue.open(fs.open("q"))
        await q.push(b"keep-me")
        await q.commit(meta=42)
        await q.commit()
        # a new meta header staged but never synced; the kill may tear
        # or corrupt exactly that slot — the synced slot must win
        await q._write_header()
        fs.kill_unsynced()
        q2, frames = await DiskQueue.open(fs.open("q"))
        assert [p for p, _ in frames] == [b"keep-me"]
        assert q2.meta == 42
    run_simulation(main())


# --- engine recovery under a torn-disk kill ---

@pytest.mark.parametrize("engine_name", ["memory", "lsm", "btree"])
def test_engine_recovers_committed_state_through_torn_kill(engine_name):
    """Every IKeyValueStore engine recovers its COMMITTED state
    byte-identically through a kill whose unsynced writes tear and
    corrupt (sector granularity)."""
    from foundationdb_tpu.storage import engine_class
    from foundationdb_tpu.storage.kv_store import OP_SET

    async def main():
        prof = DiskFaultProfile()
        prof.arm(DeterministicRandom(29), torn_p=1.0, corrupt_p=0.5,
                 sector=128)
        fs = SimFileSystem(profile=prof)
        cls = engine_class(engine_name)
        kv = await cls.open(fs, "e/kv")
        committed = {}
        for batch in range(6):
            ops = []
            for i in range(40):
                k = b"k%02d-%03d" % (batch, i)
                v = (b"v%d" % batch) * 20
                ops.append((OP_SET, k, v))
                committed[k] = v
            await kv.commit(ops, {"durable_version": batch + 1})
        # stage unsynced garbage ops (never committed), then tear
        import contextlib
        with contextlib.suppress(Exception):
            # best-effort: some engines do all their IO inside commit
            f = fs.open("e/kv.wal")
            await f.write(f.size(), b"\xEE" * 700)
        fs.kill_unsynced()
        kv2 = await cls.open(fs, "e/kv")
        got = dict(kv2.range(b"", b"\xff\xff\xff\xff"))
        assert got == committed, (
            f"{engine_name}: {len(got)} rows recovered vs "
            f"{len(committed)} committed")
        assert kv2.meta["durable_version"] == 6
        await kv2.close()
    run_simulation(main())


# --- acceptance: chaos sim with hostile disks on a durable cluster ---

def _digest(rows) -> str:
    h = hashlib.sha256()
    for k, v in sorted(rows):
        h.update(len(k).to_bytes(4, "little") + bytes(k))
        h.update(len(v).to_bytes(4, "little") + bytes(v))
    return h.hexdigest()


def test_chaos_durable_cluster_with_hostile_disks():
    """The ISSUE 12 acceptance: buggify + attrition kills + the full
    disk-fault profile (torn writes, corruption, IO errors, stalls) on
    a durable 5-machine cluster under live writes — zero acked-write
    loss and the recovered keyspace sha256-byte-identical to the acked
    oracle (ambiguous commit_unknown_result keys resolved against the
    surviving state: old or new, never garbage)."""
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.runtime.buggify import enable_buggify
    from foundationdb_tpu.runtime.errors import (CommitUnknownResult,
                                                 FdbError)
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    knobs = Knobs().override(BUGGIFY_ENABLED=True,
                             STORAGE_VERSION_WINDOW=200_000,
                             STORAGE_DURABILITY_LAG=0.1)
    enable_buggify(True)

    async def main():
        sim = SimulatedCluster(knobs, n_machines=5, durable_storage=True,
                               spec=ClusterConfigSpec(min_workers=5,
                                                      replication=2))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        # arm every machine's hostile-disk profile
        for i, m in enumerate(sim.machines):
            m.fault_profile.arm(DeterministicRandom(1000 + i),
                                io_error_p=0.01, stall_p=0.02,
                                stall_max_s=0.02, torn_p=1.0,
                                corrupt_p=0.3)

        acked: dict[bytes, bytes] = {}
        ambiguous: dict[bytes, tuple[bytes | None, bytes]] = {}

        async def writer(wid: int, lo: int, hi: int) -> None:
            for i in range(lo, hi):
                key = b"chaos%05d" % i
                val = b"w%d-" % wid + b"v" * 40
                tr = db.create_transaction()
                while True:
                    try:
                        tr.set(key, val)
                        await tr.commit()
                        acked[key] = val
                        break
                    except CommitUnknownResult:
                        ambiguous[key] = (acked.get(key), val)
                        break
                    except BaseException as e:
                        try:
                            await tr.on_error(e)
                        except FdbError:
                            ambiguous[key] = (acked.get(key), val)
                            break
                # paced so the kills land UNDER live writes
                await asyncio.sleep(0.15)

        async def chaos() -> None:
            # kill + reboot two non-coordinator machines mid-write: the
            # kill tears their unsynced writes, the reboot re-adopts
            # the surviving durable state.  No epoch-bump wait: a
            # machine hosting only storage replicas dies without an
            # epoch recovery (its team's survivor keeps serving), and
            # its rejoin-on-reboot requests one itself.
            for m in (sim.machines[3], sim.machines[4]):
                await asyncio.sleep(2.0)
                await m.kill()
                await asyncio.sleep(1.5)
                await m.reboot()
                await asyncio.sleep(1.0)

        await asyncio.gather(
            writer(0, 0, 40), writer(1, 40, 80), chaos())
        # wind down live injection; kills are over — the final read
        # runs on quiet disks (the DiskFaultWorkload discipline)
        injected = 0
        for m in sim.machines:
            s = m.fault_profile.stats()
            injected += s["io_errors"] + s["stalls"] + s["torn_kills"]
            m.fault_profile.quiesce()
        assert injected > 0, \
            "no fault ever fired — this chaos run proved nothing"

        async def read_all():
            tr = db.create_transaction()
            while True:
                try:
                    return await tr.get_range(b"chaos", b"chaot",
                                              snapshot=True)
                except BaseException as e:
                    await tr.on_error(e)

        rows = await read_all()
        got = {bytes(k): bytes(v) for k, v in rows}
        # zero acked-write loss, byte-identical to the oracle: every
        # acked key must hold exactly its acked value; an ambiguous key
        # resolves to either side of its race but never to garbage
        expected = dict(acked)
        for key, (old, new) in ambiguous.items():
            if key in expected:     # a later acked write overwrote it
                continue
            cur = got.get(key)
            assert cur in (old, new), (
                f"ambiguous key {key!r} holds {cur!r}, neither the "
                f"prior value {old!r} nor the attempted {new!r}")
            if cur is None:
                continue
            expected[key] = cur
        assert _digest(got.items()) == _digest(expected.items()), (
            f"recovered keyspace diverged from the acked oracle: "
            f"{len(got)} rows vs {len(expected)} expected")
        assert len(acked) >= 60, f"only {len(acked)} acked commits"
        await sim.stop()

    run_simulation(main(), seed=1212)


# --- gray failure: a slow-but-alive disk is detected and avoided ---

def test_gray_failure_detection_and_avoidance():
    """One machine's disk stalled through the latency profile must be
    (a) marked degraded in the CC's FailureMonitor via the disk-health
    poll, (b) avoided by DD destination picking (dd_stats counts it),
    and (c) surfaced in the cluster.degraded status rollup with its
    latency."""
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.core.status import cluster_status
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    knobs = Knobs().override(DD_ENABLED=True, DD_INTERVAL=1.0,
                             CC_DISK_HEALTH_INTERVAL=0.25,
                             DISK_DEGRADED_LATENCY_MS=5.0,
                             STORAGE_VERSION_WINDOW=50_000,
                             STORAGE_DURABILITY_LAG=0.1)

    async def main():
        sim = SimulatedCluster(knobs, n_machines=6, durable_storage=True,
                               spec=ClusterConfigSpec(min_workers=6,
                                                      replication=2))
        await sim.start()
        state = await sim.wait_epoch(1)
        db = await sim.database()
        # stall a machine that hosts a storage replica (durable ticks
        # guarantee a steady stream of disk ops to measure)
        storage_ips = {s["worker"][0] for s in state["storage"]}
        victim = next(m for m in sim.machines if m.ip in storage_ips)
        victim.fault_profile.arm(DeterministicRandom(5),
                                 stall_floor_s=0.02)

        async def writers() -> None:
            for i in range(60):
                await db.set(b"gray%04d" % i, b"v" * 64)
                await asyncio.sleep(0.05)

        async def wait_degraded() -> None:
            cc = sim.leader_cc()
            deadline = asyncio.get_running_loop().time() + 60
            while not cc.fm.is_degraded(victim.addr):
                assert asyncio.get_running_loop().time() < deadline, \
                    "degraded disk never detected"
                await asyncio.sleep(0.25)

        await asyncio.gather(writers(), wait_degraded())
        cc = sim.leader_cc()
        assert victim.addr in cc.fm.degraded_addresses()
        # recruitment ordering: the degraded machine sorts last
        live = cc._live_workers()
        ordered = cc.order_for_recruitment(live)
        assert ordered[-1][0] == victim.addr
        assert len(ordered) == len(live)
        # DD destination picking skips it while healthy workers exist
        dd = sim.leader_dd()
        picks = {dd._pick_worker() for _ in range(12)}
        assert victim.addr not in picks, picks
        assert dd.degraded_avoided > 0
        assert "degraded_avoided" in dd.stats()
        # status rollup: the slowed disk shows up with latency + flag
        ct = sim.client_transport()
        doc = await cluster_status(sim.knobs, ct,
                                   sim.coordinator_stubs(ct))
        deg = doc["cluster"]["degraded"]
        assert deg["count"] >= 1, deg
        entry = next(e for e in deg["disks"] if e["ip"] == victim.ip)
        assert entry["degraded"] and entry["latency_ms"] >= 5.0, entry
        # healthy machines are NOT flagged
        assert all(not e["degraded"] for e in deg["disks"]
                   if e["ip"] != victim.ip), deg
        await sim.stop()

    run_simulation(main(), seed=77)
