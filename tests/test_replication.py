"""Replication, load-balanced reads, and ratekeeper admission."""

import asyncio

import pytest

from foundationdb_tpu.client import Database
from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
from foundationdb_tpu.core.data import KeyRange
from foundationdb_tpu.core.load_balance import ReplicaGroup
from foundationdb_tpu.core.ratekeeper import Ratekeeper
from foundationdb_tpu.runtime.errors import ConnectionFailed
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


def sim(coro_fn, seed=0, config=None, knobs=None):
    async def main():
        async with Cluster(config or ClusterConfig(),
                           knobs or Knobs()) as cluster:
            return await coro_fn(Database(cluster))
    return run_simulation(main(), seed=seed)


def test_double_replication_reads_and_consistency():
    cfg = ClusterConfig(storage_servers=2, replication=2, logs=2)

    async def body(db):
        for i in range(30):
            await db.set(b"k%02d" % i, b"v%d" % i)
        rows = await db.get_range(b"", b"\xff")
        assert len(rows) == 30
        # every replica of every shard applied identical data
        cluster = db.cluster
        for group in cluster._replica_groups:
            replicas = group.replicas
            assert len(replicas) == 2
            datas = []
            for ss in replicas:
                kvs, _ = await ss.get_key_values(
                    ss.shard.begin, ss.shard.end, ss.version)
                datas.append(kvs)
            assert datas[0] == datas[1], "replicas diverged"
        # reads were spread across replicas, not pinned to one
        reads = [ss.total_reads for ss in cluster.storage_servers]
        assert sum(1 for r in reads if r > 0) >= 3
    sim(body, config=cfg)


def test_load_balance_fails_over():
    class FlakyStorage:
        def __init__(self, tag, fail):
            self.tag = tag
            self.fail = fail
            self.calls = 0

        async def get_value(self, key, version):
            self.calls += 1
            if self.fail:
                raise ConnectionFailed()
            return b"ok"

    async def main():
        good = FlakyStorage(0, fail=False)
        bad = FlakyStorage(1, fail=True)
        group = ReplicaGroup(KeyRange(b"", b"\xff"), [bad, good])
        # every read succeeds despite one dead replica
        for _ in range(10):
            assert await group.get_value(b"k", 1) == b"ok"
        assert good.calls >= 10
        # after the first failure the dead replica is penalized, so it is
        # not hammered on every request
        assert bad.calls < 10
    run_simulation(main(), seed=2)


def test_load_balance_nonretryable_propagates():
    from foundationdb_tpu.runtime.errors import TransactionTooOld

    class OldStorage:
        tag = 0

        async def get_value(self, key, version):
            raise TransactionTooOld()

    async def main():
        group = ReplicaGroup(KeyRange(b"", b"\xff"), [OldStorage()])
        with pytest.raises(TransactionTooOld):
            await group.get_value(b"k", 1)
    run_simulation(main())


def test_ratekeeper_throttles_on_queue():
    class FakeSS:
        def __init__(self):
            self.tag = 0
            self.engine = object()
            self.bytes_input = 10_000
            self.bytes_durable = 0
            self.version = 0
            self.durable_version = 0

    async def main():
        k = Knobs().override(TARGET_STORAGE_QUEUE_BYTES=10_000,
                             RATEKEEPER_MAX_TPS=1000.0,
                             RATEKEEPER_MIN_TPS=5.0)
        rk = Ratekeeper(k, [FakeSS()], [])
        await rk._recompute()
        # queue at 100% of target: rate pinned to the floor
        assert rk.rate_tps == 5.0
        assert "storage_queue" in rk.limiting_reason
        # admission now takes real (virtual) time
        t0 = asyncio.get_running_loop().time()
        await rk.admit(50)
        await rk.admit(50)
        assert asyncio.get_running_loop().time() - t0 >= 50 / 5.0
    run_simulation(main())


def test_ratekeeper_full_rate_when_healthy():
    class HealthySS:
        tag = 0
        engine = None

    async def main():
        k = Knobs()
        rk = Ratekeeper(k, [HealthySS()], [])
        await rk._recompute()
        assert rk.rate_tps == k.RATEKEEPER_MAX_TPS
        assert rk.limiting_reason == "unlimited"
    run_simulation(main())


def test_replicated_durable_restart():
    from foundationdb_tpu.runtime.files import SimFileSystem

    async def main():
        fs = SimFileSystem()
        cfg = ClusterConfig(storage_servers=2, replication=2, logs=2)
        k = Knobs().override(STORAGE_VERSION_WINDOW=50_000,
                             STORAGE_DURABILITY_LAG=0.05)
        cluster = await Cluster.create(cfg, k, fs=fs, data_dir="r")
        async with cluster:
            db = Database(cluster)
            for i in range(12):
                await db.set(b"k%02d" % i, b"v")
            await asyncio.sleep(1.0)
        fs.kill_unsynced()
        cluster2 = await Cluster.create(cfg, k, fs=fs, data_dir="r")
        async with cluster2:
            rows = await Database(cluster2).get_range(b"", b"\xff")
            assert len(rows) == 12
    run_simulation(main(), seed=9)
