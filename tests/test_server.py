"""Deployment smoke test: a real 3-process cluster on localhost TCP.

Spawns three ``python -m foundationdb_tpu.server`` processes from a
cluster file (all three coordinators), waits for election + recovery,
then drives set/get/getrange/status through the CLI path — the deployment
story of REF:fdbserver/fdbserver.actor.cpp + fdbcli.
"""

from __future__ import annotations

import asyncio
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from foundationdb_tpu.core.cluster_file import ClusterFile
from foundationdb_tpu.rpc.transport import NetworkAddress

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_cluster_file_roundtrip(tmp_path):
    cf = ClusterFile("test", "abc123", [NetworkAddress("127.0.0.1", 4500),
                                        NetworkAddress("127.0.0.1", 4501)])
    p = tmp_path / "fdb.cluster"
    cf.save(str(p))
    cf2 = ClusterFile.load(str(p))
    assert cf2 == cf
    with pytest.raises(ValueError):
        ClusterFile.parse("garbage")


def test_three_process_cluster_smoke(tmp_path):
    ports = free_ports(3)
    cf = ClusterFile("smoke", "t1",
                     [NetworkAddress("127.0.0.1", p) for p in ports])
    cf_path = tmp_path / "fdb.cluster"
    cf.save(str(cf_path))

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = []
    try:
        for p in ports:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "foundationdb_tpu.server",
                 "-C", str(cf_path), "-l", f"127.0.0.1:{p}",
                 "--spec", "min_workers=3"],
                cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT))

        async def drive():
            from foundationdb_tpu.cli import open_cli
            from foundationdb_tpu.runtime.knobs import Knobs
            cli = await open_cli(str(cf_path), Knobs(), timeout=60.0)
            assert await cli.execute("set hello world") == "Committed"
            assert await cli.execute("set hellp worle") == "Committed"
            out = await cli.execute("get hello")
            assert out == "`hello' is `world'"
            out = await cli.execute("getrange hell hellz")
            assert "`hello' is `world'" in out and "`hellp' is `worle'" in out
            out = await cli.execute("status")
            assert "epoch: 1" in out
            assert await cli.execute("clear hello") == "Committed"
            out = await cli.execute("get hello")
            assert "not found" in out

        asyncio.run(asyncio.wait_for(drive(), timeout=90.0))
    finally:
        tails = []
        for pr in procs:
            pr.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for pr in procs:
            try:
                out, _ = pr.communicate(timeout=max(0.1, deadline - time.time()))
                tails.append(out.decode(errors="replace")[-2000:])
            except subprocess.TimeoutExpired:
                pr.kill()
                out, _ = pr.communicate()
                tails.append("KILLED\n" + out.decode(errors="replace")[-2000:])
        if any("Traceback" in t for t in tails):
            print("\n=== server logs ===")
            for i, t in enumerate(tails):
                print(f"--- server {i} ---\n{t}")
