"""Deployment smoke test: a real 3-process cluster on localhost TCP.

Spawns three ``python -m foundationdb_tpu.server`` processes from a
cluster file (all three coordinators), waits for election + recovery,
then drives set/get/getrange/status through the CLI path — the deployment
story of REF:fdbserver/fdbserver.actor.cpp + fdbcli.
"""

from __future__ import annotations

import asyncio
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from foundationdb_tpu.core.cluster_file import ClusterFile
from foundationdb_tpu.rpc.transport import NetworkAddress

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn_server(args: list[str], log_path, env) -> subprocess.Popen:
    """Server subprocess with output to a FILE, not a pipe: a pipe
    nobody drains blocks the server at 64KB of trace output and wedges
    the cluster (the bug fdbmonitor's logdir exists to prevent)."""
    log = open(log_path, "ab")
    try:
        return subprocess.Popen(args, cwd=REPO, env=env, stdout=log,
                                stderr=subprocess.STDOUT)
    finally:
        log.close()


def server_log_tail(log_path, n: int = 2000) -> str:
    try:
        with open(log_path, "rb") as f:
            return f.read().decode(errors="replace")[-n:]
    except OSError:
        return ""


def teardown_servers(procs, logs=None) -> None:
    """SIGTERM every live server, escalate to SIGKILL on a shared
    deadline, and dump log tails when any server crashed."""
    procs = list(procs.values()) if isinstance(procs, dict) else list(procs)
    for pr in procs:
        if pr.poll() is None:
            pr.send_signal(signal.SIGTERM)
    deadline = time.time() + 10
    for pr in procs:
        try:
            pr.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            pr.kill()
            pr.wait()
    if logs:
        tails = {str(lg): server_log_tail(lg) for lg in logs}
        if any("Traceback" in t for t in tails.values()):
            print("\n=== server logs ===")
            for name, t in tails.items():
                print(f"--- {name} ---\n{t}")


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_cluster_file_roundtrip(tmp_path):
    cf = ClusterFile("test", "abc123", [NetworkAddress("127.0.0.1", 4500),
                                        NetworkAddress("127.0.0.1", 4501)])
    p = tmp_path / "fdb.cluster"
    cf.save(str(p))
    cf2 = ClusterFile.load(str(p))
    assert cf2 == cf
    with pytest.raises(ValueError):
        ClusterFile.parse("garbage")


def test_three_process_cluster_smoke(tmp_path):
    ports = free_ports(3)
    cf = ClusterFile("smoke", "t1",
                     [NetworkAddress("127.0.0.1", p) for p in ports])
    cf_path = tmp_path / "fdb.cluster"
    cf.save(str(cf_path))

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = []
    logs = [tmp_path / f"server-{p}.log" for p in ports]
    try:
        for p, lg in zip(ports, logs):
            procs.append(spawn_server(
                [sys.executable, "-m", "foundationdb_tpu.server",
                 "-C", str(cf_path), "-l", f"127.0.0.1:{p}",
                 "--spec", "min_workers=3"], lg, env))

        async def drive():
            from foundationdb_tpu.cli import open_cli
            from foundationdb_tpu.runtime.knobs import Knobs
            cli = await open_cli(str(cf_path), Knobs(), timeout=60.0)
            assert await cli.execute("set hello world") == "Committed"
            assert await cli.execute("set hellp worle") == "Committed"
            out = await cli.execute("get hello")
            assert out == "`hello' is `world'"
            out = await cli.execute("getrange hell hellz")
            assert "`hello' is `world'" in out and "`hellp' is `worle'" in out
            out = await cli.execute("status")
            assert "epoch: 1" in out
            assert await cli.execute("clear hello") == "Committed"
            out = await cli.execute("get hello")
            assert "not found" in out

        asyncio.run(asyncio.wait_for(drive(), timeout=90.0))
    finally:
        teardown_servers(procs, logs)


def test_change_coordinators_through_cli(tmp_path):
    """changeQuorum over real TCP: 4 processes, coordinators move from
    {0,1,2} to {1,2,3} via the cli `coordinators` command; the cluster
    file is rewritten and the cluster keeps serving."""
    ports = free_ports(4)
    cf = ClusterFile("movq", "t1",
                     [NetworkAddress("127.0.0.1", p) for p in ports[:3]])
    cf_path = tmp_path / "fdb.cluster"
    cf.save(str(cf_path))

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = []
    logs = [tmp_path / f"server-{p}.log" for p in ports]
    try:
        for p, lg in zip(ports, logs):
            procs.append(spawn_server(
                [sys.executable, "-m", "foundationdb_tpu.server",
                 "-C", str(cf_path), "-l", f"127.0.0.1:{p}",
                 "--spec", "min_workers=4"], lg, env))

        async def drive():
            from foundationdb_tpu.cli import open_cli
            from foundationdb_tpu.runtime.knobs import Knobs
            cli = await open_cli(str(cf_path), Knobs(), timeout=60.0)
            assert await cli.execute("set before move") == "Committed"
            new = ",".join(f"127.0.0.1:{p}" for p in ports[1:])
            out = await cli.execute(f"coordinators {new}")
            assert out == "Coordinators changed", out
            # the cli's cluster file now names the new set
            cf2 = ClusterFile.load(str(cf_path))
            assert [a.port for a in cf2.coordinators] == ports[1:]
            # the cluster keeps serving through the new set (recovery may
            # be in flight while hosts repoint: retry within a budget)
            deadline = time.time() + 60
            while True:
                out = await cli.execute("set after move")
                if out == "Committed":
                    break
                assert time.time() < deadline, out
                await asyncio.sleep(1.0)
            assert await cli.execute("get before") == "`before' is `move'"
            out = await cli.execute("coordinators")
            assert all(f":{p}" in out for p in ports[1:])

        asyncio.run(asyncio.wait_for(drive(), timeout=150.0))
    finally:
        teardown_servers(procs, logs)


def test_dr_and_lock_through_cli(tmp_path):
    """fdbdr analog end-to-end over real TCP: two single-process
    clusters, `dr start/status/switch` plus `lock`/`unlock` through the
    CLI; after switchover the destination holds the data and the source
    is fenced."""
    # one server per cluster: multi-process clustering is covered by the
    # smoke test above, and real-TCP leases churn under CPU load when four
    # JAX server processes share this VM
    ports = free_ports(2)
    files = []
    for name, pair in (("src", ports[:1]), ("dst", ports[1:])):
        cf = ClusterFile(name, "t1",
                         [NetworkAddress("127.0.0.1", p) for p in pair])
        path = tmp_path / f"{name}.cluster"
        cf.save(str(path))
        files.append(str(path))
    src_cf, dst_cf = files

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = []
    try:
        for cf_path, pair in ((src_cf, ports[:1]), (dst_cf, ports[1:])):
            for p in pair:
                procs.append(spawn_server(
                    [sys.executable, "-m", "foundationdb_tpu.server",
                     "-C", cf_path, "-l", f"127.0.0.1:{p}",
                     "--spec", "min_workers=1"],
                    tmp_path / f"server-{p}.log", env))

        async def drive():
            from foundationdb_tpu.cli import open_cli
            from foundationdb_tpu.runtime.knobs import Knobs
            src = await open_cli(src_cf, Knobs(), timeout=60.0)
            dst = await open_cli(dst_cf, Knobs(), timeout=60.0)
            assert await src.execute("set alpha one") == "Committed"
            out = await src.execute(f"dr start {dst_cf}")
            assert out.startswith("DR started"), out
            assert await src.execute("set beta two") == "Committed"
            out = await src.execute("dr status")
            assert "running: True" in out, out
            out = await src.execute("dr switch")
            assert "destination is primary" in out, out
            # destination has both writes; source is fenced
            assert await dst.execute("get alpha") == "`alpha' is `one'"
            assert await dst.execute("get beta") == "`beta' is `two'"
            out = await src.execute("set gamma three")
            assert "ERROR" in out or "database_locked" in out, out
            # destination keeps serving writes
            assert await dst.execute("set gamma ok") == "Committed"

        asyncio.run(asyncio.wait_for(drive(), timeout=240.0))
    finally:
        teardown_servers(procs)


def test_tcp_leader_kill_failover(tmp_path):
    """The wall-clock churn scenario the two-phase nominate/confirm
    election exists for: SIGKILL the elected cluster controller's
    process on a loaded single-CPU host, and the survivors must
    re-elect exactly one leader, recover a new epoch, and serve
    transactions — no split grant, no leadership ping-pong."""
    ports = free_ports(3)
    cf = ClusterFile("failover", "t1",
                     [NetworkAddress("127.0.0.1", p) for p in ports])
    cf_path = tmp_path / "fdb.cluster"
    cf.save(str(cf_path))

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = {}
    logs = {p: tmp_path / f"server-{p}.log" for p in ports}
    try:
        for p in ports:
            # min_workers=2: recovery after the kill must be able to
            # complete with the two survivors.  replication=2 is the
            # POINT of the scenario: with the default replication=1 a
            # killed host's shard is *correctly* unavailable forever
            # (its only replica died), so the post-failover reads would
            # legitimately never succeed — diagnosed the hard way via
            # per-replica error tracing
            procs[p] = spawn_server(
                [sys.executable, "-m", "foundationdb_tpu.server",
                 "-C", str(cf_path), "-l", f"127.0.0.1:{p}",
                 "--spec", "min_workers=2,replication=2"], logs[p], env)

        async def drive():
            from foundationdb_tpu.cli import open_cli
            from foundationdb_tpu.rpc.stubs import CoordinatorClient
            from foundationdb_tpu.rpc.tcp_transport import TcpTransport
            from foundationdb_tpu.rpc.transport import WLTOKEN_COORDINATOR
            from foundationdb_tpu.runtime.knobs import Knobs

            cli = await open_cli(str(cf_path), Knobs(), timeout=90.0)
            assert await cli.execute("set before failover") == "Committed"

            # locate the elected leader through the coordinators
            t = TcpTransport(NetworkAddress("127.0.0.1", 0))
            leader_port = None
            try:
                for p in ports:
                    co = CoordinatorClient(t, NetworkAddress("127.0.0.1", p),
                                           WLTOKEN_COORDINATOR)
                    try:
                        led = await asyncio.wait_for(co.read_leader(), 5.0)
                    except (Exception, asyncio.TimeoutError):
                        continue
                    if led is not None:
                        leader_port = led[1][1]
                        break
            finally:
                await t.close()
            assert leader_port in procs, f"no leader found ({leader_port})"

            procs[leader_port].kill()          # SIGKILL: no goodbye
            procs[leader_port].wait()

            # the survivors re-elect and recover; every CLI call may
            # retry through the recovery window.  Each attempt is
            # bounded: a single hung await must surface as a diagnosable
            # timeout (with parked-task stacks), not eat the whole budget
            async def bounded(line, want, budget=60.0):
                deadline = time.time() + budget
                last = None
                while True:
                    try:
                        out = await asyncio.wait_for(cli.execute(line), 30.0)
                        if want in out:
                            return out
                        last = out
                    except asyncio.TimeoutError:
                        view = cli.view
                        last = (f"{line!r} hung >30s; epoch={view.epoch} "
                                f"teams={view.shard_map.shard_tags} "
                                f"storage={[(s.tag, s._address.port) for s in view.storage_clients]}")
                    except Exception as e:  # noqa: BLE001 — retry window
                        last = repr(e)
                    assert time.time() < deadline, f"no recovery: {last}"
                    await asyncio.sleep(2.0)

            await bounded("set after failover", "Committed")
            assert await bounded("get before", "is") \
                == "`before' is `failover'"
            assert await bounded("get after", "is") == "`after' is `failover'"
            out = await bounded("status", "epoch:")
            assert "epoch:" in out

        asyncio.run(asyncio.wait_for(drive(), timeout=300.0))
    finally:
        teardown_servers(procs, logs.values())
