"""Same-seed sim-trace determinism with the device pipeline ON (ISSUE 6).

The repo's standing discipline: a seeded 5-machine sim must produce a
BIT-IDENTICAL trace across two fresh-process runs.  The device commit
pipeline moves resolver dispatch onto a pump task with async verdict
readback (device/pipeline.py), which is exactly the kind of change that
could reorder observable events without failing any semantic test — so
the discipline is now a standing tier-1 test, not a manual note in
CHANGES.md.  Fresh processes (not two in-process runs) because hash
seeds, import order, and interned-object identity are per-process
state a same-process repeat would share.

The child half runs under ``python tests/test_sim_determinism.py
--child <trace-path>``: a seeded multi-role sim with
RESOLVER_DEVICE_PIPELINE forced ON and every transaction sampled, then
prints the sha256 of the (rolled) trace JSONL.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys

_THIS = os.path.abspath(__file__)
_REPO = os.path.dirname(os.path.dirname(_THIS))

_SEED = 4321
_N_MACHINES = 5


def _child(path: str, mode: str = "default") -> None:
    import asyncio

    sys.path.insert(0, _REPO)
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.runtime import span as span_mod
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.runtime.simloop import run_simulation
    from foundationdb_tpu.runtime.trace import Severity, TraceLog, set_trace_log
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    log = TraceLog(path=path, min_severity=Severity.DEBUG)
    set_trace_log(log)
    span_mod.reset_totals()
    # ISSUE 7 acceptance: with the heat CONSUMERS off (their defaults —
    # pinned explicitly here so a default flip can't silently change
    # what this test proves) the trace must stay bit-identical; the
    # tracker itself always runs, so its accounting being deterministic
    # is part of what the same-seed comparison now covers
    # ISSUE 8: the backup knobs are pinned OFF explicitly (the PR 7
    # pattern) — this sim runs no backup agent, but a future default
    # flip arming anything cluster-side (progress state transactions,
    # an auto-started tail) must not silently change what the
    # bit-identical acceptance proves
    # ISSUE 9: the packed range-read path is pinned ON explicitly (its
    # default) — the bit-identical acceptance must cover the columnar
    # read path, and a future default flip must not silently change
    # what this test proves
    # ISSUE 11: the durability-ring spill budget is pinned at its
    # default (large enough that this sim never spills); the "spill"
    # mode instead forces a 1-byte budget on DURABLE storage so every
    # durability tick spills+reads back — the bit-identical acceptance
    # then covers the spill path itself (spill decisions are byte- and
    # version-driven, no RNG, so same-seed traces must still match)
    # ISSUE 12: the disk-fault knobs are pinned at their defaults (OFF)
    # explicitly — the standing bit-identical children must keep proving
    # the fault-free path, and a future default flip arming injection
    # (or changing the CC health-poll cadence) must not silently change
    # what they prove.  The "faults" mode instead forces injection ON
    # (stalls + IO errors on a durable cluster), asserting
    # DiskFaultInjected events are present, the acked writes all
    # survive, and the trace is STILL bit-identical — every fault draw
    # comes from per-machine seeded streams, so hostile disks add
    # chaos, never nondeterminism.
    # ISSUE 13: the columnar MVCC window is pinned at its default (ON)
    # explicitly — the standing bit-identical children cover the
    # columnar window serving every read; the "mvcc_on"/"mvcc_off"
    # modes instead force the knob each way on DURABLE storage with a
    # tiny seal budget and a tight version window, so seals, tiered
    # compaction and whole-segment drops all run inside the
    # bit-identical proof for BOTH implementations
    # ISSUE 15: the metrics plane is pinned ON with a tight interval —
    # every standing bit-identical child now proves the registry
    # emitter's per-interval *Metrics streams replay exactly (emission
    # order is registration order, cadence is the virtual clock); the
    # "metrics_off" mode forces the emitter OFF so the plane-less twin
    # keeps its own bit-identical proof and a future knob-default flip
    # cannot silently change what either child demonstrates
    # ISSUE 16: routed mesh resolution is pinned ON (its default) and
    # the heat-driven resolver rebalance OFF (its default) explicitly —
    # the standing children prove the routed proxy path replays exactly;
    # the "mesh_on"/"mesh_off" modes instead recruit a 2-resolver
    # transaction subsystem and force the routing knob each way, so the
    # empty-clip fast path + sparse sub-batch scatter (ON) and the
    # verbatim broadcast twin (OFF) each carry their own bit-identical
    # proof
    # ISSUE 17: the consistency scrubber is pinned OFF (its default)
    # explicitly — the standing children keep proving the scrub-less
    # trace, and a future default flip arming the always-on audit plane
    # (its digest RPCs, GRV pins and watchdog rounds all emit traffic
    # and events) must not silently change what they prove.  The
    # "scrub_on"/"scrub_off" modes instead force the knob each way at a
    # hot cadence, so the audit plane itself carries its own
    # bit-identical proof.
    # ISSUE 18: the device-plane knobs are pinned explicitly — verdict
    # bitmask readback ON (its default; the packed-words reply path is
    # now inside every standing bit-identical proof), the Pallas
    # in-place ring write OFF (its default) and the sharded read mirror
    # OFF (shards=0) — so a future default flip on any of the three
    # cannot silently change what these children prove.  The "devplane"
    # mode instead forces the OTHER side of each: shards=4, bitmask OFF,
    # ring_inplace ON (interpret-mode on CPU), so the flipped plane
    # carries its own bit-identical proof.
    # ISSUE 19: the layer-ecosystem knobs are pinned at their defaults
    # explicitly — layers are client-side objects, so nothing runs in
    # the standing children unless one is constructed, but a future
    # default flip (a hotter poll cadence, async index mode, a different
    # progress publish pace) must not silently change what the "layers"
    # mode below proves.  That mode constructs the REAL stack (feed
    # consumer + async index + cache + watches) with every layer knob
    # flipped away from its default at once, and drives it inside the
    # bit-identical proof; the standing children stay layer-less, so
    # they also prove layers-off traces carry zero layer traffic.
    knobs = Knobs().override(CLIENT_LATENCY_PROBE_SAMPLE=1.0,
                             RESOLVER_DEVICE_PIPELINE=True,
                             DD_SHARD_HEAT_SPLITS=False,
                             CLIENT_READ_LOAD_BALANCE="score",
                             BACKUP_PROGRESS_PUBLISH=False,
                             CLIENT_PACKED_RANGE_READS=True,
                             STORAGE_DBUF_SPILL_BYTES=128 << 20,
                             SIM_DISK_FAULTS=False,
                             CC_DISK_HEALTH_INTERVAL=1.0,
                             DISK_DEGRADED_LATENCY_MS=25.0,
                             STORAGE_MVCC_COLUMNAR=True,
                             METRICS_EMITTER=True,
                             METRICS_INTERVAL=1.0,
                             RESOLVER_MESH_ROUTING=True,
                             RESOLVER_REBALANCE=False,
                             SCRUB_ENABLED=False,
                             RESOLVER_VERDICT_BITMASK=True,
                             RESOLVER_RING_INPLACE=False,
                             STORAGE_DEVICE_READ_SHARDS=0,
                             LAYER_FEED_POLL_INTERVAL=0.05,
                             LAYER_FEED_POP_LAG_VERSIONS=1_000_000,
                             LAYER_INDEX_TRANSACTIONAL=True,
                             LAYER_CACHE_CAPACITY=4096,
                             LAYER_WATCH_LIMIT=10_000,
                             LAYER_PROGRESS_INTERVAL=1.0,
                             LAYER_CHECK_PAGE_ROWS=256)
    durable = False
    n_resolvers = 1
    if mode == "metrics_off":
        knobs = knobs.override(METRICS_EMITTER=False)
    if mode == "spill":
        knobs = knobs.override(STORAGE_DBUF_SPILL_BYTES=1,
                               STORAGE_VERSION_WINDOW=1_000,
                               STORAGE_DURABILITY_LAG=0.1)
        durable = True
    elif mode == "faults":
        knobs = knobs.override(SIM_DISK_FAULTS=True,
                               SIM_DISK_IO_ERROR_P=0.02,
                               SIM_DISK_STALL_P=0.3,
                               SIM_DISK_STALL_MAX_S=0.01,
                               STORAGE_VERSION_WINDOW=100_000,
                               STORAGE_DURABILITY_LAG=0.1)
        durable = True
    elif mode in ("mvcc_on", "mvcc_off"):
        knobs = knobs.override(STORAGE_MVCC_COLUMNAR=(mode == "mvcc_on"),
                               STORAGE_MVCC_SEAL_OPS=8,
                               STORAGE_VERSION_WINDOW=1_000,
                               STORAGE_DURABILITY_LAG=0.1)
        durable = True
    elif mode in ("mesh_on", "mesh_off"):
        # ISSUE 16: a 2-resolver transaction subsystem so the routing
        # knob actually selects between paths — the workload's det-k*
        # keys all sit below the \x80 partition boundary, so routing ON
        # exercises sparse sub-batches to partition 0 AND header-only
        # version advances to partition 1, while routing OFF replays the
        # verbatim clipped-broadcast twin
        knobs = knobs.override(
            RESOLVER_MESH_ROUTING=(mode == "mesh_on"))
        n_resolvers = 2
    elif mode in ("scrub_on", "scrub_off"):
        # ISSUE 17: the always-on audit plane forced each way at a hot
        # cadence — full replica-digest passes, mismatch-free triage
        # arithmetic, watchdog rounds and scrub_stats publishes all run
        # inside the bit-identical proof when ON; the OFF twin proves
        # the knob gates the plane outright
        knobs = knobs.override(SCRUB_ENABLED=(mode == "scrub_on"),
                               SCRUB_PASS_INTERVAL=0.5,
                               SCRUB_WATCHDOG_INTERVAL=0.5,
                               SCRUB_PAGES_PER_SEC=500.0,
                               SCRUB_PAGE_ROWS=8,
                               SCRUB_MAX_PAGES_PER_REQUEST=4)
    elif mode == "devplane":
        # ISSUE 18: every device-plane knob flipped AWAY from its
        # default at once — a 4-shard read mirror (the forced 8-CPU
        # device shape), raw-vector verdict readback, and the Pallas
        # in-place ring append (interpret mode on CPU).  The flipped
        # plane must replay bit-identically too.
        knobs = knobs.override(RESOLVER_VERDICT_BITMASK=False,
                               RESOLVER_RING_INPLACE=True,
                               STORAGE_DEVICE_READ_SHARDS=4)
    elif mode == "layers":
        # ISSUE 19: every layer knob flipped AWAY from its default at
        # once — a hotter feed poll, a tiny pop lag, async index mode,
        # a small LRU, a tight watch limit, a faster progress publish,
        # small checker pages — with the real client-side stack
        # constructed and driven below.  The flipped ecosystem must
        # replay bit-identically too: the consumer's poll cadence,
        # progress-publish transactions and flush commits all ride the
        # virtual clock.
        knobs = knobs.override(LAYER_FEED_POLL_INTERVAL=0.01,
                               LAYER_FEED_POP_LAG_VERSIONS=1_000,
                               LAYER_INDEX_TRANSACTIONAL=False,
                               LAYER_CACHE_CAPACITY=8,
                               LAYER_WATCH_LIMIT=4,
                               LAYER_PROGRESS_INTERVAL=0.25,
                               LAYER_CHECK_PAGE_ROWS=8)
    elif mode in ("lsm_on", "lsm_off"):
        # ISSUE 14: durable lsm storage with a tiny memtable/trigger so
        # flushes AND compactions run inside the sim — leveled
        # background compaction forced ON (its default) or OFF (the
        # monolithic inline twin).  The background compactor's task
        # scheduling, slice yields and manifest installs are all part
        # of what each pair must replay bit-identically.
        import foundationdb_tpu.storage.lsm as lsm_mod
        lsm_mod._MEMTABLE_BYTES = 1200
        lsm_mod._MAX_RUNS = 2
        lsm_mod._BLOCK_BYTES = 512
        knobs = knobs.override(STORAGE_ENGINE="lsm",
                               LSM_LEVELED_COMPACTION=(mode == "lsm_on"),
                               LSM_COMPACT_SLICE_BYTES=2048,
                               STORAGE_VERSION_WINDOW=1_000,
                               STORAGE_DURABILITY_LAG=0.1)
        durable = True

    async def main():
        sim = SimulatedCluster(knobs, n_machines=_N_MACHINES,
                               durable_storage=durable,
                               spec=ClusterConfigSpec(min_workers=_N_MACHINES,
                                                      replication=2,
                                                      resolvers=n_resolvers))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        for i in range(6):
            async def body(tr, i=i):
                await tr.get(b"det-k%d" % i)
                tr.set(b"det-k%d" % i, b"v%d" % i)
            await db.run(body)

        # one packed range scan (ISSUE 9): the columnar read path's
        # events are part of what must stay bit-identical
        async def scan(tr):
            rows = await tr.get_range(b"det-", b"det.", snapshot=True)
            assert len(rows) == 6, rows
        await db.run(scan)
        if mode == "layers":
            # ISSUE 19: the real layer stack on one whole-db feed,
            # driven through registration, zipfless deterministic
            # reads/writes, a watch fire, an eviction-forcing read run
            # (capacity 8 over more keys), a checker pass over the
            # flipped page size, and a clean teardown — all inside the
            # bit-identical proof
            from foundationdb_tpu.client.subspace import Subspace
            from foundationdb_tpu.layers import (LayerConsistencyChecker,
                                                 LayerFeedConsumer,
                                                 ReadThroughCache,
                                                 SecondaryIndex,
                                                 WatchRegistry)
            consumer = LayerFeedConsumer(db, name="det")
            index = SecondaryIndex(db, Subspace(raw_prefix=b"lidx/"),
                                   primary_begin=b"det-",
                                   primary_end=b"det.",
                                   consumer=consumer)
            assert index.mode == "async", (
                "LAYER_INDEX_TRANSACTIONAL=False no longer selects "
                "async mode — the flipped pin proves nothing")
            cache = ReadThroughCache(db, consumer)
            watches = WatchRegistry(db, consumer)
            checker = LayerConsistencyChecker(db, index=index,
                                              cache=cache,
                                              watches=watches)
            await consumer.start()
            await index.start_async()
            fut = await watches.watch(b"det-k3")
            async def mutate(tr):
                tr.set(b"det-k3", b"layered")
            await db.run(mutate)
            await asyncio.wait_for(fut, 60)
            for i in range(12):        # > capacity 8: evictions run
                await cache.get(b"det-k%d" % (i % 6))
            tr = db.create_transaction()
            tip = await tr.get_read_version()
            tr.reset()
            await consumer.wait_frontier(tip, timeout=60)
            verdict = await checker.check()
            assert verdict["divergences"] == 0, verdict
            await consumer.stop(destroy=True)
        if mode in ("lsm_on", "lsm_off"):
            # ISSUE 14: push enough per-replica volume through the
            # tiny-memtable lsm engine that flushes AND compactions
            # (background leveled merges / inline monolithic ones)
            # run inside the bit-identical proof
            for w in range(14):
                async def wave(tr, w=w):
                    for j in range(6):
                        tr.set(b"lsm-%02d-%02d" % (w, j), b"x" * 120)
                await db.run(wave)
        # let the async halves drain: storage pull/apply and the
        # pipeline's verdict readbacks both emit trace events
        await asyncio.sleep(1.5)
        if mode == "scrub_on":
            # ISSUE 17: hold the sim open long enough that the scrubber
            # (recruited after the first published state) completes at
            # least one full keyspace pass inside the recorded trace
            await asyncio.sleep(4.0)
        await sim.stop()

    run_simulation(main(), seed=_SEED)
    log.close()

    h = hashlib.sha256()
    n = 0
    pipeline_events = 0
    spill_events = 0
    fault_events = 0
    compact_events = 0
    metrics_events = 0
    base = os.path.basename(path)
    d = os.path.dirname(path)
    rolled = sorted(
        e for e in os.listdir(d)
        if e == base or (e.startswith(base + ".")
                         and e[len(base) + 1:].isdigit()))
    for name in rolled:
        with open(os.path.join(d, name), "rb") as f:
            data = f.read()
        h.update(data)
        n += data.count(b"\n")
        pipeline_events += data.count(b"ResolverDevice.")
        spill_events += data.count(b"StorageDbufSpill")
        fault_events += data.count(b"DiskFaultInjected")
        compact_events += data.count(b"LsmCompact")
        metrics_events += data.count(b"Metrics\",")
    print("%s %d %d %d %d %d %d" % (h.hexdigest(), n, pipeline_events,
                                    spill_events, fault_events,
                                    compact_events, metrics_events))


def _run_child(tmp_path, tag: str, mode: str = "default"
               ) -> tuple[str, int, int, int, int, int, int]:
    path = os.path.join(str(tmp_path), f"trace-{tag}.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run([sys.executable, _THIS, "--child", path, mode],
                       cwd=_REPO, env=env, capture_output=True, text=True,
                       timeout=300)
    assert p.returncode == 0, f"child {tag} failed: {p.stderr[-2000:]}"
    (digest, n_events, n_pipeline, n_spill, n_fault, n_compact,
     n_metrics) = p.stdout.strip().splitlines()[-1].split()
    return digest, int(n_events), int(n_pipeline), int(n_spill), \
        int(n_fault), int(n_compact), int(n_metrics)


def test_same_seed_sim_trace_bit_identical_with_pipeline(tmp_path):
    d1, n1, p1, _s1, _f1, _c1, m1 = _run_child(tmp_path, "a")
    d2, n2, p2, *_ = _run_child(tmp_path, "b")
    assert n1 > 100, f"trace suspiciously small ({n1} events)"
    assert p1 > 0, (
        "no ResolverDevice span events in the trace — the device "
        "pipeline path did not run, so this test proved nothing")
    assert m1 > 0, (
        "no *Metrics events in the trace — the metrics-plane emitter "
        "(pinned ON) never fired, so the plane-on half of the ISSUE 15 "
        "determinism acceptance proved nothing")
    assert (d1, n1, p1) == (d2, n2, p2), (
        f"same-seed sim trace diverged across fresh processes with the "
        f"device pipeline ON: run a = {d1} ({n1} events), "
        f"run b = {d2} ({n2} events) — async readback reordered "
        f"observable events")


def test_same_seed_sim_trace_bit_identical_metrics_emitter_off(tmp_path):
    """ISSUE 15 acceptance, the other way: the same seeded sim with the
    registry emitter forced OFF must also replay bit-identically (and
    actually emit no periodic *Metrics stream) — the knob selects the
    plane outright, so each pair proves its own path."""
    d1, n1, _p1, _s1, _f1, _c1, m1 = _run_child(tmp_path, "na",
                                                mode="metrics_off")
    d2, n2, *_ = _run_child(tmp_path, "nb", mode="metrics_off")
    assert n1 > 100, f"trace suspiciously small ({n1} events)"
    assert m1 == 0, (
        f"{m1} *Metrics events with the emitter forced OFF — the knob "
        f"no longer gates the plane")
    assert (d1, n1) == (d2, n2), (
        f"same-seed sim trace diverged with the metrics emitter OFF: "
        f"run a = {d1} ({n1} events), run b = {d2} ({n2} events)")


def test_same_seed_sim_trace_bit_identical_with_spill_forced_on(tmp_path):
    """ISSUE 11 acceptance: a durable same-seed sim with the durability
    ring's spill budget forced to 1 byte (every tick spills sealed
    segments to the side file and reads them back through the commit
    slice) must still produce a BIT-IDENTICAL trace — the spill path
    adds disk hops, never nondeterminism."""
    d1, n1, _p1, s1, *_ = _run_child(tmp_path, "sa", mode="spill")
    d2, n2, _p2, s2, *_ = _run_child(tmp_path, "sb", mode="spill")
    assert n1 > 100, f"trace suspiciously small ({n1} events)"
    assert s1 > 0, (
        "no StorageDbufSpill events in the trace — the forced-on spill "
        "path did not run, so this test proved nothing")
    assert (d1, n1, s1) == (d2, n2, s2), (
        f"same-seed sim trace diverged with the ring spill forced ON: "
        f"run a = {d1} ({n1} events, {s1} spills), run b = {d2} "
        f"({n2} events, {s2} spills)")


def test_same_seed_sim_trace_bit_identical_with_disk_faults_on(tmp_path):
    """ISSUE 12 acceptance: a durable same-seed sim with the hostile-
    disk profile forced ON (per-op stalls + IO errors from boot) must
    STILL produce a bit-identical trace — fault draws come from
    per-machine seeded streams, so injection adds chaos, never
    nondeterminism — with DiskFaultInjected events present and all
    acked writes surviving (the child asserts its scan sees every row,
    so a passing run IS zero acked-write loss)."""
    d1, n1, _p1, _s1, f1, _c1, _m1 = _run_child(tmp_path, "fa",
                                                mode="faults")
    d2, n2, _p2, _s2, f2, _c2, _m2 = _run_child(tmp_path, "fb",
                                                mode="faults")
    assert n1 > 100, f"trace suspiciously small ({n1} events)"
    assert f1 > 0, (
        "no DiskFaultInjected events in the trace — the forced-on "
        "fault profile did not run, so this test proved nothing")
    assert (d1, n1, f1) == (d2, n2, f2), (
        f"same-seed sim trace diverged with disk faults forced ON: "
        f"run a = {d1} ({n1} events, {f1} faults), run b = {d2} "
        f"({n2} events, {f2} faults)")


def test_same_seed_sim_trace_bit_identical_mvcc_knob_both_ways(tmp_path):
    """ISSUE 13 acceptance: a durable same-seed sim with the columnar
    MVCC window forced ON (tiny seal budget — seals, tiered compaction
    and whole-segment drops all run) must be bit-identical across fresh
    processes, AND the same sim with the knob forced OFF (the legacy
    dict-of-chains twin) must be too — the knob selects the
    implementation outright, so each pair proves its own path."""
    d1, n1, *_ = _run_child(tmp_path, "ma", mode="mvcc_on")
    d2, n2, *_ = _run_child(tmp_path, "mb", mode="mvcc_on")
    assert n1 > 100, f"trace suspiciously small ({n1} events)"
    assert (d1, n1) == (d2, n2), (
        f"same-seed sim trace diverged with the columnar MVCC window "
        f"forced ON: run a = {d1} ({n1} events), run b = {d2} ({n2})")
    d3, n3, *_ = _run_child(tmp_path, "mc", mode="mvcc_off")
    d4, n4, *_ = _run_child(tmp_path, "md", mode="mvcc_off")
    assert n3 > 100, f"trace suspiciously small ({n3} events)"
    assert (d3, n3) == (d4, n4), (
        f"same-seed sim trace diverged with the legacy MVCC window "
        f"forced: run a = {d3} ({n3} events), run b = {d4} ({n4})")


def test_same_seed_sim_trace_bit_identical_lsm_knob_both_ways(tmp_path):
    """ISSUE 14 acceptance: a durable same-seed sim on the LSM engine
    with leveled background compaction forced ON (tiny memtable +
    trigger, so flushes, background merges, slice yields and manifest
    installs all run) must be bit-identical across fresh processes,
    AND the same sim with the knob forced OFF (the monolithic inline
    twin) must be too — the knob selects the compaction discipline
    outright, so each pair proves its own path."""
    d1, n1, _p1, _s1, _f1, c1, _m1 = _run_child(tmp_path, "la",
                                                mode="lsm_on")
    d2, n2, _p2, _s2, _f2, c2, _m2 = _run_child(tmp_path, "lb",
                                                mode="lsm_on")
    assert n1 > 100, f"trace suspiciously small ({n1} events)"
    assert c1 > 0, (
        "no LsmCompact events in the trace — the leveled background "
        "compactor never ran, so this test proved nothing")
    assert (d1, n1, c1) == (d2, n2, c2), (
        f"same-seed sim trace diverged with leveled lsm compaction "
        f"forced ON: run a = {d1} ({n1} events, {c1} compactions), "
        f"run b = {d2} ({n2} events, {c2}) — the background compactor "
        f"reordered observable events")
    d3, n3, *_ = _run_child(tmp_path, "lc", mode="lsm_off")
    d4, n4, *_ = _run_child(tmp_path, "ld", mode="lsm_off")
    assert n3 > 100, f"trace suspiciously small ({n3} events)"
    assert (d3, n3) == (d4, n4), (
        f"same-seed sim trace diverged with the monolithic lsm "
        f"compaction twin forced: run a = {d3} ({n3} events), "
        f"run b = {d4} ({n4})")


def _trace_bytes(tmp_path, tag: str) -> bytes:
    """Concatenated (rolled) trace JSONL a child with this tag wrote."""
    base = f"trace-{tag}.jsonl"
    d = str(tmp_path)
    out = b""
    for name in sorted(e for e in os.listdir(d)
                       if e == base or (e.startswith(base + ".")
                                        and e[len(base) + 1:].isdigit())):
        with open(os.path.join(d, name), "rb") as f:
            out += f.read()
    return out


def test_same_seed_sim_trace_bit_identical_mesh_knob_both_ways(tmp_path):
    """ISSUE 16 acceptance: a same-seed sim with a 2-resolver mesh and
    routed resolution forced ON (sparse sub-batches to the partition
    owning the keys, header-only version advances to the other) must be
    bit-identical across fresh processes, AND the same sim with the knob
    forced OFF (the verbatim clipped-broadcast twin) must be too — the
    knob selects the proxy's send shape outright, so each pair proves
    its own path.  The routed pair must also show the empty-clip fast
    path actually firing (a nonzero per-partition SkippedBatches gauge
    in the recorded ResolverMetrics stream) and the broadcast pair must
    show it never firing."""
    import re

    d1, n1, *_ = _run_child(tmp_path, "xa", mode="mesh_on")
    d2, n2, *_ = _run_child(tmp_path, "xb", mode="mesh_on")
    assert n1 > 100, f"trace suspiciously small ({n1} events)"
    skipped = [int(m) for m in re.findall(
        rb'"SkippedBatches":(\d+)', _trace_bytes(tmp_path, "xa"))]
    assert skipped and max(skipped) > 0, (
        "no nonzero SkippedBatches gauge in the routed child's metrics "
        "stream — the empty-clip fast path never fired, so the mesh_on "
        "half of this test proved nothing")
    assert (d1, n1) == (d2, n2), (
        f"same-seed sim trace diverged with mesh routing forced ON: "
        f"run a = {d1} ({n1} events), run b = {d2} ({n2})")
    d3, n3, *_ = _run_child(tmp_path, "xc", mode="mesh_off")
    d4, n4, *_ = _run_child(tmp_path, "xd", mode="mesh_off")
    assert n3 > 100, f"trace suspiciously small ({n3} events)"
    off_skipped = [int(m) for m in re.findall(
        rb'"SkippedBatches":(\d+)', _trace_bytes(tmp_path, "xc"))]
    assert not off_skipped or max(off_skipped) == 0, (
        f"SkippedBatches {max(off_skipped)} with routing forced OFF — "
        f"the broadcast twin is no longer verbatim")
    assert (d3, n3) == (d4, n4), (
        f"same-seed sim trace diverged with the broadcast twin forced: "
        f"run a = {d3} ({n3} events), run b = {d4} ({n4})")


def test_same_seed_sim_trace_bit_identical_devplane_knobs_flipped(tmp_path):
    """ISSUE 18 acceptance: the standing children pin the device-plane
    knobs at their defaults (verdict bitmask ON, ring in-place OFF,
    read-mirror shards 0); this pair flips ALL THREE the other way —
    raw-vector verdict replies (abort_words None on the wire, the
    proxy's per-txn scatter twin), the in-place ring append, a 4-shard
    mirror — and must still replay bit-identically across fresh
    processes.  Together the two sides prove every new knob pinned both
    ways."""
    d1, n1, p1, *_ = _run_child(tmp_path, "va", mode="devplane")
    d2, n2, p2, *_ = _run_child(tmp_path, "vb", mode="devplane")
    assert n1 > 100, f"trace suspiciously small ({n1} events)"
    assert p1 > 0, (
        "no ResolverDevice span events in the devplane child's trace — "
        "the device pipeline path did not run, so this test proved "
        "nothing")
    assert (d1, n1, p1) == (d2, n2, p2), (
        f"same-seed sim trace diverged with the device-plane knobs "
        f"flipped (bitmask OFF / ring in-place ON / 4-shard mirror): "
        f"run a = {d1} ({n1} events), run b = {d2} ({n2})")


def test_same_seed_sim_trace_bit_identical_layers_knobs_flipped(tmp_path):
    """ISSUE 19 acceptance: the standing children pin every layer knob
    at its default (and construct no layers, proving layers-off traces
    carry zero layer traffic); this pair flips ALL SEVEN the other way
    — a 0.01s feed poll, a 1k-version pop lag, async index mode, an
    8-entry LRU, a 4-watch limit, a 0.25s progress publish, 8-row
    checker pages — while driving the REAL stack (feed consumer, async
    secondary index, read-through cache with forced evictions, a fired
    watch, a clean checker pass, a destroy teardown) and must still
    replay bit-identically across fresh processes.  Together the two
    sides prove every new knob pinned both ways."""
    import re

    d1, n1, *_ = _run_child(tmp_path, "ya", mode="layers")
    d2, n2, *_ = _run_child(tmp_path, "yb", mode="layers")
    assert n1 > 100, f"trace suspiciously small ({n1} events)"
    on_trace = _trace_bytes(tmp_path, "ya")
    assert re.search(rb"layers/det", on_trace), (
        "no layer feed traffic in the layers child's trace — the stack "
        "never ran, so this test proved nothing")
    assert not re.search(rb'"Type":"LayerMismatch"', on_trace), (
        "LayerMismatch on an honest stack inside the determinism child")
    assert (d1, n1) == (d2, n2), (
        f"same-seed sim trace diverged with the layer knobs flipped "
        f"(hot poll / async index / tiny LRU / hot progress publish): "
        f"run a = {d1} ({n1} events), run b = {d2} ({n2}) — the layer "
        f"ecosystem added nondeterminism, not just derived state")


def test_same_seed_sim_trace_bit_identical_scrub_knob_both_ways(tmp_path):
    """ISSUE 17 acceptance: a same-seed sim with the consistency
    scrubber forced ON at a hot cadence (full replica-digest passes,
    GRV pins, watchdog invariant rounds, scrub_stats publishes) must be
    bit-identical across fresh processes, AND the same sim with the
    knob forced OFF must be too — the knob selects the audit plane
    outright, so each pair proves its own path.  The scrub-on pair
    must show at least one completed pass and ZERO mismatches (an
    honest cluster — the false-positive guard rides the determinism
    proof); the scrub-off pair must show no scrub events at all."""
    import re

    d1, n1, *_ = _run_child(tmp_path, "ca", mode="scrub_on")
    d2, n2, *_ = _run_child(tmp_path, "cb", mode="scrub_on")
    assert n1 > 100, f"trace suspiciously small ({n1} events)"
    on_trace = _trace_bytes(tmp_path, "ca")
    passes = len(re.findall(rb'"Type":"ScrubPassComplete"', on_trace))
    assert passes > 0, (
        "no ScrubPassComplete in the scrub-on child's trace — the "
        "scrubber never finished a pass, so this test proved nothing")
    assert not re.search(rb'"Type":"ScrubMismatch"', on_trace), (
        "ScrubMismatch on an honest cluster — a false positive inside "
        "the determinism child")
    assert not re.search(rb'"Type":"ScrubInvariantViolation"', on_trace), (
        "watchdog violation on a healthy cluster inside the "
        "determinism child")
    assert (d1, n1) == (d2, n2), (
        f"same-seed sim trace diverged with the scrubber forced ON: "
        f"run a = {d1} ({n1} events), run b = {d2} ({n2}) — the audit "
        f"plane added nondeterminism, not just chaos")
    d3, n3, *_ = _run_child(tmp_path, "cc", mode="scrub_off")
    d4, n4, *_ = _run_child(tmp_path, "cd", mode="scrub_off")
    assert n3 > 100, f"trace suspiciously small ({n3} events)"
    assert not re.search(rb'"Type":"Scrub', _trace_bytes(tmp_path, "cc")), (
        "scrub events with the knob forced OFF — SCRUB_ENABLED no "
        "longer gates the plane")
    assert (d3, n3) == (d4, n4), (
        f"same-seed sim trace diverged with the scrubber forced OFF: "
        f"run a = {d3} ({n3} events), run b = {d4} ({n4})")


if __name__ == "__main__":
    if len(sys.argv) in (3, 4) and sys.argv[1] == "--child":
        _child(sys.argv[2], sys.argv[3] if len(sys.argv) == 4 else "default")
    else:
        raise SystemExit(
            "usage: test_sim_determinism.py --child <path> [mode]")
