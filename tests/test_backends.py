"""Backend registry: knob selection, chunking, coalescing conservatism."""

import pytest

from foundationdb_tpu.ops.backends import coalesce_ranges, make_conflict_backend
from foundationdb_tpu.ops.batch import COMMITTED, CONFLICT, TxnRequest
from foundationdb_tpu.ops.oracle import OracleConflictSet
from foundationdb_tpu.runtime import DeterministicRandom, Knobs


def K(**kw):
    return Knobs().override(CONFLICT_RING_CAPACITY=4096, KEY_ENCODE_BYTES=16,
                            RESOLVER_BATCH_TXNS=8, RESOLVER_RANGES_PER_TXN=4, **kw)


def rand_txn(rng, version, nr):
    def rr():
        a = bytes(rng.random_int(0, 3) for _ in range(rng.random_int(1, 12)))
        return (a, a + b"\x01")
    return TxnRequest([rr() for _ in range(rng.random_int(0, nr))],
                      [rr() for _ in range(rng.random_int(0, nr))],
                      rng.random_int(max(0, version - 40), version + 1))


@pytest.mark.parametrize("kind", ["cpp", "numpy", "tpu"])
def test_all_backends_match_oracle_in_bucket(kind):
    rng = DeterministicRandom(11)
    be = make_conflict_backend(K(RESOLVER_CONFLICT_BACKEND=kind))
    oracle = OracleConflictSet()
    version = 100
    for _ in range(20):
        txns = [rand_txn(rng, version, nr=4) for _ in range(rng.random_int(1, 9))]
        version += rng.random_int(1, 15)
        assert be.resolve(txns, version) == oracle.resolve(txns, version)
        if rng.coinflip(0.2):
            v = version - rng.random_int(5, 50)
            be.set_oldest_version(v)
            oracle.set_oldest_version(v)


def test_chunking_preserves_semantics():
    """Batch of 20 txns through B=8 backend == oracle one-shot."""
    rng = DeterministicRandom(22)
    be = make_conflict_backend(K(RESOLVER_CONFLICT_BACKEND="numpy"))
    oracle = OracleConflictSet()
    txns = [rand_txn(rng, 100, nr=4) for _ in range(20)]
    assert be.resolve(txns, 120) == oracle.resolve(txns, 120)


def test_coalesce_ranges():
    rs = [(bytes([i]), bytes([i]) + b"\x00") for i in range(10)]
    out = coalesce_ranges(rs, 4)
    assert len(out) <= 4
    # covering: every original range inside some merged range
    for (b, e) in rs:
        assert any(mb <= b and e <= me for (mb, me) in out)
    assert coalesce_ranges(rs, 10) == rs  # no-op when it fits


def test_oversize_txn_is_conservative_not_error():
    """Txn with 12 ranges through R=4 backend: runs, and any verdict flip
    vs oracle is COMMITTED->CONFLICT only."""
    rng = DeterministicRandom(33)
    be = make_conflict_backend(K(RESOLVER_CONFLICT_BACKEND="numpy"))
    oracle = OracleConflictSet()
    version = 100
    for _ in range(15):
        txns = [rand_txn(rng, version, nr=12) for _ in range(4)]
        version += 10
        bv = be.resolve(txns, version)
        ov = oracle.resolve(txns, version)
        for x, o in zip(bv, ov):
            if x != o:
                assert (x, o) == (CONFLICT, COMMITTED)
        # keep oracle's history aligned with what the backend committed:
        # feed the backend's verdicts forward by re-adding... (divergence is
        # expected after a flip; stop comparing once they differ)
        if bv != ov:
            break


def _run_groups(be, rng, n_groups=12, group=6, start_version=1000):
    """Drive resolve_group_begin over random txn batches; returns flat
    verdicts."""
    import asyncio

    from foundationdb_tpu.ops.backends import resolve_group_begin
    version = start_version
    out = []

    async def drive():
        nonlocal version
        for _ in range(n_groups):
            batches, versions = [], []
            for _ in range(group):
                batches.append([rand_txn(rng, version, nr=4)
                                for _ in range(rng.random_int(1, 9))])
                version += rng.random_int(1, 15)
                versions.append(version)
            for vs in await resolve_group_begin(be, batches, versions):
                out.extend(vs)
    asyncio.run(drive())
    return out


def test_dict_compressed_group_path_matches_lanes_path():
    """The endpoint-id dictionary path (device-resident lane dictionary +
    u32 ids) must produce bit-identical verdicts to the uncompressed lanes
    path, including across dictionary slot eviction/reuse."""
    # small dictionary (min viable = 8*R*B*64) forces slot reuse quickly
    min_slots = 8 * 4 * 8 * 64
    lanes = make_conflict_backend(
        K(RESOLVER_CONFLICT_BACKEND="tpu", CONFLICT_DICT_SLOTS=0))
    dct = make_conflict_backend(
        K(RESOLVER_CONFLICT_BACKEND="tpu", CONFLICT_DICT_SLOTS=min_slots))
    assert dct._dict is not None, "dictionary path not active"
    r1 = _run_groups(lanes, DeterministicRandom(77))
    r2 = _run_groups(dct, DeterministicRandom(77))
    assert r1 == r2
    # and the numpy twin agrees
    np_be = make_conflict_backend(K(RESOLVER_CONFLICT_BACKEND="numpy"))
    r3 = _run_groups(np_be, DeterministicRandom(77))
    assert r1 == r3


def test_dict_path_ring_state_matches_lanes_path():
    import numpy as np
    min_slots = 8 * 4 * 8 * 64
    lanes = make_conflict_backend(
        K(RESOLVER_CONFLICT_BACKEND="tpu", CONFLICT_DICT_SLOTS=0))
    dct = make_conflict_backend(
        K(RESOLVER_CONFLICT_BACKEND="tpu", CONFLICT_DICT_SLOTS=min_slots))
    _run_groups(lanes, DeterministicRandom(5), n_groups=6)
    _run_groups(dct, DeterministicRandom(5), n_groups=6)
    for f in ("hb", "he", "hver", "floor"):
        a = np.asarray(getattr(lanes.cs.state, f))
        b = np.asarray(getattr(dct.cs.state, f))
        assert (a == b).all(), f"ring field {f} diverged"


def test_wire_path_matches_object_path_both_backends():
    """The serialized WireBatch form must resolve bit-identically to the
    TxnRequest object form on both the cpp baseline and the jax/dict
    path (the wire layout is the canonical proxy payload)."""
    import asyncio

    from foundationdb_tpu.ops.backends import resolve_group_wire_begin
    from foundationdb_tpu.ops.batch import wire_from_txns

    def gen(seed, n_groups=6, group=5):
        rng = DeterministicRandom(seed)
        version = 500
        out = []
        for _ in range(n_groups):
            batches, versions = [], []
            for _ in range(group):
                batches.append([rand_txn(rng, version, nr=4)
                                for _ in range(rng.random_int(1, 8))])
                version += rng.random_int(1, 15)
                versions.append(version)
            out.append((batches, versions))
        return out

    def run_wire(be):
        flat = []

        async def drive():
            for batches, versions in gen(31):
                wires = [wire_from_txns(b) for b in batches]
                for vs in await resolve_group_wire_begin(be, wires, versions):
                    flat.extend(vs)
        asyncio.run(drive())
        return flat

    def run_obj(be):
        flat = []
        for batches, versions in gen(31):
            for b, v in zip(batches, versions):
                flat.extend(be.resolve(b, v))
        return flat

    min_slots = 8 * 4 * 8 * 64
    cpp_obj = run_obj(make_conflict_backend(K(RESOLVER_CONFLICT_BACKEND="cpp")))
    cpp_wire = run_wire(make_conflict_backend(K(RESOLVER_CONFLICT_BACKEND="cpp")))
    tpu_wire = run_wire(make_conflict_backend(
        K(RESOLVER_CONFLICT_BACKEND="tpu", CONFLICT_DICT_SLOTS=min_slots)))
    assert cpp_obj == cpp_wire, "cpp wire layout diverged from object path"
    assert cpp_obj == tpu_wire, "tpu wire/dict path diverged from cpp"


def test_point_compressed_wire_groups_match_cpp():
    """All-point groups take the compact path (begin ids only; end rows
    derived on device).  Must stay bit-identical to cpp across the
    encode-width boundary (keys shorter, equal and longer than width)."""
    import asyncio

    from foundationdb_tpu.ops.backends import resolve_group_wire_begin
    from foundationdb_tpu.ops.batch import wire_from_txns

    def point_txn(rng, version):
        def pr():
            a = bytes(rng.random_int(0, 4)
                      for _ in range(rng.random_int(1, 24)))
            return (a, a + b"\x00")
        return TxnRequest([pr() for _ in range(rng.random_int(0, 4))],
                          [pr() for _ in range(rng.random_int(0, 4))],
                          rng.random_int(max(0, version - 40), version))

    def drive(be, seed):
        rng = DeterministicRandom(seed)
        version = 900
        flat = []

        async def go():
            nonlocal version
            for _ in range(8):
                bs, vs = [], []
                for _ in range(5):
                    bs.append([point_txn(rng, version)
                               for _ in range(rng.random_int(1, 8))])
                    version += rng.random_int(1, 12)
                    vs.append(version)
                wires = [wire_from_txns(b) for b in bs]
                for v in await resolve_group_wire_begin(be, wires, vs):
                    flat.extend(v)
        asyncio.run(go())
        return flat

    min_slots = 8 * 4 * 8 * 64
    cpp = drive(make_conflict_backend(K(RESOLVER_CONFLICT_BACKEND="cpp")), 3)
    tpu_be = make_conflict_backend(
        K(RESOLVER_CONFLICT_BACKEND="tpu", CONFLICT_DICT_SLOTS=min_slots))
    tpu = drive(tpu_be, 3)
    assert cpp == tpu and len(cpp) > 50
    # the compact path must actually have been exercised
    enc = tpu_be._dict.encode_group_wire(
        [wire_from_txns([TxnRequest([(b"k", b"k\x00")], [], 900)])],
        tpu_be.B, tpu_be.R, 1)
    assert enc[-1] is True, "compact detection failed on a point range"
