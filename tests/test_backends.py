"""Backend registry: knob selection, chunking, coalescing conservatism."""

import pytest

from foundationdb_tpu.ops.backends import coalesce_ranges, make_conflict_backend
from foundationdb_tpu.ops.batch import COMMITTED, CONFLICT, TxnRequest
from foundationdb_tpu.ops.oracle import OracleConflictSet
from foundationdb_tpu.runtime import DeterministicRandom, Knobs


def K(**kw):
    return Knobs().override(CONFLICT_RING_CAPACITY=4096, KEY_ENCODE_BYTES=16,
                            RESOLVER_BATCH_TXNS=8, RESOLVER_RANGES_PER_TXN=4, **kw)


def rand_txn(rng, version, nr):
    def rr():
        a = bytes(rng.random_int(0, 3) for _ in range(rng.random_int(1, 12)))
        return (a, a + b"\x01")
    return TxnRequest([rr() for _ in range(rng.random_int(0, nr))],
                      [rr() for _ in range(rng.random_int(0, nr))],
                      rng.random_int(max(0, version - 40), version + 1))


@pytest.mark.parametrize("kind", ["cpp", "numpy", "tpu"])
def test_all_backends_match_oracle_in_bucket(kind):
    rng = DeterministicRandom(11)
    be = make_conflict_backend(K(RESOLVER_CONFLICT_BACKEND=kind))
    oracle = OracleConflictSet()
    version = 100
    for _ in range(20):
        txns = [rand_txn(rng, version, nr=4) for _ in range(rng.random_int(1, 9))]
        version += rng.random_int(1, 15)
        assert be.resolve(txns, version) == oracle.resolve(txns, version)
        if rng.coinflip(0.2):
            v = version - rng.random_int(5, 50)
            be.set_oldest_version(v)
            oracle.set_oldest_version(v)


def test_chunking_preserves_semantics():
    """Batch of 20 txns through B=8 backend == oracle one-shot."""
    rng = DeterministicRandom(22)
    be = make_conflict_backend(K(RESOLVER_CONFLICT_BACKEND="numpy"))
    oracle = OracleConflictSet()
    txns = [rand_txn(rng, 100, nr=4) for _ in range(20)]
    assert be.resolve(txns, 120) == oracle.resolve(txns, 120)


def test_coalesce_ranges():
    rs = [(bytes([i]), bytes([i]) + b"\x00") for i in range(10)]
    out = coalesce_ranges(rs, 4)
    assert len(out) <= 4
    # covering: every original range inside some merged range
    for (b, e) in rs:
        assert any(mb <= b and e <= me for (mb, me) in out)
    assert coalesce_ranges(rs, 10) == rs  # no-op when it fits


def test_oversize_txn_is_conservative_not_error():
    """Txn with 12 ranges through R=4 backend: runs, and any verdict flip
    vs oracle is COMMITTED->CONFLICT only."""
    rng = DeterministicRandom(33)
    be = make_conflict_backend(K(RESOLVER_CONFLICT_BACKEND="numpy"))
    oracle = OracleConflictSet()
    version = 100
    for _ in range(15):
        txns = [rand_txn(rng, version, nr=12) for _ in range(4)]
        version += 10
        bv = be.resolve(txns, version)
        ov = oracle.resolve(txns, version)
        for x, o in zip(bv, ov):
            if x != o:
                assert (x, o) == (CONFLICT, COMMITTED)
        # keep oracle's history aligned with what the backend committed:
        # feed the backend's verdicts forward by re-adding... (divergence is
        # expected after a flip; stop comparing once they differ)
        if bv != ov:
            break
