"""TaskBucket/FutureBucket — durable task scheduling semantics.

Reference test model: REF:fdbclient/TaskBucket.actor.cpp — concurrent
agents never double-claim, a crashed agent's lease expires back to
available (at-least-once), and future-parked tasks run only after the
future fires, surviving through the keyspace rather than agent memory.
"""

from __future__ import annotations

import asyncio

from foundationdb_tpu.backup.task_bucket import (FutureBucket, TaskBucket,
                                                 task_agent)
from foundationdb_tpu.client.database import Database
from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


def _cluster_main(body):
    async def main():
        async with Cluster(ClusterConfig(commit_proxies=2, resolvers=2,
                                         storage_servers=2),
                           Knobs()) as cluster:
            await body(Database(cluster))
    run_simulation(main())


def test_concurrent_agents_execute_each_task_once():
    async def body(db):
        bucket = TaskBucket(db, b"tb1/", lease_seconds=30.0)
        done: list[int] = []

        async def handler(params):
            await asyncio.sleep(0.01)
            done.append(params["i"])
        for i in range(12):
            await bucket.add_task({"type": "t", "i": i})
        agents = [asyncio.get_running_loop().create_task(
            task_agent(bucket, {"t": handler})) for _ in range(3)]
        while not await bucket.is_empty():
            await asyncio.sleep(0.05)
        for a in agents:
            a.cancel()
        await asyncio.gather(*agents, return_exceptions=True)
        assert sorted(done) == list(range(12)), sorted(done)
    _cluster_main(body)


def test_expired_lease_requeues_crashed_agents_task():
    async def body(db):
        bucket = TaskBucket(db, b"tb2/", lease_seconds=0.05)
        await bucket.add_task({"type": "t", "i": 1})
        got = await bucket.get_one()
        assert got is not None
        tid, params = got
        # the "agent" dies here: no extend, no finish.  Let the version
        # clock pass the lease (commits advance the committed version).
        for _ in range(3):
            await asyncio.sleep(0.1)

            async def tick(tr):
                tr.set(b"tick", b"1")
            await db.run(tick)
        n = await bucket.requeue_expired()
        assert n >= 1
        got2 = await bucket.get_one()
        assert got2 is not None and got2[1] == params
        await bucket.finish(got2[0])
        assert await bucket.is_empty()
    _cluster_main(body)


def test_future_parks_task_until_set():
    async def body(db):
        bucket = TaskBucket(db, b"tb3/", lease_seconds=30.0)
        done: list[str] = []

        async def handler(params):
            done.append(params["name"])

        async def setup(tr):
            bucket.futures.create(tr, b"f1")
            await bucket.add(tr, {"type": "t", "name": "dependent"},
                             after=b"f1")
            await bucket.add(tr, {"type": "t", "name": "free"})
        await db.run(setup)

        agent = asyncio.get_running_loop().create_task(
            task_agent(bucket, {"t": handler}))
        while "free" not in done:
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.3)
        assert done == ["free"], done          # dependent still parked

        await bucket.futures.set(b"f1")
        while "dependent" not in done:
            await asyncio.sleep(0.05)
        while not await bucket.is_empty():
            await asyncio.sleep(0.05)
        agent.cancel()
        await asyncio.gather(agent, return_exceptions=True)
        assert sorted(done) == ["dependent", "free"]
    _cluster_main(body)


def test_add_after_already_fired_future_runs_immediately():
    """A task added AFTER its future fired must not strand in park/
    forever: add() reads the future in the same transaction and routes
    straight to available."""
    async def body(db):
        bucket = TaskBucket(db, b"tb5/", lease_seconds=30.0)

        async def setup(tr):
            bucket.futures.create(tr, b"done-fut")
        await db.run(setup)
        await bucket.futures.set(b"done-fut")

        await bucket.add_task({"type": "t", "n": 1}, after=b"done-fut")
        got = await bucket.get_one()
        assert got is not None and got[1] == {"type": "t", "n": 1}
        await bucket.finish(got[0])
        assert await bucket.is_empty()
    _cluster_main(body)


def test_two_adds_in_one_transaction_both_survive():
    """Mutations in one transaction share a versionstamp; the per-bucket
    nonce keeps two add()s from colliding on the same key."""
    async def body(db):
        bucket = TaskBucket(db, b"tb6/", lease_seconds=30.0)

        async def both(tr):
            await bucket.add(tr, {"type": "t", "n": "a"})
            await bucket.add(tr, {"type": "t", "n": "b"})
        await db.run(both)
        got = set()
        for _ in range(2):
            t = await bucket.get_one()
            assert t is not None
            got.add(t[1]["n"])
            await bucket.finish(t[0])
        assert got == {"a", "b"}, got
        assert await bucket.is_empty()
    _cluster_main(body)


def test_sweep_releases_parks_under_fired_future():
    """A crash between set()'s flag commit and its drain leaves tasks
    parked under a set future; sweep_fired (run by every agent) frees
    them."""
    async def body(db):
        bucket = TaskBucket(db, b"tb7/", lease_seconds=30.0)

        async def setup(tr):
            bucket.futures.create(tr, b"crashy")
            await bucket.add(tr, {"type": "t", "n": 3}, after=b"crashy")
        await db.run(setup)

        # simulate the crash: flag set WITHOUT the drain
        async def flag(tr):
            tr.set(b"tb7/fut/crashy", b"1")
        await db.run(flag)
        assert await bucket.get_one() is None    # still stranded

        moved = await bucket.sweep_fired()
        assert moved == 1
        got = await bucket.get_one()
        assert got is not None and got[1]["n"] == 3
        await bucket.finish(got[0])
    _cluster_main(body)


def test_lease_extension_keeps_task_claimed():
    async def body(db):
        bucket = TaskBucket(db, b"tb4/", lease_seconds=0.2)
        await bucket.add_task({"type": "t", "i": 9})
        got = await bucket.get_one()
        assert got is not None
        for _ in range(4):
            await asyncio.sleep(0.1)
            assert await bucket.extend(got[0])

            async def tick(tr):
                tr.set(b"tick4", b"1")
            await db.run(tick)
            await bucket.requeue_expired()
        # never expired: still claimed, nothing available
        assert await bucket.get_one() is None
        await bucket.finish(got[0])
        assert await bucket.is_empty()
    _cluster_main(body)
