"""DataDistribution v1: shard stats → split → fetchKeys move, under load.

Reference test model: REF:fdbserver/workloads/ (move/split under live
writes must lose no rows and invent none).
"""

from __future__ import annotations

import asyncio

from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
from foundationdb_tpu.core.data_distribution import (layout_of, move_layout,
                                                     split_layout)
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster


def test_split_and_move_layout_helpers():
    layout = {"boundaries": [b"\x80"], "teams": [[0], [1]]}
    l2, nxt = split_layout(layout, 0, b"\x40", 2)
    assert l2 == {"boundaries": [b"\x40", b"\x80"], "teams": [[0], [2], [1]]}
    assert nxt == 3
    l3, nxt = move_layout(l2, 1, nxt)
    assert l3["teams"] == [[0], [3], [1]]
    assert nxt == 4


def test_hot_shard_splits_under_live_writes():
    """Fill one shard past the split threshold while writes keep flowing;
    the distributor must split it (new layout + recovery + fetchKeys) with
    zero lost and zero phantom rows."""
    async def main():
        k = Knobs().override(DD_ENABLED=True, DD_INTERVAL=1.0,
                             DD_SHARD_SPLIT_BYTES=6_000)
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        n_shards_before = len(state1["shard_teams"])
        db = await sim.database()

        written: dict[bytes, bytes] = {}
        stop = asyncio.Event()

        async def writer(wid: int) -> None:
            i = 0
            while not stop.is_set():
                items = {b"hot%02d%05d" % (wid, i + j): b"v" * 40
                         for j in range(5)}
                i += 5

                async def do(tr, items=items):
                    for key, v in items.items():
                        tr.set(key, v)
                await db.run(do)
                written.update(items)
                await asyncio.sleep(0.05)

        writers = [asyncio.ensure_future(writer(w)) for w in range(2)]
        # wait for the split-driven recovery (epoch 2+) with writes live
        state2 = await sim.wait_epoch(2)
        # let a few more writes land after the flip
        await asyncio.sleep(2.0)
        stop.set()
        await asyncio.gather(*writers)

        assert len(state2["shard_teams"]) > n_shards_before
        # every acknowledged row is present with the right value (no loss),
        # and a full scan returns exactly the written hot keys (no phantoms)
        tr = db.create_transaction()
        while True:
            try:
                rows = await tr.get_range(b"hot", b"hou", limit=0)
                break
            except Exception as e:   # noqa: BLE001 — retry through recovery
                await tr.on_error(e)
        got = dict(rows)
        missing = [key for key in written if key not in got]
        assert not missing, f"{len(missing)} rows lost, e.g. {missing[:3]}"
        wrong = [key for key, v in written.items() if got.get(key) != v]
        assert not wrong, f"{len(wrong)} rows corrupted"
        phantom = [key for key in got if key not in written]
        assert not phantom, f"{len(phantom)} phantom rows, e.g. {phantom[:3]}"
        await sim.stop()
    run_simulation(main())
