"""DataDistribution layout helpers.

Reference test model: REF:fdbserver/workloads/ (move/split under live
writes must lose no rows and invent none).
"""

from __future__ import annotations

from foundationdb_tpu.core.data_distribution import move_layout, split_layout


def test_split_and_move_layout_helpers():
    layout = {"boundaries": [b"\x80"], "teams": [[0], [1]]}
    l2, nxt = split_layout(layout, 0, b"\x40", 2)
    assert l2 == {"boundaries": [b"\x40", b"\x80"], "teams": [[0], [2], [1]]}
    assert nxt == 3
    l3, nxt = move_layout(l2, 1, nxt)
    assert l3["teams"] == [[0], [3], [1]]
    assert nxt == 4


# The split-under-live-writes scenario moved to
# tests/test_live_move.py::test_live_split_without_recovery when
# DataDistribution v2 made relocations live (no recovery involved).
