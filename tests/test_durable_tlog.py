"""Durable TLog generations: acked commits survive whole-cluster death.

Reference test model: REF:fdbserver/TLogServer.actor.cpp persistent-state
recovery + REF:tests/restarting/ — every acknowledged commit is fsync'd
in the TLogs' disk queues before the client sees it, so killing EVERY
machine at once and rebooting must lose nothing: the coordinators reopen
their durable register, the workers reopen storage engines AND TLog disk
queues (locked, as old-generation copies), and recovery adopts the
reopened log copies to compute the recovery version and replay.
"""

from __future__ import annotations

import asyncio

from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster


def test_full_cluster_reboot_recovers_acked_commits():
    async def main():
        k = Knobs().override(STORAGE_DURABILITY_LAG=0.1,
                             STORAGE_VERSION_WINDOW=1000)
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6,
                                                      replication=2),
                               durable_storage=True)
        await sim.start()
        state1 = await sim.wait_epoch(1)
        db = await sim.database()

        phase1 = {b"boot%03d" % i: b"p1-%03d" % i for i in range(30)}

        async def fill1(tr):
            for key, v in phase1.items():
                tr.set(key, v)
        await db.run(fill1)
        # one durability tick: engines record shard meta (+ early rows)
        await asyncio.sleep(1.0)

        # phase 2 rows are acked JUST before the crash — with the
        # durability loop mid-cycle, some exist only in the TLogs' disk
        # queues at kill time
        phase2 = {b"crash%03d" % i: b"p2-%03d" % i for i in range(20)}

        async def fill2(tr):
            for key, v in phase2.items():
                tr.set(key, v)
        await db.run(fill2)

        # whole-cluster power loss: every machine at once, unsynced
        # writes gone
        for m in sim.machines:
            await m.kill()
        await asyncio.sleep(0.5)
        for m in sim.machines:
            await m.reboot()

        state2 = await sim.wait_epoch(state1["epoch"] + 1)
        assert state2["recovery_version"] > 0

        db2 = await sim.database()
        expected = dict(phase1)
        expected.update(phase2)
        tr = db2.create_transaction()
        while True:
            try:
                rows = await tr.get_range(b"", b"\xff", limit=0)
                break
            except Exception as e:   # noqa: BLE001 — retry through recovery
                await tr.on_error(e)
        got = dict(rows)
        missing = [key for key in expected if key not in got]
        assert not missing, (
            f"{len(missing)} acked rows lost after full-cluster reboot, "
            f"e.g. {missing[:5]}")
        wrong = [key for key, v in expected.items() if got.get(key) != v]
        assert not wrong, f"{len(wrong)} rows corrupted, e.g. {wrong[:3]}"
        phantom = [key for key in got if key not in expected]
        assert not phantom, f"{len(phantom)} phantom rows: {phantom[:5]}"

        # and the revived cluster accepts new commits
        async def again(tr):
            tr.set(b"post-reboot", b"alive")
        await db2.run(again)
        assert await db2.get(b"post-reboot") == b"alive"
        await sim.stop()
    run_simulation(main())


def test_reboot_tlog_adoption_preserves_undurable_suffix():
    """Slow storage durability (long lag): rows acked right before the
    crash exist ONLY in the TLog disk queues.  After reboot they must
    come back through the adopted log copies — this fails if recovery
    relied on storage engines alone."""
    async def main():
        # huge version window/lag: storage makes (almost) nothing durable
        # after the initial meta tick
        k = Knobs().override(STORAGE_DURABILITY_LAG=0.2,
                             STORAGE_VERSION_WINDOW=30_000_000)
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6,
                                                      replication=2),
                               durable_storage=True)
        await sim.start()
        state1 = await sim.wait_epoch(1)
        db = await sim.database()

        async def meta_tick(tr):
            tr.set(b"seed", b"x")
        await db.run(meta_tick)
        await asyncio.sleep(1.0)     # engines persist shard meta

        rows = {b"logonly%03d" % i: b"L%03d" % i for i in range(25)}

        async def fill(tr):
            for key, v in rows.items():
                tr.set(key, v)
        await db.run(fill)

        for m in sim.machines:
            await m.kill()
        await asyncio.sleep(0.5)
        for m in sim.machines:
            await m.reboot()
        await sim.wait_epoch(state1["epoch"] + 1)

        db2 = await sim.database()
        for key, v in list(rows.items())[:5] + list(rows.items())[-5:]:
            got = await db2.get(key)
            assert got == v, f"{key!r}: {got!r} != {v!r} (TLog replay lost it)"
        await sim.stop()
    run_simulation(main())
