"""Change feeds (ISSUE 4): versioned streaming change capture.

Coverage: store capture/pop/read semantics over packed batches, the
retention spill/recovery round trip through DiskQueue, the numpy
``select`` equivalence, the 713 protocol fence, commit-proxy marker
routing (including the register/pop/destroy vs range-split race), the
apply-path capture of resolved atomics, rollback of unacked feed
entries at storage rejoin, and the client cursor lifecycle end-to-end
(create → stream → pop → resume → destroy).

The seeded-sim completeness proofs (buggify + attrition failover,
bit-identical across two same-seed runs; duplicate-free resume after a
mid-stream storage kill; feed handoff across a live range split) live
at the bottom — they are the subsystem's acceptance tests.
"""

from __future__ import annotations

import asyncio

import pytest

from foundationdb_tpu.core.change_feed import (ChangeFeedStore,
                                               ChangeFeedStreamRequest)
from foundationdb_tpu.core.data import (KeyRange, Mutation, MutationBatch,
                                        MutationType)
from foundationdb_tpu.core.storage_server import StorageServer
from foundationdb_tpu.core.tlog import TLog
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


def batch(*muts: Mutation) -> MutationBatch:
    return MutationBatch.from_mutations(muts)


# --- store semantics ---

def test_store_capture_clips_to_range_and_reads_in_order():
    async def main():
        st = ChangeFeedStore()
        st.register(b"f", b"b", b"d", 10)
        st.capture(11, batch(Mutation.set(b"a", b"0"),   # below range
                             Mutation.set(b"b1", b"1"),
                             Mutation.set(b"d", b"2")))  # at end: out
        st.capture(12, batch(Mutation.clear_range(b"a", b"c"),  # overlaps
                             Mutation.set(b"zz", b"3")))
        st.capture(13, batch(Mutation.set(b"x", b"4")))  # fully outside
        entries, trunc = await st.read(b"f", 1, 0, 100)
        assert trunc is None
        assert [(v, [(m.param1, m.param2) for m in b])
                for v, b in entries] == [
            (11, [(b"b1", b"1")]),
            # the overlapping clear is CLIPPED to the feed range: the
            # consumer must never see keys outside what it subscribed to
            (12, [(b"b", b"c")]),
        ]
        # capture at or below the registration version is ignored
        st.capture(10, batch(Mutation.set(b"b9", b"old")))
        entries, _ = await st.read(b"f", 1, 0, 100)
        assert len(entries) == 2
        # pop releases the prefix; reads resume above it
        st.pop(b"f", 11)
        entries, _ = await st.read(b"f", 12, 0, 100)
        assert [v for v, _b in entries] == [12]
        assert st.feeds[b"f"].popped_version == 11
    asyncio.run(main())


def test_store_zero_copy_identity_slice():
    """A batch fully inside the feed range is retained as the SAME
    object the apply path consumed — the PR's zero-copy motivation."""
    async def main():
        st = ChangeFeedStore()
        st.register(b"f", b"", b"\xff", 0)
        b = batch(Mutation.set(b"k1", b"v1"), Mutation.set(b"k2", b"v2"))
        st.capture(5, b)
        entries, _ = await st.read(b"f", 1, 0, 10)
        assert entries[0][1] is b
    asyncio.run(main())


def test_store_spill_and_recovery_roundtrip():
    """Retention outgrows memory → sealed segments spill to the side
    DiskQueue; a reopened queue + engine meta restores the exact same
    stream (the reboot path), and pops release the dead prefix."""
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.storage.disk_queue import DiskQueue

    async def main():
        fs = SimFileSystem()
        q, _ = await DiskQueue.open(fs.open("feeds.dq"))
        st = ChangeFeedStore(q)
        st.register(b"f", b"", b"\xff", 0)
        payload = b"x" * 200
        for v in range(1, 11):
            st.capture(v, batch(Mutation.set(b"k%03d" % v, payload)))
        # durable floor 6: only versions <= 6 may spill
        spilled = await st.maybe_spill(6, 800)
        assert spilled > 0
        f = st.feeds[b"f"]
        assert f.spilled and f.spilled[-1][0] <= 6
        # the stream reads back complete and ordered across the seam
        entries, _ = await st.read(b"f", 1, 0, 100)
        assert [v for v, _b in entries] == list(range(1, 11))
        assert all(b[0].param1 == b"k%03d" % v for v, b in entries)

        # reboot: reopen the queue, restore from engine-meta + frames
        meta = st.export_meta()
        q2, frames = await DiskQueue.open(fs.open("feeds.dq"))
        st2 = ChangeFeedStore(q2)
        st2.restore(meta, frames, q2.front_offset)
        entries2, _ = await st2.read(b"f", 1, 0, 100)
        spilled_versions = [v for v, *_ in st2.feeds[b"f"].spilled]
        assert spilled_versions == [v for v, *_ in f.spilled]
        assert [(v, b[0].param1) for v, b in entries2] == \
            [(v, b"k%03d" % v) for v, _st, _en, _nb in f.spilled]

        # pop past the spilled prefix releases queue space
        used_before = q.bytes_used
        st.pop(b"f", 6)
        await st.maybe_spill(6, 1 << 30)      # runs the release pass
        assert q.bytes_used < used_before
        entries3, _ = await st.read(b"f", 7, 0, 100)
        assert [v for v, _b in entries3] == [7, 8, 9, 10]
    asyncio.run(main())


# --- numpy select (ROADMAP PR 3 follow-up (b)) ---

def test_select_numpy_matches_naive():
    import random
    rng = random.Random(42)
    muts = [Mutation.set(b"k%04d" % i, bytes(rng.randrange(256)
                                             for _ in range(rng.randrange(9))))
            if rng.random() < 0.7
            else Mutation.clear_range(b"a%04d" % i, b"b%04d" % i)
            for i in range(200)]
    mb = MutationBatch.from_mutations(muts)
    for _ in range(20):
        k = rng.randrange(0, 200)
        idxs = sorted(rng.sample(range(200), k))
        sub = mb.select(idxs)            # numpy path for len >= 16
        assert [sub[j] for j in range(len(idxs))] == [muts[i] for i in idxs]
    # duplicate-bearing same-length list is NOT the identity
    idxs = [0, 0] + list(range(2, 200))
    sub = mb.select(idxs)
    assert sub is not mb and sub[1] == muts[0]
    # true identity is zero-copy
    assert mb.select(list(range(200))) is mb


# --- the protocol fence (712 peer must be refused) ---

def test_version_gate_fences_712_peer():
    from foundationdb_tpu.core.cluster_client import RecoveredClusterView
    from foundationdb_tpu.runtime.errors import ClusterVersionChanged
    new = Knobs()
    assert new.PROTOCOL_VERSION >= 713   # feeds landed at 713
    old = new.override(PROTOCOL_VERSION=712)
    state = {"epoch": 1, "seq": 0, "protocol": new.PROTOCOL_VERSION}
    with pytest.raises(ClusterVersionChanged):
        RecoveredClusterView(old, None, state)


def test_feed_wire_structs_roundtrip():
    from foundationdb_tpu.core.change_feed import ChangeFeedStreamReply
    from foundationdb_tpu.rpc.wire import decode, encode
    req = ChangeFeedStreamRequest(b"f", 42, 1024)
    assert decode(encode(req)) == req
    rep = ChangeFeedStreamReply(
        [(7, batch(Mutation.set(b"k", b"v")))], 9, 3)
    got = decode(encode(rep))
    assert got.end_version == 9 and got.popped_version == 3
    assert got.entries[0][0] == 7 and got.entries[0][1][0].param1 == b"k"


# --- commit-proxy marker routing ---

def _proxy():
    from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
    cluster = Cluster(ClusterConfig(storage_servers=4))
    return cluster.commit_proxies[0]


def _reg_mut(feed_id: bytes, begin: bytes, end: bytes) -> Mutation:
    from foundationdb_tpu.core.system_data import change_feed_key
    from foundationdb_tpu.rpc.wire import encode
    return Mutation.set(change_feed_key(feed_id),
                        encode({"b": begin, "e": end}))


def test_proxy_routes_feed_markers_to_owning_tags():
    from foundationdb_tpu.core.system_data import (change_feed_key,
                                                   change_feed_pop_key)
    from foundationdb_tpu.rpc.wire import encode
    p = _proxy()
    # register over shards 1-2 of the 4-shard even map
    markers = p._apply_metadata(10, [_reg_mut(b"f", b"\x50", b"\x90")])
    assert sorted(m[0] for m in markers) == [1, 2]
    assert all(m[1] == int(MutationType.PRIVATE_FEED_REGISTER)
               for m in markers)
    # pop routes to the same owners, payload untouched
    markers = p._apply_metadata(11, [Mutation.set(
        change_feed_pop_key(b"f"), encode(10))])
    assert sorted((m[0], m[1]) for m in markers) == \
        [(1, int(MutationType.PRIVATE_FEED_POP)),
         (2, int(MutationType.PRIVATE_FEED_POP))]
    # pop of an unregistered feed routes nowhere
    assert p._apply_metadata(12, [Mutation.set(
        change_feed_pop_key(b"nope"), encode(1))]) == []
    # destroy = clear of the registration key
    key = change_feed_key(b"f")
    markers = p._apply_metadata(13, [Mutation.clear_range(
        key, key + b"\x00")])
    assert sorted((m[0], m[1]) for m in markers) == \
        [(1, int(MutationType.PRIVATE_FEED_DESTROY)),
         (2, int(MutationType.PRIVATE_FEED_DESTROY))]
    assert p._feeds == {}


def test_proxy_feed_pop_follows_range_split():
    """The race the satellite names: after a layout change moves the
    feed's range to new tags, a pop/destroy must route to the NEW
    owners — the versioned registry + current map compose correctly."""
    from foundationdb_tpu.core.system_data import (LAYOUT_KEY,
                                                   change_feed_pop_key)
    from foundationdb_tpu.rpc.wire import encode
    p = _proxy()
    markers = p._apply_metadata(10, [_reg_mut(b"f", b"\x00", b"\x40")])
    assert sorted(m[0] for m in markers) == [0]
    # split shard 0 at \x20; the right half moves to fresh tag 9
    layout = {"boundaries": [b"\x20", b"\x40", b"\x80", b"\xc0"],
              "teams": [[0], [9], [1], [2], [3]]}
    p._apply_metadata(11, [Mutation.set(LAYOUT_KEY, encode(layout))])
    markers = p._apply_metadata(12, [Mutation.set(
        change_feed_pop_key(b"f"), encode(11))])
    assert sorted(m[0] for m in markers) == [0, 9]


def test_client_cannot_forge_private_markers():
    from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.runtime.errors import ClientInvalidOperation

    async def main():
        async with Cluster(ClusterConfig()) as cluster:
            db = Database(cluster)
            tr = db.create_transaction()
            tr._writes.atomic(MutationType.PRIVATE_FEED_DESTROY, b"f", b"")
            tr._write_conflicts.append((b"f", b"f\x00"))
            with pytest.raises(ClientInvalidOperation):
                await tr.commit()
    run_simulation(main())


# --- whole-database feeds (ISSUE 8) ---

def test_proxy_routes_whole_db_feed_to_all_tags():
    """A whole-db registration (the backup feed's shape) routes its
    register/pop/destroy markers to EVERY current owner — and keeps
    routing to the post-split owners after a layout change."""
    from foundationdb_tpu.core.system_data import (LAYOUT_KEY,
                                                   change_feed_pop_key)
    from foundationdb_tpu.rpc.wire import encode
    p = _proxy()
    markers = p._apply_metadata(10, [_reg_mut(b"whole", b"", b"\xff")])
    assert sorted(m[0] for m in markers) == [0, 1, 2, 3]
    assert all(m[1] == int(MutationType.PRIVATE_FEED_REGISTER)
               for m in markers)
    # split shard 0; the pop must reach the NEW owner too
    layout = {"boundaries": [b"\x20", b"\x40", b"\x80", b"\xc0"],
              "teams": [[0], [9], [1], [2], [3]]}
    p._apply_metadata(11, [Mutation.set(LAYOUT_KEY, encode(layout))])
    markers = p._apply_metadata(12, [Mutation.set(
        change_feed_pop_key(b"whole"), encode(11))])
    assert sorted(m[0] for m in markers) == [0, 1, 2, 3, 9]


def test_proxy_clamps_forged_feed_range_to_user_keyspace():
    """A forged registration spanning past \\xff must clamp
    \\xff-exclusive (feeds may never observe system writes), and one
    living entirely in system space registers nothing."""
    p = _proxy()
    markers = p._apply_metadata(10, [_reg_mut(b"forged", b"",
                                              b"\xff\xff\xff")])
    assert p._feeds[b"forged"] == (b"", b"\xff")
    assert markers
    assert p._apply_metadata(11, [_reg_mut(b"sys", b"\xff/a",
                                           b"\xff/b")]) == []
    assert b"sys" not in p._feeds


def test_whole_db_capture_excludes_system_writes():
    """A storage server owning the system range still captures ONLY
    user keys into a whole-db feed — system writes are excluded at
    capture, and a clear spanning into \\xff space is clipped."""
    async def main():
        st = ChangeFeedStore()
        st.register(b"w", b"", b"\xff", 0)
        st.capture(5, batch(Mutation.set(b"user1", b"u"),
                            Mutation.set(b"\xff/conf/x", b"sys"),
                            Mutation.set(b"\xff\xff/status", b"sys2")),
                   shard=KeyRange(b"", b"\xff\xff\xff"))
        st.capture(6, batch(Mutation.clear_range(b"zz", b"\xff\xff")),
                   shard=KeyRange(b"", b"\xff\xff\xff"))
        entries, _ = await st.read(b"w", 1, 0, 100)
        flat = [(v, m.type, m.param1, m.param2)
                for v, b in entries for m in b]
        assert flat == [
            (5, MutationType.SET_VALUE, b"user1", b"u"),
            (6, MutationType.CLEAR_RANGE, b"zz", b"\xff"),
        ]
        # a forged over-wide registration clamps at the store too
        st2 = ChangeFeedStore()
        st2.register(b"forged", b"", b"\xff\xff\xff", 0)
        assert st2.feeds[b"forged"].range.end == b"\xff"
        st2.register(b"sys", b"\xff/a", b"\xff/b", 0)
        assert b"sys" not in st2.feeds
    asyncio.run(main())


def test_whole_db_feed_end_to_end_with_system_traffic():
    """A whole-db cursor over a live cluster sees every user mutation
    exactly once and NO system keys, even while system writes (feed
    lifecycle, layout-ish state transactions) flow concurrently."""
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.core.cluster import Cluster, ClusterConfig

    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs()) as cluster:
            db = Database(cluster)
            v0 = await db.create_change_feed(b"wdb")   # whole-db default
            committed = []
            for i in range(5):
                tr = db.create_transaction()
                while True:
                    try:
                        tr.set(b"u%02d" % i, b"v%d" % i)
                        committed.append((b"u%02d" % i, await tr.commit()))
                        break
                    except BaseException as e:
                        await tr.on_error(e)
                # interleave a system write (another feed's lifecycle)
                await db.create_change_feed(b"other%d" % i, b"q", b"r")
            tip = max(v for _k, v in committed)
            cur = db.read_change_feed(b"wdb")
            loop = asyncio.get_running_loop()
            entries = await cur.drain_through(tip,
                                              deadline=loop.time() + 60)
            got = [(m.param1, v) for v, b in entries for m in b]
            assert sorted(got) == sorted(committed)
            assert all(v > v0 for _k, v in got)
            assert all(not k.startswith(b"\xff") for k, _v in got)
    run_simulation(main())


# --- storage apply path: effective capture + rollback ---

def _register_marker(feed_id: bytes, begin: bytes, end: bytes) -> Mutation:
    from foundationdb_tpu.rpc.wire import encode
    return Mutation(MutationType.PRIVATE_FEED_REGISTER, feed_id,
                    encode({"b": begin, "e": end}))


def test_storage_captures_resolved_atomics():
    async def main():
        k = Knobs()
        ss = StorageServer(k, 0, KeyRange(b"", b"\xff"), TLog(k))
        ss._apply(5, [_register_marker(b"f", b"", b"\xff")])
        ss._apply(6, [Mutation.set(b"ctr", (5).to_bytes(8, "little"))])
        ss._apply(7, [Mutation(MutationType.ADD, b"ctr",
                               (3).to_bytes(8, "little"))])
        ss._apply(8, [Mutation(MutationType.COMPARE_AND_CLEAR, b"ctr",
                               (8).to_bytes(8, "little"))])
        entries, _ = await ss.feeds.read(b"f", 1, 0, 100)
        flat = [(v, m.type, m.param1, m.param2)
                for v, b in entries for m in b]
        assert flat == [
            (6, MutationType.SET_VALUE, b"ctr", (5).to_bytes(8, "little")),
            # the feed sees the RESOLVED add, not the operand
            (7, MutationType.SET_VALUE, b"ctr", (8).to_bytes(8, "little")),
            # compare-and-clear resolves to a single-key clear
            (8, MutationType.CLEAR_RANGE, b"ctr", b"ctr\x00"),
        ]
    asyncio.run(main())


def test_storage_rejoin_rolls_back_unacked_feed_entries():
    async def main():
        k = Knobs()
        ss = StorageServer(k, 0, KeyRange(b"", b"\xff"), TLog(k))
        ss._apply(5, [_register_marker(b"f", b"", b"\xff")])
        ss._apply(10, [Mutation.set(b"a", b"1")])
        ss._apply(20, [Mutation.set(b"b", b"2")])
        ss._apply(30, [Mutation.set(b"c", b"3")])
        await ss.rejoin(ss.log_system.generations, 20)
        entries, _ = await ss.feeds.read(b"f", 1, 0, 100)
        assert [v for v, _b in entries] == [10, 20]
        # a feed registered in the rolled-back suffix vanishes entirely
        ss._apply(25, [_register_marker(b"g", b"", b"\xff")])
        await ss.rejoin(ss.log_system.generations, 21)
        assert b"g" not in ss.feeds.feeds
    run_simulation(main())


def test_stream_fences_and_errors():
    from foundationdb_tpu.runtime.errors import (ChangeFeedNotRegistered,
                                                 ChangeFeedPopped,
                                                 WrongShardServer)

    async def main():
        k = Knobs()
        ss = StorageServer(k, 0, KeyRange(b"", b"\xff"), TLog(k))
        with pytest.raises(ChangeFeedNotRegistered):
            await ss.change_feed_stream(ChangeFeedStreamRequest(b"f", 1))
        ss._apply(5, [_register_marker(b"f", b"", b"\x80")])
        ss._apply(6, [Mutation.set(b"a", b"1")])
        ss._apply(7, [Mutation(MutationType.PRIVATE_FEED_POP, b"f",
                               __import__("foundationdb_tpu.rpc.wire",
                                          fromlist=["encode"]).encode(6))])
        with pytest.raises(ChangeFeedPopped):
            await ss.change_feed_stream(ChangeFeedStreamRequest(b"f", 6))
        # a drop over the feed range fences streams above the handoff
        ss._apply(9, [Mutation(MutationType.PRIVATE_DROP_SHARD,
                               b"", b"\x80")])
        with pytest.raises(WrongShardServer):
            await ss.change_feed_stream(ChangeFeedStreamRequest(b"f", 10))
    run_simulation(main())


# --- client cursor end-to-end (in-process cluster) ---

def test_cursor_lifecycle_end_to_end():
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
    from foundationdb_tpu.runtime.errors import ChangeFeedPopped

    async def main():
        async with Cluster(ClusterConfig(storage_servers=2),
                           Knobs()) as cluster:
            db = Database(cluster)
            v0 = await db.create_change_feed(b"f1", b"", b"\xfe")
            committed = []
            for i in range(6):
                tr = db.create_transaction()
                while True:
                    try:
                        tr.set(b"k%02d" % i, b"v%d" % i)
                        committed.append((b"k%02d" % i,
                                          await tr.commit()))
                        break
                    except BaseException as e:
                        await tr.on_error(e)
            tip = max(v for _k, v in committed)
            loop = asyncio.get_running_loop()
            cur = db.read_change_feed(b"f1")
            entries = await cur.drain_through(tip,
                                              deadline=loop.time() + 60)
            got = [(m.param1, v) for v, b in entries for m in b]
            assert sorted(got) == sorted(committed)
            assert all(v > v0 for _k, v in got)
            # versions non-decreasing as delivered
            vs = [v for v, _b in entries]
            assert vs == sorted(vs)

            # pop releases the prefix; a resumed cursor above it is exact
            mid = entries[2][0]
            await db.pop_change_feed(b"f1", mid)
            await asyncio.sleep(1.0)     # markers reach the storages
            cur2 = db.read_change_feed(b"f1", begin_version=mid + 1)
            e2 = await cur2.drain_through(tip, deadline=loop.time() + 60)
            assert [(m.param1, v) for v, b in e2 for m in b] == \
                [g for g in got if g[1] > mid]
            # a cursor below the low-water mark is refused
            with pytest.raises(ChangeFeedPopped):
                stale = db.read_change_feed(b"f1", begin_version=1)
                await stale.drain_through(tip, deadline=loop.time() + 60)
    run_simulation(main())

# --- acceptance sims (ISSUE 4) ---

def _chaos_changefeed_run(seed: int) -> dict:
    """Buggify + machine-attrition chaos around the ChangeFeed
    completeness workload: 2 writers + 1 consumer, one txn-role machine
    killed mid-run (epoch recovery + rollback path), feed popped
    mid-stream."""
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.runtime.buggify import enable_buggify
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster
    from foundationdb_tpu.workloads import run_workloads_on

    knobs = Knobs().override(BUGGIFY_ENABLED=True)
    enable_buggify(True)

    async def main():
        sim = SimulatedCluster(knobs, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6,
                                                      replication=2))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        results = await run_workloads_on(db, [
            {"testName": "ChangeFeed", "transactionsPerClient": 12,
             "popAfter": 8},
            {"testName": "MachineAttrition", "sim": sim,
             "machinesToKill": 1, "secondsBetweenKills": 2.0},
        ], client_count=3)
        await sim.stop()
        return results

    try:
        return run_simulation(main(), seed=seed)
    finally:
        enable_buggify(False)


def test_sim_completeness_under_buggify_attrition_bit_identical():
    """The acceptance criterion verbatim: every committed mutation in
    the feed range delivered exactly once, in version order, under
    buggify + an attrition-driven failover — and the whole delivered
    stream bit-identical across two same-seed runs (the workload's
    check() enforces exactness; the crc pins the bytes)."""
    r1 = _chaos_changefeed_run(29)
    assert r1["ChangeFeed"]["delivered"] >= r1["ChangeFeed"]["commits"] > 0
    assert r1["MachineAttrition"]["machines_killed"] >= 1
    assert r1["ChangeFeed"]["popped_at"] > 0
    r2 = _chaos_changefeed_run(29)
    assert r1 == r2


def test_sim_duplicate_free_resume_after_storage_kill():
    """Mid-stream kill of a machine hosting a feed-range storage
    replica (durable storage): the cursor fails over to the surviving
    replica and, after the reboot, the stream stays complete and
    duplicate-free — the begin-version cursor + committed-floor
    heartbeat contract."""
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        sim = SimulatedCluster(Knobs(), n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6,
                                                      replication=2),
                               durable_storage=True)
        await sim.start()
        state = await sim.wait_epoch(1)
        db = await sim.database()
        await db.create_change_feed(b"rk", b"rk/", b"rk0")
        committed: list[tuple[bytes, int]] = []
        unknown: list[bytes] = []

        async def write(i: int) -> None:
            tr = db.create_transaction()
            while True:
                try:
                    tr.set(b"rk/%04d" % i, b"v%d" % i)
                    committed.append((b"rk/%04d" % i, await tr.commit()))
                    return
                except BaseException as e:
                    from foundationdb_tpu.runtime.errors import \
                        CommitUnknownResult
                    if isinstance(e, CommitUnknownResult):
                        unknown.append(b"rk/%04d" % i)
                        return
                    await tr.on_error(e)

        for i in range(6):
            await write(i)
        cur = db.read_change_feed(b"rk")
        loop = asyncio.get_running_loop()
        first = await cur.drain_through(max(v for _k, v in committed),
                                        deadline=loop.time() + 120)

        # kill a non-coordinator machine hosting a replica of rk/'s
        # shard, keep writing through the outage, then reboot it
        coord_ips = {a.ip for a in sim.coord_addrs}
        replica_ips = [s["worker"][0] for s in state["storage"]
                       if s["begin"] <= b"rk/" < s["end"]]
        # prefer a non-coordinator host; a 3-coordinator quorum survives
        # one member's kill+reboot, so fall back if placement forces it
        victims = [ip for ip in replica_ips if ip not in coord_ips] \
            or replica_ips
        assert victims, "no killable feed-range replica"
        machine = next(m for m in sim.machines if m.ip == victims[0])
        await machine.kill()
        for i in range(6, 12):
            await write(i)
        await machine.reboot()
        for i in range(12, 15):
            await write(i)

        tip = max(v for _k, v in committed)
        rest = await cur.drain_through(tip, deadline=loop.time() + 240)
        got = [(m.param1, v) for v, b in first + rest for m in b]
        acked = {k for k, _v in committed}
        # exactly once, at the exact commit version, for every ack
        assert sorted(g for g in got if g[0] in acked) == sorted(committed)
        # strays must be maybe-committed writes, at most once each
        from collections import Counter
        strays = Counter(k for k, _v in got if k not in acked)
        assert all(k in unknown and n == 1 for k, n in strays.items())
        # delivered in version order
        vs = [v for v, _b in first + rest]
        assert vs == sorted(vs)
        await sim.stop()

    run_simulation(main(), seed=41)


def test_sim_feed_handoff_across_live_split():
    """Register/pop vs range-split races: a live DD split relocates the
    feed's hot half while writes flow; the destination receives the
    retained window via fetch_feed_state, the source fences, and the
    consumer's merged cursor stays complete and duplicate-free."""
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        k = Knobs().override(DD_ENABLED=True, DD_INTERVAL=1.0,
                             DD_SHARD_SPLIT_BYTES=6_000)
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        n_shards = len(state1["shard_teams"])
        db = await sim.database()
        await db.create_change_feed(b"hot", b"hot", b"hou")
        committed: list[tuple[bytes, bytes, int]] = []
        stop = asyncio.Event()

        async def writer(wid: int) -> None:
            i = 0
            while not stop.is_set():
                key = b"hot%02d%05d" % (wid, i)
                val = b"v" * 40
                i += 1
                tr = db.create_transaction()
                while True:
                    try:
                        tr.set(key, val)
                        committed.append((key, val, await tr.commit()))
                        break
                    except BaseException as e:
                        from foundationdb_tpu.runtime.errors import \
                            CommitUnknownResult
                        if isinstance(e, CommitUnknownResult):
                            break     # unique key; never retried
                        await tr.on_error(e)
                await asyncio.sleep(0.05)

        writers = [asyncio.ensure_future(writer(w)) for w in range(2)]
        await sim.wait_state(lambda s: s.get("seq", 0) > 0
                             and len(s["shard_teams"]) > n_shards)
        await asyncio.sleep(2.0)          # writes continue post-flip
        stop.set()
        await asyncio.gather(*writers)

        tip = max(v for _k, _val, v in committed)
        cur = db.read_change_feed(b"hot")
        loop = asyncio.get_running_loop()
        entries = await cur.drain_through(tip, deadline=loop.time() + 240)
        got = sorted((m.param1, v) for v, b in entries for m in b)
        assert got == sorted((k, v) for k, _val, v in committed), \
            f"{len(got)} delivered vs {len(committed)} committed"
        await sim.stop()

    run_simulation(main(), seed=5)


# --- feed stream spans → trace file (ROADMAP PR 2 follow-up (a)) ---

def test_feed_stream_spans_reach_trace_file(tmp_path):
    """A feed consumer never runs a sampled transaction, so the stream
    path roots its own server-side spans (knob SERVER_SPAN_SAMPLE):
    the trace file must carry changeFeedStream Before/After events
    trace_tool can group into a consumer timeline."""
    import os
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import trace_tool

    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
    from foundationdb_tpu.runtime import span as span_mod
    from foundationdb_tpu.runtime.trace import (TraceLog, get_trace_log,
                                                set_trace_log)

    path = os.path.join(str(tmp_path), "trace.jsonl")
    log = TraceLog(path=path)
    prev = get_trace_log()
    set_trace_log(log)
    span_mod.reset_totals()
    knobs = Knobs().override(SERVER_SPAN_SAMPLE=1.0)

    async def main():
        async with Cluster(ClusterConfig(), knobs) as cluster:
            db = Database(cluster)
            await db.create_change_feed(b"tf", b"t", b"u")
            for i in range(3):
                await db.set(b"t%d" % i, b"v")
            cur = db.read_change_feed(b"tf")
            tip = cluster.sequencer.committed_version
            await cur.drain_through(
                tip, deadline=asyncio.get_running_loop().time() + 60)

    run_simulation(main(), seed=77)
    set_trace_log(prev)
    log.close()

    events = trace_tool.load_events(trace_tool.rolled_paths(path))
    feed_events = [e for e in events
                   if str(e.get("Location", "")).startswith(
                       "StorageServer.changeFeedStream")]
    assert feed_events, "no feed-stream span events reached the file"
    assert all(e.get("TraceID") for e in feed_events)
    befores = sum(1 for e in feed_events
                  if e["Location"].endswith(".Before"))
    closes = sum(1 for e in feed_events
                 if e["Location"].endswith((".After", ".Error")))
    assert befores == closes, "unpaired feed-stream span events"
    # the analyzer groups them into per-consumer-poll timelines
    traces = trace_tool.reconstruct(feed_events)
    assert traces


# --- review-hardening regressions ---

def test_capture_clips_clears_to_shard():
    """A CLEAR spanning a shard boundary inside the feed range must be
    captured CLIPPED by each owning server, or the consumer's per-shard
    merge would deliver the overlap once per shard."""
    async def main():
        left = ChangeFeedStore()
        left.register(b"f", b"a", b"z", 0)
        left.capture(5, batch(Mutation.clear_range(b"c", b"p")),
                     shard=KeyRange(b"a", b"m"))
        right = ChangeFeedStore()
        right.register(b"f", b"a", b"z", 0)
        right.capture(5, batch(Mutation.clear_range(b"c", b"p")),
                      shard=KeyRange(b"m", b"z"))
        el, _ = await left.read(b"f", 1, 0, 10)
        er, _ = await right.read(b"f", 1, 0, 10)
        assert [m for _v, b in el for m in b] == \
            [Mutation.clear_range(b"c", b"m")]
        assert [m for _v, b in er for m in b] == \
            [Mutation.clear_range(b"m", b"p")]
        # SETs outside the shard are dropped entirely
        left.capture(6, batch(Mutation.set(b"q", b"1"),
                              Mutation.set(b"b", b"2")),
                     shard=KeyRange(b"a", b"m"))
        el, _ = await left.read(b"f", 6, 0, 10)
        assert [m.param1 for _v, b in el for m in b] == [b"b"]
    asyncio.run(main())


def test_bad_pop_blob_rejected_and_survived():
    """A malformed \\xff/changeFeedPop blob must neither route markers
    (proxy) nor kill the apply loop (storage defense in depth)."""
    from foundationdb_tpu.core.system_data import change_feed_pop_key
    p = _proxy()
    p._apply_metadata(10, [_reg_mut(b"f", b"\x00", b"\x40")])
    assert p._apply_metadata(11, [Mutation.set(
        change_feed_pop_key(b"f"), b"\xff\xfegarbage")]) == []

    async def main():
        k = Knobs()
        ss = StorageServer(k, 0, KeyRange(b"", b"\xff"), TLog(k))
        ss._apply(5, [_register_marker(b"g", b"", b"\xff")])
        # a forged/corrupt marker reaches the apply loop: logged, skipped
        ss._apply(6, [Mutation(MutationType.PRIVATE_FEED_POP, b"g",
                               b"\x00junk"),
                      Mutation.set(b"k", b"v")])
        entries, _ = await ss.feeds.read(b"g", 1, 0, 10)
        assert [m.param1 for _v, b in entries for m in b] == [b"k"]
    asyncio.run(main())


def test_spill_is_durability_not_memory_pressure():
    """Every sealed entry at or below the floor spills each tick even
    far under any memory budget — the TLog pop in the same tick drops
    the replay copies, so an unspilled sub-floor entry would be lost to
    the next crash."""
    from foundationdb_tpu.runtime.files import SimFileSystem
    from foundationdb_tpu.storage.disk_queue import DiskQueue

    async def main():
        fs = SimFileSystem()
        q, _ = await DiskQueue.open(fs.open("d.dq"))
        st = ChangeFeedStore(q)
        st.register(b"f", b"", b"\xff", 0)
        for v in range(1, 6):
            st.capture(v, batch(Mutation.set(b"k%d" % v, b"x")))
        await st.maybe_spill(3)           # durability pass, no mem cap
        f = st.feeds[b"f"]
        assert [v for v, *_ in f.spilled] == [1, 2, 3]
        assert list(f.versions[f.start:]) == [4, 5]
        # the spilled prefix survives a reopen even though memory was
        # nowhere near any budget
        q2, frames = await DiskQueue.open(fs.open("d.dq"))
        st2 = ChangeFeedStore(q2)
        st2.restore(st.export_meta(), frames, q2.front_offset)
        entries, _ = await st2.read(b"f", 1, 0, 10)
        assert [v for v, _b in entries] == [1, 2, 3]
    asyncio.run(main())
