"""Shard heat subsystem (ISSUE 7): tracker units, heat-driven DD
splits under sustained skew, heat-armed tag throttling, and replica
read spreading.

Reference test model: REF:fdbserver/workloads/ReadHotDetection.actor.cpp
(a deliberately heated range must be detected and acted on) +
MoveKeys semantics (the heat-driven relocation must lose no rows).
"""

from __future__ import annotations

import asyncio

from foundationdb_tpu.core.shard_load import (DecayingRate, HeatReservoir,
                                              ShardHeatTracker,
                                              weighted_split_key)
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


# --- unit: decayed rates ---

def test_decaying_rate_converges_and_decays():
    r = DecayingRate(halflife_s=10.0)
    t = 0.0
    # steady 100 events/sec for 60s: estimate converges near 100
    for _ in range(600):
        r.add(10, t)
        t += 0.1
    assert 90.0 < r.rate(t) <= 100.0
    # idle for two half-lives: the estimate drops to ~a quarter
    assert r.rate(t + 20.0) < 0.3 * r.rate(t)
    # long idle: effectively zero (no stale heat hijacking a later scan)
    assert r.rate(t + 200.0) < 1e-3


def test_decaying_rate_warmup_is_biased_low():
    r = DecayingRate(halflife_s=10.0)
    r.add(1000, 0.0)
    # one instant burst never reads back as a huge sustained rate
    assert r.rate(0.0) < 1000.0


# --- unit: the reservoir histogram + split midpoint ---

def test_reservoir_weighted_midpoint():
    res = HeatReservoir(cap=32, seed=1)
    # uniform heat over 16 distinct keys: the midpoint lands mid-keyspace
    for i in range(16):
        res.offer(b"k%02d" % i, 10.0)
    split = res.split_key(b"", b"z")
    assert split is not None
    assert b"k04" < split <= b"k0:"  # within the middle third
    # weights concentrate low (but below the single-key bar): the
    # midpoint shifts left
    res.offer(b"k01", 100.0)
    assert res.split_key(b"", b"z") <= split


def test_reservoir_single_hot_key_returns_none():
    res = HeatReservoir(cap=32, seed=1)
    res.offer(b"hot", 1000.0)
    for i in range(8):
        res.offer(b"cold%d" % i, 1.0)
    # one key holds the bulk of the heat: no split boundary can spread
    # it, the caller must MOVE the shard instead
    assert res.split_key(b"", b"z") is None


def test_reservoir_stays_bounded():
    res = HeatReservoir(cap=16, seed=2)
    for i in range(10_000):
        res.offer(b"u%05d" % i, 1.0)
    assert len(res) <= 16
    assert res.total_weight == 10_000.0


def test_weighted_split_key_respects_bounds():
    samples = [(b"a", 1.0), (b"b", 1.0), (b"c", 1.0), (b"d", 1.0)]
    assert weighted_split_key(samples, b"", b"z") == b"c"
    # too few samples inside the range: no signal
    assert weighted_split_key(samples[:3], b"", b"z") is None
    # the returned key must be STRICTLY inside (begin, end)
    assert weighted_split_key(samples, b"c", b"z") is None


# --- unit: the tracker over the storage accounting shape ---

def test_tracker_ranks_hot_over_cold():
    k = Knobs()
    t = {"now": 0.0}
    hot = ShardHeatTracker(k, 0, clock=lambda: t["now"])
    cold = ShardHeatTracker(k, 1, clock=lambda: t["now"])
    for step in range(200):
        t["now"] = step * 0.05
        hot.record_reads(8, b"h%03d" % (step % 40))
        hot.record_write(b"h%03d" % (step % 40), 80)
        if step % 20 == 0:
            cold.record_reads(1, b"c%03d" % step)
    sh = hot.snapshot(b"", b"\xff")
    sc = cold.snapshot(b"", b"\xff")
    assert sh["rw_per_sec"] > 10 * max(sc["rw_per_sec"], 0.1)
    assert sh["total_reads"] == 1600 and sh["total_writes"] == 200
    # the reservoir saw enough distinct keys for an interior split point
    assert sh["heat_split_key"] is not None
    assert sh["heat_split_key"].startswith(b"h")


def test_tracker_reservoir_tracks_workload_shift():
    """The histogram must age on the rate half-life: after the hotspot
    moves, the split point must follow the NEW heat instead of a
    long-dead hotspot's lifetime-cumulative weight."""
    k = Knobs().override(SHARD_HEAT_HALFLIFE=5.0)
    t = {"now": 0.0}
    tr = ShardHeatTracker(k, 0, clock=lambda: t["now"])
    # hours of hotspot A (low keys)
    for step in range(2000):
        t["now"] = step * 0.05
        tr.record_write(b"a%03d" % (step % 30), 50)
    assert tr.snapshot(b"", b"\xff")["heat_split_key"].startswith(b"a")
    # the workload shifts to hotspot B (high keys) for a few half-lives
    for step in range(2000):
        t["now"] = 100.0 + step * 0.05
        tr.record_write(b"z%03d" % (step % 30), 50)
    split = tr.snapshot(b"", b"\xff")["heat_split_key"]
    assert split is not None and split.startswith(b"z"), split


def test_tracker_packed_batch_accounting():
    from foundationdb_tpu.core.data import MutationBatchBuilder
    k = Knobs()
    t = {"now": 0.0}
    tr = ShardHeatTracker(k, 0, clock=lambda: t["now"])
    b = MutationBatchBuilder()
    for i in range(100):
        b.add(0, b"pk%04d" % i, b"v" * 32)
    batch = b.finish()
    tr.record_write_batch(batch)
    s = tr.snapshot(b"", b"\xff")
    assert s["total_writes"] == 100
    assert s["write_bytes_per_sec"] > 0
    assert len(s["samples"]) >= 1


# --- unit: replica read spreading (knob CLIENT_READ_LOAD_BALANCE) ---

class _FakeStorage:
    def __init__(self, tag: int, log: list) -> None:
        self.tag = tag
        self._log = log
        self.fail = False

    async def get_value(self, key: bytes, version: int) -> bytes:
        if self.fail:
            from foundationdb_tpu.runtime.errors import FutureVersion
            raise FutureVersion()
        self._log.append(self.tag)
        return b"v-" + key


def _group(policy: str, n: int = 3):
    from foundationdb_tpu.core.data import KeyRange
    from foundationdb_tpu.core.load_balance import ReplicaGroup
    log: list = []
    k = Knobs().override(CLIENT_READ_LOAD_BALANCE=policy)
    g = ReplicaGroup(KeyRange(b"", b"\xff"),
                     [_FakeStorage(i, log) for i in range(n)], k)
    return g, log


def test_replica_spread_policies_equivalent_results():
    async def main():
        for policy in ("score", "rotate", "least"):
            g, _log = _group(policy)
            for i in range(12):
                assert await g.get_value(b"k%d" % i, 1) == b"v-k%d" % i
    run_simulation(main())


def test_rotate_spreads_across_team():
    async def main():
        g, log = _group("rotate")
        for i in range(30):
            await g.get_value(b"k", 1)
        counts = g.spread_counts()
        assert sum(counts) == 30
        # every replica served a fair share (exact round-robin here:
        # sequential calls, no penalties)
        assert min(counts) == max(counts) == 10, counts
        assert log[:6] == [0, 1, 2, 0, 1, 2]
    run_simulation(main())


def test_rotate_failover_skips_penalized_replica():
    async def main():
        g, _log = _group("rotate")
        g.replicas[1].fail = True
        for i in range(9):
            assert await g.get_value(b"k", 1) == b"v-k"
        counts = g.spread_counts()
        # the dead replica served nothing; the survivors shared the load
        assert counts[1] == 0
        assert counts[0] > 0 and counts[2] > 0
        # recovery: once healthy (and the penalty expired), it rejoins
        g.replicas[1].fail = False
        await asyncio.sleep(1.1)
        for i in range(6):
            await g.get_value(b"k", 1)
        assert g.spread_counts()[1] > 0
    run_simulation(main())


def test_degraded_replicas_rank_last_under_every_policy():
    """ISSUE 13 / ROADMAP 6 (a): a FailureMonitor-degraded replica (the
    CC-published machine flag, stamped onto storage stubs by
    cluster_client) is the LAST read choice under every spread policy —
    a stable partition composing with rotate/least/score, exactly like
    the penalty class — yet still serves when every healthy teammate
    fails."""
    async def main():
        for policy in ("score", "rotate", "least"):
            g, _log = _group(policy)
            g.replicas[0].degraded = True
            for i in range(12):
                await g.get_value(b"k%d" % i, 1)
            counts = g.spread_counts()
            assert counts[0] == 0, (policy, counts)
            assert counts[1] + counts[2] == 12, (policy, counts)
            # the degraded replica is deprioritized, not excluded: with
            # every healthy teammate failing it still serves the read
            g.replicas[1].fail = True
            g.replicas[2].fail = True
            assert await g.get_value(b"k", 1) == b"v-k"
            assert g.spread_counts()[0] == 1, policy
    run_simulation(main())


def test_least_policy_is_deterministic():
    async def main():
        g, log = _group("least")
        for i in range(6):
            await g.get_value(b"k", 1)
        # sequential reads, zero outstanding at each choice: the stable
        # index tiebreak always picks replica 0 — no RNG draw at all
        assert log == [0] * 6
    run_simulation(main())


# --- unit: heat-armed tag throttling at the ratekeeper ---

class _HeatSS:
    """Storage fake: healthy queues, configurable shard heat — the
    metrics() shape the ratekeeper's heat arm consumes (heat scalars
    ride the SAME sweep as the queue sample, zero extra RPCs)."""
    tag = 0

    def __init__(self) -> None:
        self.writes_per_sec = 0.0
        self.write_bytes_per_sec = 0.0

    async def metrics(self) -> dict:
        return {"tag": self.tag, "durable_engine": True,
                "queue_bytes": 0, "version": 0, "durable_version": 0,
                "shard_begin": b"", "shard_end": b"\xff",
                "shard_reads_per_sec": 0.0,
                "shard_writes_per_sec": self.writes_per_sec,
                "shard_write_bytes_per_sec": self.write_bytes_per_sec,
                "shard_rw_per_sec": self.writes_per_sec}


def _heat_knobs():
    return Knobs().override(TARGET_STORAGE_QUEUE_BYTES=10_000,
                            RATEKEEPER_MAX_TPS=1000.0,
                            RATEKEEPER_MIN_TPS=5.0,
                            RATEKEEPER_HOT_SHARD_WRITES_PER_SEC=50.0,
                            RATEKEEPER_HEAT_WEDGE_S=10.0)


def test_heat_arms_tag_throttle_before_global_falloff():
    from foundationdb_tpu.core.ratekeeper import Ratekeeper

    async def main():
        ss = _HeatSS()
        rk = Ratekeeper(_heat_knobs(), [ss], [])
        # one shard's write rate alone would wedge its queue: 2000 B/s
        # * 10s wedge horizon = 20000 > the 10000-byte target — while
        # the queue itself is still EMPTY (worst == 0, no global limit)
        ss.writes_per_sec = 400.0
        ss.write_bytes_per_sec = 2000.0
        for _ in range(4):
            await rk.admit(90, tags={"hot": 90})
            await rk.admit(10)
            await rk._recompute()
        assert "hot" in rk.heat_tag_rates, rk.limiting_reason
        assert rk.tag_rates["hot"] == rk.heat_tag_rates["hot"]
        assert rk.rate_tps == 1000.0        # the GLOBAL lane stays open
        assert rk.limiting_reason == "heat_tag_throttle_hot"
        # one arming = one activation, not one per recompute tick
        assert rk.heat_throttle_activations == 1
        # cold untagged work sails through
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await rk.admit(50)
        assert loop.time() - t0 < 1.0, "cold work was throttled"
        # heat subsides: the clamp lifts at the next recompute
        ss.writes_per_sec = ss.write_bytes_per_sec = 0.0
        await rk.admit(90, tags={"hot": 90})
        await rk._recompute()
        assert rk.heat_tag_rates == {} and "hot" not in rk.tag_rates
        # re-heating arms AGAIN (a second activation)
        ss.writes_per_sec, ss.write_bytes_per_sec = 400.0, 2000.0
        await rk.admit(90, tags={"hot": 90})
        await rk._recompute()
        assert rk.heat_throttle_activations == 2
    run_simulation(main())


def test_heat_blind_tick_holds_clamp():
    """A tick in which every heat-bearing sample fails (recovery,
    partition) must HOLD the armed clamp — not release a one-interval
    burst mid-overload and re-count the activation a tick later."""
    from foundationdb_tpu.core.ratekeeper import Ratekeeper

    async def main():
        ss = _HeatSS()
        rk = Ratekeeper(_heat_knobs(), [ss], [])
        ss.writes_per_sec, ss.write_bytes_per_sec = 400.0, 2000.0
        for _ in range(3):
            await rk.admit(90, tags={"hot": 90})
            await rk._recompute()
        assert "hot" in rk.heat_tag_rates
        assert rk.heat_throttle_activations == 1
        orig = ss.metrics

        async def boom():
            raise RuntimeError("rpc failed")
        ss.metrics = boom                   # blind tick: sample fails
        await rk._recompute()
        assert "hot" in rk.tag_rates, "clamp released on a blind tick"
        assert rk.heat_throttle_activations == 1
        ss.metrics = orig                   # sample recovers
        await rk.admit(90, tags={"hot": 90})
        await rk._recompute()
        assert "hot" in rk.heat_tag_rates
        assert rk.heat_throttle_activations == 1, \
            "activation double-counted across a blind tick"
    run_simulation(main())


def test_heat_never_arms_without_dominant_tag():
    from foundationdb_tpu.core.ratekeeper import Ratekeeper

    async def main():
        ss = _HeatSS()
        ss.writes_per_sec, ss.write_bytes_per_sec = 400.0, 2000.0
        rk = Ratekeeper(_heat_knobs(), [ss], [])
        for _ in range(4):
            await rk.admit(90)              # untagged workload
            await rk._recompute()
        assert rk.tag_rates == {} and rk.heat_tag_rates == {}
        assert rk.rate_tps == 1000.0
        assert rk.limiting_reason == "unlimited"
        # hot shards still surface for status even without an arm
        assert rk.hot_shards and rk.hot_shards[0]["writes_per_sec"] == 400.0
    run_simulation(main())


def test_heat_below_wedge_horizon_does_not_arm():
    from foundationdb_tpu.core.ratekeeper import Ratekeeper

    async def main():
        ss = _HeatSS()
        # fast ops but tiny bytes: the queue target is 100s away
        ss.writes_per_sec, ss.write_bytes_per_sec = 400.0, 100.0
        rk = Ratekeeper(_heat_knobs(), [ss], [])
        for _ in range(4):
            await rk.admit(90, tags={"hot": 90})
            await rk._recompute()
        assert rk.heat_tag_rates == {}
    run_simulation(main())


# --- sim: a deliberately heated shard splits LIVE at the heat midpoint ---

def test_heat_split_under_sustained_skew(tmp_path):
    """Size policy disabled (split threshold at 16MB, dataset ~100KB),
    heat policy armed: sustained zipf-skewed reads+writes on one shard
    must drive a LIVE heat split whose boundary lands inside the hot
    key range — epoch unchanged, zero lost and zero phantom rows,
    client read latency does not degrade post-split, and the trace
    carries a DDHotSplit/DDHotMove event with the triggering rate."""
    import json
    import os

    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.runtime.rng import deterministic_random
    from foundationdb_tpu.runtime.trace import (TraceLog, get_trace_log,
                                                set_trace_log)
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    trace_path = os.path.join(str(tmp_path), "heat-trace.jsonl")
    prev_log = get_trace_log()
    set_trace_log(TraceLog(path=trace_path))

    async def main():
        k = Knobs().override(
            DD_ENABLED=True, DD_INTERVAL=0.5,
            DD_SHARD_SPLIT_BYTES=1 << 24,          # size policy silent
            DD_SHARD_HEAT_SPLITS=True,
            DD_SHARD_HOT_RW_PER_SEC=40.0,
            DD_HEAT_SUSTAIN_ROUNDS=2, DD_HEAT_COOLDOWN_S=3.0,
            SHARD_HEAT_HALFLIFE=3.0,
            CLIENT_READ_LOAD_BALANCE="rotate")
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6,
                                                      replication=2))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        n_shards_before = len(state1["shard_teams"])
        db = await sim.database()

        written: dict[bytes, bytes] = {}
        stop = asyncio.Event()
        read_lat: list[float] = []
        rng = deterministic_random()

        def hot_key() -> bytes:
            # exponential skew over 200 keys — the zipfian hotspot shape
            i = min(int(rng.random_exp(25.0)), 199)
            return b"hot%05d" % i

        async def writer(wid: int) -> None:
            while not stop.is_set():
                items = {hot_key(): b"v" * 40 for _ in range(5)}

                async def do(tr, items=items):
                    for key, v in items.items():
                        tr.set(key, v)
                await db.run(do)
                written.update(items)
                await asyncio.sleep(0.04)

        async def reader(rid: int) -> None:
            loop = asyncio.get_running_loop()
            while not stop.is_set():
                tr = db.create_transaction()
                t0 = loop.time()
                try:
                    await tr.get(hot_key(), snapshot=True)
                    read_lat.append(loop.time() - t0)
                except Exception as e:   # noqa: BLE001 — follow the move
                    try:
                        await tr.on_error(e)
                    except Exception:    # noqa: BLE001
                        pass
                await asyncio.sleep(0.03)

        tasks = [asyncio.ensure_future(writer(w)) for w in range(3)] + \
            [asyncio.ensure_future(reader(r)) for r in range(2)]

        state2 = await asyncio.wait_for(
            sim.wait_state(
                lambda s: len(s["shard_teams"]) > n_shards_before),
            timeout=120.0)
        n_before = len(read_lat)
        await asyncio.sleep(3.0)          # post-split traffic window
        stop.set()
        await asyncio.gather(*tasks)

        assert state2["epoch"] == state1["epoch"], \
            "a heat split must be LIVE — no recovery"
        # the new boundary is the heat midpoint: a sampled key inside
        # the hot range, not a byte-count artifact
        new_bounds = [bytes(b) for b in state2["shard_boundaries"]]
        hot_bounds = [b for b in new_bounds if b.startswith(b"hot")]
        assert hot_bounds, f"no boundary inside the hot range: {new_bounds}"
        # the distributor attributed the relocation to heat and
        # published the counters with the flip
        dd = sim.leader_dd()
        assert dd is not None
        assert dd.heat_splits_done + dd.heat_moves_done >= 1
        stats = state2.get("dd_stats") or {}
        assert stats.get("heat_splits", 0) + stats.get("heat_moves", 0) >= 1
        assert stats.get("last_heat_rw_per_sec", 0) >= 40.0

        # p99 recovers: the post-split window must not degrade (strict
        # improvement is the real-time bench's job — virtual time has no
        # CPU queueing, so equality is the expected healthy shape here)
        def p99(xs):
            xs = sorted(xs)
            return xs[min(len(xs) - 1, int(len(xs) * 0.99))]
        pre, post = read_lat[:n_before], read_lat[n_before:]
        assert len(pre) >= 10 and len(post) >= 10
        assert p99(post) <= 2.0 * p99(pre) + 0.05, (p99(pre), p99(post))

        # zero lost, zero phantom rows across the handoff
        tr = db.create_transaction()
        while True:
            try:
                rows = await tr.get_range(b"hot", b"hou", limit=0)
                break
            except Exception as e:   # noqa: BLE001 — follow the move
                await tr.on_error(e)
        got = dict(rows)
        missing = [key for key in written if key not in got]
        assert not missing, f"{len(missing)} rows lost, e.g. {missing[:3]}"
        phantom = [key for key in got if key not in written]
        assert not phantom, f"{len(phantom)} phantoms, e.g. {phantom[:3]}"
        await sim.stop()

    try:
        run_simulation(main())
    finally:
        log = get_trace_log()
        set_trace_log(prev_log)
        log.close()
    # the why-did-this-move breadcrumb: a DDHotSplit/DDHotMove event
    # carrying the triggering rate rode the trace file
    hot_events = []
    with open(trace_path) as f:
        for line in f:
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if ev.get("Type") in ("DDHotSplit", "DDHotMove"):
                hot_events.append(ev)
    assert hot_events, "no DDHotSplit/DDHotMove trace event emitted"
    assert hot_events[0]["TriggerRwPerSec"] >= 40.0, hot_events[0]
    assert hot_events[0]["ReadsPerSec"] >= 0.0
    assert hot_events[0]["WritesPerSec"] >= 0.0
