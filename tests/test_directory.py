"""Directory layer + Subspace + high-contention allocator.

Reference test model: REF:bindings/python/fdb/directory_impl.py semantics
and the bindingtester's directory operations — path→prefix mapping via
the \\xfe node tree, allocator uniqueness under contention, partitions
moving as a unit.
"""

from __future__ import annotations

import asyncio

from foundationdb_tpu.client.directory import (DirectoryError, DirectoryLayer,
                                               DirectoryPartition,
                                               HighContentionAllocator)
from foundationdb_tpu.client.subspace import Subspace
from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster


def test_subspace_pack_unpack_range():
    s = Subspace(("app",))
    k = s.pack((1, b"x"))
    assert s.unpack(k) == (1, b"x")
    assert s.contains(k)
    b, e = s.range((1,))
    assert b <= s.pack((1, b"x")) < e
    assert not (b <= s.pack((2,)) < e)
    nested = s["users"]
    assert nested.key().startswith(s.key())
    assert Subspace(("app",))["users"] == nested


async def _with_db(fn):
    k = Knobs()
    sim = SimulatedCluster(k, n_machines=3,
                           spec=ClusterConfigSpec(min_workers=3))
    await sim.start()
    await sim.wait_epoch(1)
    db = await sim.database()
    try:
        await fn(db)
    finally:
        await sim.stop()


def test_directory_create_open_list_remove():
    async def main(db):
        dl = DirectoryLayer()

        async def body(tr):
            d = await dl.create_or_open(tr, ("app", "users"))
            d2 = await dl.create_or_open(tr, ("app", "orders"))
            assert d.key() != d2.key()
            assert len(d.key()) < len(b"app/users")   # short allocated prefix
            tr.set(d.pack((b"alice",)), b"1")
            tr.set(d2.pack((7,)), b"o")
            return d.key(), d2.key()
        p1, p2 = await db.run(body)

        async def body2(tr):
            # reopen finds the same prefixes
            d = await dl.open(tr, ("app", "users"))
            assert d.key() == p1
            assert await tr.get(d.pack((b"alice",))) == b"1"
            names = await dl.list(tr, ("app",))
            assert names == ["orders", "users"] or names == [b"orders", b"users"]
            # create refuses an existing path; open refuses a missing one
            try:
                await dl.create(tr, ("app", "users"))
                raise AssertionError("create on existing must fail")
            except DirectoryError:
                pass
            try:
                await dl.open(tr, ("app", "nope"))
                raise AssertionError("open on missing must fail")
            except DirectoryError:
                pass
        await db.run(body2)

        async def body3(tr):
            assert await dl.remove(tr, ("app", "users"))
            assert not await dl.exists(tr, ("app", "users"))
            d2 = await dl.open(tr, ("app", "orders"))
            assert await tr.get(d2.pack((7,))) == b"o"
            # removed directory's content is gone
            rows = await tr.get_range(p1, p1 + b"\xff")
            assert not rows
        await db.run(body3)
    run_simulation(_with_db(main))


def test_directory_move_and_layer_check():
    async def main(db):
        dl = DirectoryLayer()

        async def body(tr):
            d = await dl.create_or_open(tr, ("a", "b"), layer=b"queue")
            tr.set(d.pack((1,)), b"v")
            return d.key()
        prefix = await db.run(body)

        async def body2(tr):
            moved = await dl.move(tr, ("a", "b"), ("c",))
            assert moved.key() == prefix       # same prefix, new path
            assert not await dl.exists(tr, ("a", "b"))
            d = await dl.open(tr, ("c",), layer=b"queue")
            assert await tr.get(d.pack((1,))) == b"v"
            try:
                await dl.open(tr, ("c",), layer=b"other")
                raise AssertionError("layer mismatch must fail")
            except DirectoryError:
                pass
            try:
                await dl.move(tr, ("c",), ("c", "inside"))
                raise AssertionError("move into self must fail")
            except DirectoryError:
                pass
        await db.run(body2)
    run_simulation(_with_db(main))


def test_directory_partition_moves_as_unit():
    async def main(db):
        dl = DirectoryLayer()

        async def body(tr):
            p = await dl.create_or_open(tr, ("tenants", "acme"),
                                        layer=b"partition")
            assert isinstance(p, DirectoryPartition)
            inner = await p.create_or_open(tr, ("data",))
            tr.set(inner.pack((b"k",)), b"v")
            # raw subspace use of a partition is an error
            try:
                p.pack((1,))
                raise AssertionError("partition raw use must fail")
            except DirectoryError:
                pass
        await db.run(body)

        async def body2(tr):
            p = await dl.open(tr, ("tenants", "acme"))
            inner = await p.open(tr, ("data",))
            assert await tr.get(inner.pack((b"k",))) == b"v"
            names = await p.list(tr)
            assert [str(n) if isinstance(n, str) else n.decode()
                    for n in names] == ["data"]
        await db.run(body2)
    run_simulation(_with_db(main))


def test_hca_unique_under_contention():
    """Concurrent allocators must never hand out the same prefix."""
    async def main(db):
        hca_space = Subspace((b"hca-test",))
        got: list[bytes] = []

        async def one(i):
            async def body(tr):
                hca = HighContentionAllocator(hca_space)
                return await hca.allocate(tr)
            got.append(await db.run(body))
        await asyncio.gather(*(one(i) for i in range(24)))
        assert len(set(got)) == len(got), f"duplicate prefixes: {got}"
    run_simulation(_with_db(main))


def test_directory_path_crossing_partition_routes_inside():
    """A path whose ancestor is a partition must resolve inside the
    partition's own node tree — dl.open(("t","p","data")) and
    partition.open(("data",)) are the same directory."""
    async def main(db):
        dl = DirectoryLayer()

        async def body(tr):
            p = await dl.create_or_open(tr, ("t", "p"), layer=b"partition")
            inner = await p.create_or_open(tr, ("data",))
            tr.set(inner.pack((b"k",)), b"v")
            return inner.key()
        inner_prefix = await db.run(body)

        async def body2(tr):
            via_dl = await dl.open(tr, ("t", "p", "data"))
            assert via_dl.key() == inner_prefix
            assert await dl.exists(tr, ("t", "p", "data"))
            created = await dl.create_or_open(tr, ("t", "p", "more"))
            p = await dl.open(tr, ("t", "p"))
            names = sorted(str(n) if isinstance(n, str) else n.decode()
                           for n in await p.list(tr))
            assert names == ["data", "more"], names
            # listing through the outer layer routes too
            names2 = sorted(str(n) if isinstance(n, str) else n.decode()
                            for n in await dl.list(tr, ("t", "p")))
            assert names2 == ["data", "more"], names2
        await db.run(body2)
    run_simulation(_with_db(main))
