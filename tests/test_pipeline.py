"""End-to-end transaction pipeline tests under deterministic simulation.

Covers the commit call stack of SURVEY.md §3.1 in-process: client RYW txn
→ GRV/commit proxy → sequencer → resolver (conflict backend) → TLog →
storage pull/apply → versioned reads.
"""

import pytest

from foundationdb_tpu.client import Database, KeySelector
from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
from foundationdb_tpu.core.data import MutationType
from foundationdb_tpu.runtime.errors import NotCommitted, TransactionTooOld
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


def sim(coro_fn, seed=0, config=None, knobs=None):
    async def main():
        async with Cluster(config or ClusterConfig(),
                           knobs or Knobs()) as cluster:
            return await coro_fn(Database(cluster))
    return run_simulation(main(), seed=seed)


def multi_config():
    return ClusterConfig(commit_proxies=2, grv_proxies=2, resolvers=2,
                         logs=2, storage_servers=4)


@pytest.mark.parametrize("config", [None, multi_config()],
                         ids=["single", "multi-role"])
def test_set_get(config):
    async def body(db):
        await db.set(b"hello", b"world")
        assert await db.get(b"hello") == b"world"
        assert await db.get(b"missing") is None
    sim(body, config=config)


@pytest.mark.parametrize("config", [None, multi_config()],
                         ids=["single", "multi-role"])
def test_get_range(config):
    async def body(db):
        async def fill(tr):
            for i in range(10):
                tr.set(b"k%02d" % i, b"v%d" % i)
        await db.run(fill)
        rows = await db.get_range(b"k00", b"k99")
        assert [k for k, _ in rows] == [b"k%02d" % i for i in range(10)]
        rows = await db.get_range(b"k03", b"k07")
        assert [k for k, _ in rows] == [b"k03", b"k04", b"k05", b"k06"]
        rows = await db.get_range(b"k00", b"k99", limit=3)
        assert [k for k, _ in rows] == [b"k00", b"k01", b"k02"]
        rows = await db.get_range(b"k00", b"k99", limit=3, reverse=True)
        assert [k for k, _ in rows] == [b"k09", b"k08", b"k07"]
    sim(body, config=config)


def test_clear_and_clear_range():
    async def body(db):
        async def fill(tr):
            for i in range(10):
                tr.set(b"k%02d" % i, b"v")
        await db.run(fill)
        await db.clear(b"k00")
        await db.clear_range(b"k03", b"k07")
        rows = await db.get_range(b"", b"\xff")
        assert [k for k, _ in rows] == [b"k01", b"k02", b"k07", b"k08", b"k09"]
    sim(body)


def test_ryw_semantics():
    async def body(db):
        await db.set(b"a", b"base")

        async def txn(tr):
            # read-your-writes: uncommitted set visible
            tr.set(b"b", b"new")
            assert await tr.get(b"b") == b"new"
            # clear hides committed data inside the txn
            tr.clear(b"a")
            assert await tr.get(b"a") is None
            # range read merges writes over snapshot
            tr.set(b"c", b"3")
            rows = await tr.get_range(b"", b"\xff")
            assert [k for k, _ in rows] == [b"b", b"c"]
            # atomic on top of uncommitted state folds client-side
            tr.add(b"ctr", (5).to_bytes(8, "little"))
            v = await tr.get(b"ctr")
            assert int.from_bytes(v, "little") == 5
        await db.run(txn)
        assert await db.get(b"a") is None
        assert await db.get(b"b") == b"new"
    sim(body)


def test_conflict_detection():
    async def body(db):
        await db.set(b"x", b"0")
        tr1 = db.create_transaction()
        tr2 = db.create_transaction()
        # both read x, both write x — loser must get not_committed
        await tr1.get(b"x")
        await tr2.get(b"x")
        tr1.set(b"x", b"1")
        tr2.set(b"x", b"2")
        await tr1.commit()
        with pytest.raises(NotCommitted):
            await tr2.commit()
        assert await db.get(b"x") == b"1"
    sim(body)


def test_no_conflict_disjoint_keys():
    async def body(db):
        tr1 = db.create_transaction()
        tr2 = db.create_transaction()
        await tr1.get(b"a")
        await tr2.get(b"b")
        tr1.set(b"a", b"1")
        tr2.set(b"b", b"2")
        await tr1.commit()
        await tr2.commit()   # must not raise
    sim(body)


def test_snapshot_read_no_conflict():
    async def body(db):
        await db.set(b"x", b"0")
        tr1 = db.create_transaction()
        tr2 = db.create_transaction()
        await tr1.get(b"x", snapshot=True)   # snapshot read: no read conflict
        await tr2.get(b"x")
        tr1.set(b"y", b"1")
        tr2.set(b"x", b"2")
        await tr2.commit()
        await tr1.commit()   # must not raise despite x changing
    sim(body)


def test_blind_write_no_conflict():
    async def body(db):
        tr1 = db.create_transaction()
        tr2 = db.create_transaction()
        tr1.set(b"x", b"1")
        tr2.set(b"x", b"2")
        await tr1.commit()
        await tr2.commit()   # blind writes never conflict
    sim(body)


def test_range_conflict():
    async def body(db):
        tr1 = db.create_transaction()
        tr2 = db.create_transaction()
        await tr1.get_range(b"a", b"m")     # read conflict on [a, m)
        tr1.set(b"out", b"1")
        tr2.set(b"c", b"2")                  # write inside the read range
        await tr2.commit()
        with pytest.raises(NotCommitted):
            await tr1.commit()
    sim(body)


def test_atomic_ops_across_commits():
    async def body(db):
        for _ in range(3):
            async def add(tr):
                tr.add(b"ctr", (10).to_bytes(8, "little"))
            await db.run(add)
        v = await db.get(b"ctr")
        assert int.from_bytes(v, "little") == 30

        async def amax(tr):
            tr.max(b"m", (7).to_bytes(8, "little"))
        await db.run(amax)
        async def amax2(tr):
            tr.max(b"m", (3).to_bytes(8, "little"))
        await db.run(amax2)
        assert int.from_bytes(await db.get(b"m"), "little") == 7
    sim(body)


def test_key_selectors():
    async def body(db):
        async def fill(tr):
            for k in (b"a", b"c", b"e", b"g"):
                tr.set(k, b"v")
        await db.run(fill)
        tr = db.create_transaction()
        assert await tr.get_key(KeySelector.first_greater_or_equal(b"c")) == b"c"
        assert await tr.get_key(KeySelector.first_greater_than(b"c")) == b"e"
        assert await tr.get_key(KeySelector.last_less_or_equal(b"c")) == b"c"
        assert await tr.get_key(KeySelector.last_less_than(b"c")) == b"a"
        assert await tr.get_key(KeySelector.first_greater_or_equal(b"b")) == b"c"
        assert await tr.get_key(KeySelector.first_greater_or_equal(b"c") + 2) == b"g"
        # selector range read
        rows = await tr.get_range(KeySelector.first_greater_than(b"a"),
                                  KeySelector.first_greater_or_equal(b"g"))
        assert [k for k, _ in rows] == [b"c", b"e"]
    sim(body)


def test_versionstamped_key():
    import struct
    async def body(db):
        async def vs(tr):
            # 10-byte placeholder at offset 3, then 4-byte LE offset suffix
            key = b"vs/" + b"\x00" * 10 + struct.pack("<I", 3)
            tr.set_versionstamped_key(key, b"payload")
        await db.run(vs)
        rows = await db.get_range(b"vs/", b"vs0")
        assert len(rows) == 1
        k, v = rows[0]
        assert v == b"payload" and len(k) == 13
        stamp_version = struct.unpack(">Q", k[3:11])[0]
        assert stamp_version > 0
    sim(body)


def test_too_old():
    async def body(db):
        import asyncio
        # two commits spaced > window apart so the second resolve raises
        # the history floor well above version 1 (the floor lags one
        # batch, matching the reference's setOldestVersion timing)
        await db.set(b"x", b"0")
        await asyncio.sleep(0.01)    # ≈10k versions of virtual time
        await db.set(b"x", b"1")
        tr = db.create_transaction()
        tr.set_read_version(1)       # ancient snapshot far below the floor
        tr.set(b"x", b"2")
        tr.add_read_conflict_key(b"x")
        with pytest.raises(TransactionTooOld):
            await tr.commit()
    knobs = Knobs().override(MAX_WRITE_TRANSACTION_LIFE_VERSIONS=1000)
    sim(body, knobs=knobs)


def test_watch():
    async def body(db):
        import asyncio
        await db.set(b"w", b"0")
        tr = db.create_transaction()
        fut = await tr.watch(b"w")
        await tr.commit()
        assert not fut.done()
        await db.set(b"w", b"1")
        await asyncio.wait_for(fut, 5)
    sim(body)


def test_db_run_retries_conflict():
    async def body(db):
        await db.set(b"ctr", (0).to_bytes(8, "little"))
        import asyncio

        async def incr(tr):
            v = await tr.get(b"ctr")
            n = int.from_bytes(v, "little") + 1
            tr.set(b"ctr", n.to_bytes(8, "little"))

        # 10 concurrent read-modify-write txns on one key: conflicts are
        # certain; db.run must retry each to completion
        await asyncio.gather(*(db.run(incr) for _ in range(10)))
        v = await db.get(b"ctr")
        assert int.from_bytes(v, "little") == 10
    sim(body)


@pytest.mark.parametrize("backend", ["numpy", "cpp"])
def test_backends_in_pipeline(backend):
    async def body(db):
        await db.set(b"x", b"0")
        tr1 = db.create_transaction()
        tr2 = db.create_transaction()
        await tr1.get(b"x")
        await tr2.get(b"x")
        tr1.set(b"x", b"1")
        tr2.set(b"x", b"2")
        await tr1.commit()
        with pytest.raises(NotCommitted):
            await tr2.commit()
    sim(body, knobs=Knobs().override(RESOLVER_CONFLICT_BACKEND=backend))


def test_determinism_same_seed_same_result():
    async def body(db):
        import asyncio
        from foundationdb_tpu.runtime.rng import deterministic_random

        async def writer(i):
            rng = deterministic_random()
            for _ in range(5):
                async def go(tr):
                    k = b"k%d" % rng.random_int(0, 20)
                    v = await tr.get(k)
                    tr.set(k, (len(v or b"") + 1).to_bytes(4, "little"))
                await db.run(go)
        await asyncio.gather(*(writer(i) for i in range(4)))
        return await db.get_range(b"", b"\xff")

    r1 = sim(body, seed=7, config=multi_config())
    r2 = sim(body, seed=7, config=multi_config())
    assert r1 == r2
