"""Client graceful degradation (ISSUE 12 satellite): the C API's
timeout / retry_limit / max_retry_delay TransactionOptions trio,
enforced in the on_error retry loop and on the blocking surfaces — a
degraded cluster surfaces BOUNDED errors instead of unbounded hangs.
"""

from __future__ import annotations

import asyncio

import pytest

from foundationdb_tpu.client import Database
from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
from foundationdb_tpu.runtime.errors import (NotCommitted,
                                             TransactionTimedOut)
from foundationdb_tpu.runtime.simloop import run_simulation


def test_retry_limit_bounds_on_error():
    """retry_limit=N allows exactly N on_error retries, then re-raises
    the ORIGINAL error; -1 (the default) stays unbounded."""
    async def main():
        cluster = Cluster(ClusterConfig())
        tr = cluster and Database(cluster).create_transaction()
        tr.set_retry_limit(2)
        await tr.on_error(NotCommitted())       # retry 1
        await tr.on_error(NotCommitted())       # retry 2
        with pytest.raises(NotCommitted):
            await tr.on_error(NotCommitted())   # limit exceeded
        # a fresh transaction with limit 0 never retries
        tr2 = Database(cluster).create_transaction()
        tr2.set_retry_limit(0)
        with pytest.raises(NotCommitted):
            await tr2.on_error(NotCommitted())
    run_simulation(main())


def test_max_retry_delay_caps_backoff():
    """Backoff grows exponentially but never past max_retry_delay —
    measured on the virtual clock, where sleeps are exact."""
    async def main():
        cluster = Cluster(ClusterConfig())
        tr = Database(cluster).create_transaction()
        tr.set_max_retry_delay(0.05)
        loop = asyncio.get_running_loop()
        # drive the retry count high enough that uncapped backoff would
        # be ~1s per retry; every individual delay must stay <= the cap
        for _ in range(12):
            t0 = loop.time()
            await tr.on_error(NotCommitted())
            assert loop.time() - t0 <= 0.05 + 1e-9
    run_simulation(main())


def test_timeout_bounds_the_retry_loop():
    """A transaction past its deadline refuses to retry: on_error raises
    transaction_timed_out instead of sleeping again — the bounded-error
    contract a degraded cluster depends on."""
    async def main():
        cluster = Cluster(ClusterConfig())
        tr = Database(cluster).create_transaction()
        tr.set_timeout(0.5)
        with pytest.raises(TransactionTimedOut):
            # retryable errors loop until the virtual clock crosses the
            # deadline, then the loop MUST terminate
            for _ in range(10_000):
                await tr.on_error(NotCommitted())
    run_simulation(main())


def test_timeout_bounds_blocking_reads():
    """An armed deadline bounds the blocking surfaces themselves: a
    read issued after the deadline fails immediately with
    transaction_timed_out rather than dialing the cluster."""
    async def main():
        cluster = Cluster(ClusterConfig())
        cluster.start()
        try:
            db = Database(cluster)
            tr = db.create_transaction()
            tr.set_timeout(0.2)
            # within the deadline: works normally
            assert await tr.get(b"opt-k") is None
            await asyncio.sleep(0.3)            # virtual: crosses it
            with pytest.raises(TransactionTimedOut):
                await tr.get(b"opt-k2")
            # commit past the deadline is refused the same way
            tr2 = db.create_transaction()
            tr2.set_timeout(0.1)
            tr2.set(b"opt-k3", b"v")
            await asyncio.sleep(0.2)
            with pytest.raises(TransactionTimedOut):
                await tr2.commit()
            # options persist across reset (upstream: the retry loop
            # holds TransactionOptions across resets)
            tr2.reset()
            assert tr2.timeout == 0.1
        finally:
            await cluster.stop()
    run_simulation(main())


def test_timeout_zero_means_unbounded():
    async def main():
        cluster = Cluster(ClusterConfig())
        cluster.start()
        try:
            tr = Database(cluster).create_transaction()
            assert tr.timeout == 0.0            # knob default: disabled
            await asyncio.sleep(1.0)
            assert await tr.get(b"nope") is None    # no deadline armed
        finally:
            await cluster.stop()
    run_simulation(main())
