"""The metrics plane's primitives (ISSUE 15 satellite): the until-now-
untested trace.py stats instruments — Histogram bucket/percentile edges,
CounterCollection rate computation across emits — plus MetricsRegistry
emission determinism under the sim clock and the RateMeter virtual-time
fix."""

from __future__ import annotations

import asyncio
import json

from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.metrics import MetricsRegistry, MetricsSource
from foundationdb_tpu.runtime.profiler import RateMeter
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.runtime.trace import (CounterCollection, Histogram,
                                            TraceLog)


def _sink_log(events: list, clock=None) -> TraceLog:
    log = TraceLog(clock=clock or (lambda: 0.0))
    log.sink = events.append
    return log


# --- Histogram edges ---


def test_histogram_bucket_edges():
    h = Histogram("T", "Op")
    # sub-1 samples land in bucket 0 (the [1, 2) bucket's floor clamp)
    h.sample(0.25)
    assert h.buckets[0] == 1
    h.sample(1.0)       # [1, 2)
    assert h.buckets[0] == 2
    h.sample(2.0)       # [2, 4) -> bucket 1
    assert h.buckets[1] == 1
    h.sample(3.9)
    assert h.buckets[1] == 2
    # a huge sample clamps into the last bucket instead of overflowing
    h.sample(float(1 << 40))
    assert h.buckets[31] == 1
    assert h.count == 5
    assert h.min == 0.25 and h.max == float(1 << 40)


def test_histogram_percentile_edges():
    h = Histogram("T", "Op")
    assert h.percentile(0.5) == 0.0         # empty: 0, not a crash
    for _ in range(99):
        h.sample(1.0)                       # bucket 0, upper bound 2
    h.sample(100.0)                         # bucket 6, upper bound 128
    assert h.percentile(0.5) == 2.0
    assert h.percentile(0.99) == 2.0
    assert h.percentile(1.0) == 128.0       # the tail sample's bucket


def test_histogram_clear_on_log():
    events: list[dict] = []
    log = _sink_log(events)
    h = Histogram("Grp", "Lat")
    h.log_metrics(log)
    assert events == []                     # empty histogram: no event
    h.sample(10.0)
    h.sample(20.0)
    h.log_metrics(log)
    assert len(events) == 1
    ev = events[0]
    assert ev["Type"] == "HistogramGrpLat" and ev["Count"] == 2
    assert ev["Min"] == 10.0 and ev["Max"] == 20.0
    # the emission cleared the interval: counts, extremes, buckets
    assert h.count == 0 and h.min is None and h.max is None
    assert sum(h.buckets) == 0
    h.log_metrics(log)
    assert len(events) == 1                 # nothing to re-emit


# --- CounterCollection rates ---


def test_counter_collection_rates_across_emits():
    events: list[dict] = []
    t = {"now": 0.0}
    log = _sink_log(events, clock=lambda: t["now"])
    cc = CounterCollection("Probe", "7")
    cc.counter("Ops").add(10)
    cc.log_metrics(log)
    # first emit: absolute values only — no interval exists yet
    assert events[0]["Ops"] == 10 and "OpsRate" not in events[0]
    cc.counter("Ops").add(30)
    t["now"] = 2.0
    cc.log_metrics(log)
    assert events[1]["Ops"] == 40
    assert events[1]["OpsRate"] == 15.0     # 30 more over 2 seconds
    # a counter created between emits rates against the full interval
    cc.counter("Late").add(8)
    t["now"] = 6.0
    cc.log_metrics(log)
    assert events[2]["LateRate"] == 2.0     # 8 over 4 seconds
    assert events[2]["OpsRate"] == 0.0
    # extra details (the registry's gauge fold) ride the same event
    t["now"] = 7.0
    cc.log_metrics(log, extra={"Gauge": 42})
    assert events[3]["Gauge"] == 42 and events[3]["ID"] == "7"


# --- MetricsRegistry ---


def test_registry_emission_order_and_gauges():
    events: list[dict] = []
    log = _sink_log(events)
    reg = MetricsRegistry()
    a = MetricsSource("Alpha", "1").gauge("V", lambda: 11)
    b = MetricsSource("Beta", "2").gauge("V", lambda: 22)
    boom = MetricsSource("Gamma", "3") \
        .gauge("Bad", lambda: 1 / 0).gauge("Good", lambda: 33)
    reg.register(a)
    reg.register(b)
    reg.register(boom)
    reg.emit_all(log)
    # registration order IS emission order (the determinism contract)
    assert [e["Type"] for e in events] == \
        ["AlphaMetrics", "BetaMetrics", "GammaMetrics"]
    assert events[0]["V"] == 11 and events[1]["V"] == 22
    # a raising gauge is skipped, its siblings survive
    assert "Bad" not in events[2] and events[2]["Good"] == 33
    # unregister removes the series
    events.clear()
    reg.unregister(b)
    reg.emit_all(log)
    assert [e["Type"] for e in events] == ["AlphaMetrics", "GammaMetrics"]
    snap = reg.snapshot()
    assert snap["Alpha/1"]["V"] == 11


def _registry_sim_run(seed: int) -> list[str]:
    """One seeded sim run of an emitter over two sources; returns the
    JSON-serialized event stream."""
    from foundationdb_tpu.runtime import trace as trace_mod

    events: list[dict] = []
    prev = trace_mod.get_trace_log()
    log = TraceLog()                # loop-clock default under the sim
    log.sink = events.append
    trace_mod.set_trace_log(log)
    try:
        async def main():
            reg = MetricsRegistry()
            state = {"n": 0}
            reg.register(MetricsSource("RoleA", "0")
                         .gauge("N", lambda: state["n"]))
            reg.register(MetricsSource("RoleB", "1")
                         .gauge("Twice", lambda: 2 * state["n"]))
            reg.start_emitter(0.5)
            for _ in range(20):
                state["n"] += 1
                await asyncio.sleep(0.2)
            await reg.stop_emitter()

        run_simulation(main(), seed=seed)
    finally:
        trace_mod.set_trace_log(prev)
    return [json.dumps(e, sort_keys=True) for e in events]


def test_registry_emission_deterministic_under_sim_clock():
    """Same seed → byte-identical *Metrics streams (ISSUE 15: the plane
    must never perturb the standing bit-identical discipline)."""
    a = _registry_sim_run(42)
    b = _registry_sim_run(42)
    assert a and a == b


def test_registry_emitter_runs_on_virtual_cadence():
    """The emitter's sleep rides the sim clock: 10 virtual seconds at a
    1s interval is exactly 10 passes, in wall milliseconds."""
    from foundationdb_tpu.runtime import trace as trace_mod

    events: list[dict] = []
    prev = trace_mod.get_trace_log()
    log = TraceLog()
    log.sink = events.append
    trace_mod.set_trace_log(log)
    try:
        async def main():
            reg = MetricsRegistry()
            reg.register(MetricsSource("Tick", "0").gauge("One", lambda: 1))
            reg.start_emitter(1.0)
            await asyncio.sleep(10.05)
            await reg.stop_emitter()
            return reg.emissions

        emissions = run_simulation(main())
    finally:
        trace_mod.set_trace_log(prev)
    assert emissions == 10
    ticks = [e for e in events if e["Type"] == "TickMetrics"]
    assert len(ticks) == 10
    times = [e["Time"] for e in ticks]
    assert times == [round(float(i), 6) for i in range(1, 11)]


# --- RateMeter under the sim clock (ISSUE 15 satellite) ---


def test_rate_meter_uses_virtual_time_under_sim():
    """Before the clock injection a sim-run meter divided virtual-time
    work by ~zero wall seconds (nonsense rates); now per_sec is the
    virtual-time rate."""
    async def main():
        m = RateMeter("probe")
        for _ in range(10):
            m.add(100)
            await asyncio.sleep(1.0)
        return m.snapshot()

    snap = run_simulation(main())
    assert snap["count"] == 1000
    # 1000 events over 10 virtual seconds: the lifetime rate is exactly
    # 100/s, and the windowed rate is in the same decade (its trailing
    # mark rotates on the 5s window)
    assert snap["per_sec_lifetime"] == 100.0
    assert 50.0 <= snap["per_sec"] <= 250.0


def test_rate_meter_wall_clock_outside_loop():
    m = RateMeter("probe")
    m.add(5)
    snap = m.snapshot()
    assert snap["count"] == 5 and snap["batches"] == 1
    assert snap["mean_batch"] == 5.0


# --- the worker-level stall surface (ISSUE 15 satellite) ---


def test_stall_metrics_surface_empty_without_profiler():
    from foundationdb_tpu.runtime.profiler import stall_metrics
    assert stall_metrics() == {}


def test_stall_metrics_surface_with_profiler():
    import time as _time

    from foundationdb_tpu.runtime.profiler import (SlowTaskProfiler,
                                                   stall_metrics)

    async def main():
        prof = SlowTaskProfiler(threshold=0.05).start()
        await asyncio.sleep(0.12)
        _time.sleep(0.2)            # the stall
        await asyncio.sleep(0.12)
        m = stall_metrics()
        prof.stop()
        return m, prof

    loop = asyncio.new_event_loop()
    try:
        m, prof = loop.run_until_complete(main())
    finally:
        loop.close()
    assert m["slow_task_stalls"] >= 1
    assert m["slow_task_last_stall_ms"] >= 50.0
    # stop() retires the active profiler: the surface empties again
    assert stall_metrics() == {}


def test_cluster_registers_every_role_kind():
    """The in-process Cluster wires every role into one registry in a
    deterministic order."""
    from foundationdb_tpu.core.cluster import Cluster, ClusterConfig

    async def main():
        c = Cluster(ClusterConfig(commit_proxies=2, grv_proxies=1,
                                  resolvers=2, logs=2, storage_servers=2),
                    Knobs())
        names = [s.name for s in c.metrics_registry.sources()]
        assert names == ["Sequencer", "TLog", "TLog", "Resolver",
                         "Resolver", "Storage", "Storage", "Ratekeeper",
                         "GrvProxy", "ProxyCommit", "ProxyCommit"]
        # ids disambiguate instances of one kind
        tlogs = [s.id for s in c.metrics_registry.sources()
                 if s.name == "TLog"]
        assert tlogs == ["0", "1"]

    run_simulation(main())
