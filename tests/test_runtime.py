"""Tests for the L0 runtime: sim loop determinism, RNG, knobs, trace, actors."""

import asyncio

import pytest

from foundationdb_tpu.runtime import (
    DeterministicRandom, Knobs, Promise, PromiseStream, ActorCollection,
    SimQuiescenceError, TraceEvent, TraceLog, run_simulation, timeout_error,
    deterministic_random, enable_buggify, buggify,
)
from foundationdb_tpu.runtime.errors import TimedOut, NotCommitted, error_from_code


def test_rng_deterministic():
    a = DeterministicRandom(42)
    b = DeterministicRandom(42)
    assert [a.next_u64() for _ in range(100)] == [b.next_u64() for _ in range(100)]
    c = DeterministicRandom(43)
    assert a.next_u64() != c.next_u64()


def test_rng_ranges():
    r = DeterministicRandom(7)
    vals = [r.random_int(10, 20) for _ in range(1000)]
    assert min(vals) >= 10 and max(vals) < 20
    fs = [r.random() for _ in range(1000)]
    assert all(0.0 <= f < 1.0 for f in fs)
    assert len(r.random_bytes(33)) == 33


def test_errors():
    e = NotCommitted()
    assert e.code == 1020 and e.retryable and not e.maybe_committed
    assert error_from_code(1021).maybe_committed
    assert error_from_code(999999).code == 999999


def test_knobs():
    k = Knobs()
    k2 = k.set_from_strings({"resolver_conflict_backend": "tpu",
                             "conflict_ring_capacity": "1024",
                             "commit_batch_interval": "0.01",
                             "buggify_enabled": "true"})
    assert k2.RESOLVER_CONFLICT_BACKEND == "tpu"
    assert k2.CONFLICT_RING_CAPACITY == 1024
    assert k2.COMMIT_BATCH_INTERVAL == 0.01
    assert k2.BUGGIFY_ENABLED is True
    assert k.RESOLVER_CONFLICT_BACKEND == "numpy"  # original untouched
    with pytest.raises(KeyError):
        k.set_from_strings({"no_such_knob": "1"})


def test_sim_virtual_time():
    async def main():
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await asyncio.sleep(100.0)       # virtual: returns instantly
        await asyncio.sleep(3600.0)
        return loop.time() - t0

    elapsed = run_simulation(main(), seed=1)
    assert abs(elapsed - 3700.0) < 1.0   # clock jumped, not slept


def test_sim_determinism():
    async def main():
        rng = deterministic_random()
        log: list = []

        async def worker(i):
            for _ in range(5):
                await asyncio.sleep(rng.random() * 0.01)
                log.append((i, round(asyncio.get_running_loop().time(), 9)))

        await asyncio.gather(*[worker(i) for i in range(5)])
        return log

    a = run_simulation(main(), seed=99)
    b = run_simulation(main(), seed=99)
    c = run_simulation(main(), seed=100)
    assert a == b
    assert a != c


def test_sim_quiescence_detected():
    async def main():
        await Promise().future  # never set, nothing else scheduled

    with pytest.raises(SimQuiescenceError):
        run_simulation(main(), seed=0)


def test_timeout_error():
    async def main():
        with pytest.raises(TimedOut):
            await timeout_error(asyncio.sleep(10.0), 0.5)
        return asyncio.get_running_loop().time()

    t = run_simulation(main(), seed=0)
    assert 0.4 < t < 1.0


def test_promise_stream_and_actor_collection():
    async def main():
        ps = PromiseStream()
        out = []

        async def consumer():
            async for v in ps:
                out.append(v)
                if v == 2:
                    return "done"

        ac = ActorCollection()
        t = ac.add(consumer())
        ps.send(1)
        ps.send(2)
        r = await t

        async def boom():
            raise ValueError("x")

        ac.add(boom())
        with pytest.raises(ValueError):
            await ac.wait_for_error()
        await ac.aclose()
        return out, r

    out, r = run_simulation(main(), seed=0)
    assert out == [1, 2] and r == "done"


def test_trace_events():
    seen = []
    log = TraceLog()
    log.sink = seen.append
    TraceEvent("TestEvent", log=log).detail("K", 5).log()
    TraceEvent("Quiet", severity=5, log=log).log()  # below min severity
    assert len(seen) == 1
    assert seen[0]["Type"] == "TestEvent" and seen[0]["K"] == 5


def test_buggify_deterministic():
    from foundationdb_tpu.runtime import set_deterministic_random
    set_deterministic_random(DeterministicRandom(5))
    enable_buggify(True)
    a = [buggify("site1") for _ in range(200)]
    set_deterministic_random(DeterministicRandom(5))
    enable_buggify(True)
    b = [buggify("site1") for _ in range(200)]
    assert a == b
    enable_buggify(False)
    assert not any(buggify("site1") for _ in range(50))
