"""FailureMonitor: detection and recovery over the simulated network."""

import asyncio

from foundationdb_tpu.rpc.failure_monitor import FailureMonitor
from foundationdb_tpu.rpc.sim_transport import SimNetwork, SimTransport
from foundationdb_tpu.rpc.transport import NetworkAddress
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation

A = NetworkAddress("10.0.0.1", 4000)
B = NetworkAddress("10.0.0.2", 4000)


def _setup(knobs):
    net = SimNetwork(knobs)
    ta = SimTransport(net, A)
    tb = SimTransport(net, B)
    return net, ta, tb


def test_detects_dead_process_and_recovery():
    async def main():
        k = Knobs().override(FAILURE_TIMEOUT=1.0, PING_INTERVAL=0.25)
        net, ta, tb = _setup(k)
        fm = FailureMonitor(ta, k)
        loop = asyncio.get_running_loop()

        assert fm.is_available(B)
        await asyncio.sleep(1.0)
        assert fm.is_available(B)          # healthy peer stays available

        net.kill(B)
        t0 = loop.time()
        await fm.wait_for_failure(B)
        detect = loop.time() - t0
        assert detect <= 3 * k.FAILURE_TIMEOUT + 1.0, detect

        net.reboot(B)
        await fm.wait_for_recovery(B)
        assert fm.is_available(B)
        await fm.close()
    run_simulation(main(), seed=1)


def test_partition_is_failure_from_one_side():
    async def main():
        k = Knobs().override(FAILURE_TIMEOUT=1.0, PING_INTERVAL=0.25)
        net, ta, tb = _setup(k)
        fm_a = FailureMonitor(ta, k)
        net.partition(A, B)
        await fm_a.wait_for_failure(B)
        assert not fm_a.is_available(B)
        net.heal(A, B)
        await fm_a.wait_for_recovery(B)
        await fm_a.close()
    run_simulation(main(), seed=2)


def test_deterministic_detection_time():
    async def main():
        k = Knobs().override(FAILURE_TIMEOUT=1.0, PING_INTERVAL=0.25)
        net, ta, tb = _setup(k)
        fm = FailureMonitor(ta, k)
        loop = asyncio.get_running_loop()
        await asyncio.sleep(0.6)
        net.kill(B)
        t0 = loop.time()
        await fm.wait_for_failure(B)
        dt = loop.time() - t0
        await fm.close()
        return dt

    assert run_simulation(main(), seed=3) == run_simulation(main(), seed=3)
