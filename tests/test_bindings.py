"""L4/L5: the C ABI and the ctypes binding over it, against a live cluster.

Builds libfdbtpu_c.so, compiles the plain-C smoke program, and runs both
it and the Python-over-C binding's mini bindingtester (same op sequence
through the native client and the C-ABI client, results must agree —
REF:bindings/bindingtester)."""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import sysconfig
import time

import pytest

from foundationdb_tpu.core.cluster_file import ClusterFile
from foundationdb_tpu.rpc.transport import NetworkAddress

from test_server import free_ports

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def live_cluster(tmp_path_factory):
    ports = free_ports(3)
    cf = ClusterFile("bind", "t1",
                     [NetworkAddress("127.0.0.1", p) for p in ports])
    cf_path = tmp_path_factory.mktemp("bind") / "fdb.cluster"
    cf.save(str(cf_path))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    from test_server import spawn_server
    logdir = cf_path.parent
    procs = [spawn_server(
        [sys.executable, "-m", "foundationdb_tpu.server",
         "-C", str(cf_path), "-l", f"127.0.0.1:{p}",
         "--spec", "min_workers=3"], logdir / f"server-{p}.log", env)
        for p in ports]
    yield str(cf_path)
    for pr in procs:
        pr.send_signal(signal.SIGTERM)
    for pr in procs:
        try:
            pr.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pr.kill()
            pr.wait()


def test_c_abi_smoke_program(live_cluster, tmp_path):
    """Plain C through the ABI: build, link against libfdbtpu_c, run."""
    from foundationdb_tpu.native.build import build
    lib = build("fdbtpu_c")
    exe = str(tmp_path / "c_smoke")
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    subprocess.run(
        ["g++", "-o", exe, os.path.join(REPO, "bindings/c/test_c_smoke.c"),
         "-I", os.path.join(REPO, "bindings/c"), "-I", inc,
         lib, f"-L{libdir}",
         "-lpython" + sysconfig.get_config_var("LDVERSION"),
         f"-Wl,-rpath,{os.path.dirname(lib)}", f"-Wl,-rpath,{libdir}"],
        check=True, capture_output=True, text=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run([exe, live_cluster], env=env, capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "C ABI SMOKE OK" in r.stdout


def test_python_binding_over_c_abi(live_cluster):
    """Mini bindingtester: the ctypes-over-C binding and the native client
    run the same operations; every observation must agree."""
    script = f'''
import sys
sys.path.insert(0, {os.path.join(REPO, "bindings/python")!r})
import fdbtpu

db = fdbtpu.open({live_cluster!r})

def ops(tr):
    tr.set(b"bt1", b"v1")
    tr.set(b"bt2", b"v2")
    assert tr.get(b"bt1") == b"v1"      # RYW through the ABI
db.run(ops)

def check(tr):
    assert tr.get(b"bt1") == b"v1"
    assert tr.get(b"bt2") == b"v2"
    assert tr.get(b"btmissing") is None
    tr.clear(b"bt1")
db.run(check)

def check2(tr):
    assert tr.get(b"bt1") is None
    assert tr.get(b"bt2") == b"v2"
db.run(check2)

def extended(tr):
    # the v2 ABI surface: range reads, atomics, GRV, options
    tr.set_option("lock_aware")
    for i in range(5):
        tr.set(b"rng%02d" % i, b"x%d" % i)
    tr.add(b"ctr", (7).to_bytes(8, "little"))
db.run(extended)

def check3(tr):
    rows = tr.get_range(b"rng", b"rng\\xff")
    assert rows == [(b"rng%02d" % i, b"x%d" % i) for i in range(5)], rows
    rev = tr.get_range(b"rng", b"rng\\xff", limit=2, reverse=True)
    assert rev == [(b"rng04", b"x4"), (b"rng03", b"x3")], rev
    assert tr.get(b"ctr") == (7).to_bytes(8, "little")
    tr.add(b"ctr", (5).to_bytes(8, "little"))
db.run(check3)

def check4(tr):
    assert tr.get(b"ctr") == (12).to_bytes(8, "little")
    assert tr.get_read_version() > 0
    try:
        tr.set_option("no_such_option")
        raise AssertionError("unknown option accepted")
    except fdbtpu.FdbtpuError as e:
        assert e.code == 2007, e.code
db.run(check4)
print("PY-OVER-C OK")
'''
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "PY-OVER-C OK" in r.stdout

    # cross-check through the NATIVE client: the C binding's writes are
    # visible and exact
    script2 = f'''
import asyncio
from foundationdb_tpu.cli import open_cli
from foundationdb_tpu.runtime.knobs import Knobs

async def main():
    cli = await open_cli({live_cluster!r}, Knobs(), timeout=30)
    out = await cli.execute("get bt2")
    assert out == "`bt2' is `v2'", out
    out = await cli.execute("get bt1")
    assert "not found" in out, out
    print("NATIVE-XCHECK OK")
asyncio.run(main())
'''
    r2 = subprocess.run([sys.executable, "-c", script2], env=env,
                        capture_output=True, text=True, timeout=300)
    assert r2.returncode == 0, f"stdout={r2.stdout}\nstderr={r2.stderr}"
    assert "NATIVE-XCHECK OK" in r2.stdout
