"""Abort-parity gate (BASELINE.md: "a correctness gate, not just a perf
one"): encoded backends may only widen conservatively, fat transactions
ride the exact sidecar, and the aggregate abort-rate delta on a
range-heavy workload stays bounded.
"""

from __future__ import annotations

from foundationdb_tpu.bench.abort_parity import (RangeHeavyWorkload,
                                                 parity_knobs, run_parity)
from foundationdb_tpu.ops.batch import TxnRequest


def _knobs(r=8):
    return parity_knobs(RESOLVER_RANGES_PER_TXN=r)


def test_range_heavy_abort_parity_gate():
    report = run_parity(_knobs(), "numpy", n_batches=40, batch_size=24,
                        seed=7)
    # the shadow replay audits EVERY txn (960 here), so an unsafe
    # verdict anywhere in the run — not just before the first benign
    # divergence — fails the gate
    assert report["txns_audited"] == 40 * 24
    assert report["safety_violations"] == 0
    # fat txns ride the exact sidecar, so coalescing-at-R contributes
    # ~nothing; the audited residual is the sidecar's deliberate
    # over-approximation (it counts even kernel-aborted slim txns'
    # writes — conservative by design) plus fixed-width key-encoding
    # widening.  Both must stay a hair's breadth from exact.
    assert report["widening_aborts_coalescing"] <= 2, report
    assert report["widening_aborts_encoding"] <= 4, report
    assert report["abort_rel_delta"] < 0.15, report


def test_fat_txn_exact_routing_matches_cpp():
    """Batches of ONLY fat transactions (every txn over the R bucket)
    must produce verdicts identical to the exact backend — they all ride
    the sidecar.  A disjoint priming fat txn births the sidecar below
    every later snapshot so the whole run is exact-routable."""
    from foundationdb_tpu.ops.backends import make_conflict_backend
    wl = RangeHeavyWorkload(fat_fraction=1.0, fat_ranges=14, seed=3)
    batches, versions = wl.make_batches(12, 16)
    knobs = _knobs()
    exact = make_conflict_backend(
        knobs.override(RESOLVER_CONFLICT_BACKEND="cpp"))
    enc = make_conflict_backend(
        knobs.override(RESOLVER_CONFLICT_BACKEND="numpy"))
    prime = [TxnRequest([(b"zzp0", b"zzp1")] * 14, [], 980_000)]
    assert enc.resolve(prime, 990_000) == exact.resolve(prime, 990_000)
    for txns, v in zip(batches, versions):
        assert enc.resolve(txns, v) == exact.resolve(txns, v)


def test_fat_txn_never_misses_pre_sidecar_slim_write():
    """A slim-only batch commits before the sidecar exists; a later fat
    txn reading that write with an old snapshot must still CONFLICT
    (it coalesces — the sidecar's history can't be trusted below its
    birth version), and once snapshots pass the birth version fat txns
    ride the sidecar with complete history.  Verdicts must equal the
    exact backend's throughout."""
    from foundationdb_tpu.ops.backends import make_conflict_backend
    from foundationdb_tpu.ops.batch import CONFLICT
    knobs = _knobs(r=2)
    enc = make_conflict_backend(
        knobs.override(RESOLVER_CONFLICT_BACKEND="numpy"))
    exact = make_conflict_backend(
        knobs.override(RESOLVER_CONFLICT_BACKEND="cpp"))
    k = lambda i: b"pre%06d" % i
    fat_reads = [(k(i), k(i + 1)) for i in range(0, 26, 2)]

    rounds = [
        # slim-only: sidecar must not yet exist
        ([TxnRequest([], [(k(4), k(5))], 1_000_000)], 1_001_000),
        # fat reads the pre-sidecar write, old snapshot -> CONFLICT
        ([TxnRequest(fat_reads, [], 1_000_500)], 1_002_000),
        # slim write the (now live) sidecar ingests
        ([TxnRequest([], [(k(8), k(9))], 1_002_500)], 1_003_000),
        # fat reads it with a post-birth snapshot -> exact-routed CONFLICT
        ([TxnRequest(fat_reads, [], 1_002_500)], 1_004_000),
    ]
    got = [enc.resolve(t, v) for t, v in rounds]
    want = [exact.resolve(t, v) for t, v in rounds]
    assert got == want, (got, want)
    assert got[1] == [CONFLICT] and got[3] == [CONFLICT]


def test_hybrid_slim_sees_fat_writes():
    """A slim txn reading a range a PREVIOUS fat txn wrote must conflict:
    the fat txn's (coalesced) writes enter the kernel ring."""
    from foundationdb_tpu.ops.backends import make_conflict_backend
    from foundationdb_tpu.ops.batch import CONFLICT, COMMITTED
    knobs = _knobs(r=2)
    enc = make_conflict_backend(
        knobs.override(RESOLVER_CONFLICT_BACKEND="numpy"))
    k = lambda i: b"hy%06d" % i
    fat = TxnRequest([], [(k(i), k(i + 1)) for i in range(0, 12, 2)],
                     1_000_000)
    [v0] = enc.resolve([fat], 1_001_000)
    assert v0 == COMMITTED
    slim = TxnRequest([(k(4), k(5))], [], 1_000_500)  # read below commit
    [v1] = enc.resolve([slim], 1_002_000)
    assert v1 == CONFLICT, "fat txn's write invisible to kernel check"
