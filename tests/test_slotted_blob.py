"""SlottedBlob (rpc/wire.py) — the shared dual-slot crc-framed persist
(ISSUE 13, ROADMAP 6 (f)): the one audited corruption-policy mechanism
the lsm MANIFEST, coordinator state and backup logs.manifest now ride.
Site-level recovery behavior stays covered by their own suites
(test_lsm / test_coordination / test_backup_feed / test_disk_faults);
this file pins the helper's own invariants."""

import asyncio

import pytest

from foundationdb_tpu.rpc.wire import SlottedBlob
from foundationdb_tpu.runtime.files import SimFileSystem


def _run(coro):
    return asyncio.run(coro)


def test_round_trip_and_alternation():
    async def main():
        fs = SimFileSystem()
        sb = SlottedBlob(fs, "state")
        payload, seen = await sb.load()
        assert payload is None and seen == 0
        await sb.save(b"one")
        await sb.save(b"two")
        await sb.save(b"three")
        # both slot files populated: writes alternate
        assert fs.open("state.a").size() > 0
        assert fs.open("state.b").size() > 0
        # a fresh reader sees the newest
        sb2 = SlottedBlob(fs, "state")
        payload, seen = await sb2.load()
        assert payload == b"three" and seen == 2
        # ...and continues the alternation (seq learned from load)
        await sb2.save(b"four")
        sb3 = SlottedBlob(fs, "state")
        payload, _ = await sb3.load()
        assert payload == b"four"

    _run(main())


def test_torn_slot_loses_to_intact_one():
    async def main():
        fs = SimFileSystem()
        sb = SlottedBlob(fs, "state")
        await sb.save(b"committed")
        await sb.save(b"newer")
        # find the slot holding "newer" and tear it (garbage bytes)
        for suffix in (".a", ".b"):
            f = fs.open("state" + suffix)
            raw = await f.read(0, f.size())
            try:
                from foundationdb_tpu.rpc.wire import unframe
                if unframe(raw)[len(SlottedBlob.MAGIC) + 8:] == b"newer":
                    await f.write(0, b"\x00garbage\xff" * 4)
                    await f.truncate(36)
                    await f.sync()
            finally:
                await f.close()
        payload, seen = await SlottedBlob(fs, "state").load()
        assert payload == b"committed"      # the older intact slot wins
        assert seen == 2                    # ...and the caller can see
        #                                     both slots existed (its
        #                                     none-decodes policy input)

    _run(main())


def test_both_slots_torn_reports_none_with_evidence():
    async def main():
        fs = SimFileSystem()
        sb = SlottedBlob(fs, "state")
        await sb.save(b"x")
        await sb.save(b"y")
        for suffix in (".a", ".b"):
            f = fs.open("state" + suffix)
            await f.write(0, b"junkjunkjunkjunk")
            await f.truncate(16)
            await f.sync()
            await f.close()
        payload, seen = await SlottedBlob(fs, "state").load()
        # the helper NEVER guesses: payload None + slots_seen 2 is the
        # evidence each site's corruption policy keys on
        assert payload is None and seen == 2

    _run(main())


def test_failed_save_retries_same_slot():
    """seq advances only after the sync: a save that dies mid-write
    must re-target the SAME slot on retry, never the slot holding the
    freshest synced state (the DiskQueue _write_header discipline)."""
    async def main():
        fs = SimFileSystem()
        sb = SlottedBlob(fs, "state")
        await sb.save(b"good")              # lands in one slot
        good_slot = sb._slot(sb._seq)
        victim = sb._slot(sb._seq + 1)      # where the next save goes

        class Boom(Exception):
            pass

        real_open = fs.open
        calls = {"n": 0}

        def failing_open(path):
            f = real_open(path)
            if path == victim and calls["n"] == 0:
                calls["n"] += 1

                async def bad_write(off, data):
                    raise Boom()
                f.write = bad_write
            return f

        fs.open = failing_open
        with pytest.raises(Boom):
            await sb.save(b"torn")
        fs.open = real_open
        # the retry targets the SAME slot; the good slot is untouched
        assert sb._slot(sb._seq + 1) == victim
        await sb.save(b"retried")
        payload, _ = await SlottedBlob(fs, "state").load()
        assert payload == b"retried"
        f = real_open(good_slot)
        from foundationdb_tpu.rpc.wire import unframe
        raw = unframe(await f.read(0, f.size()))
        assert raw[len(SlottedBlob.MAGIC) + 8:] == b"good"
        await f.close()

    _run(main())


def test_pre_helper_slot_format_is_not_misparsed():
    """Migration guard: an ISSUE-12-era slot is ``frame(encode(dict))``
    — it passes ``unframe``, and without the envelope magic its first 8
    content bytes would parse as a garbage seq (~2.5e17) and the
    mis-sliced remainder would come back as a "valid" payload, crashing
    every caller's decode and making their legacy fallbacks
    unreachable.  The helper must return None (with the slot counted in
    the evidence) and leave the save seq unpoisoned."""
    async def main():
        from foundationdb_tpu.rpc.wire import encode, frame
        fs = SimFileSystem()
        old = frame(encode({"seq": 3, "r": [1, 1], "w": [2, 2],
                            "v": b"state", "m": None}))
        f = fs.open("state.a")
        await f.write(0, old)
        await f.sync()
        await f.close()
        sb = SlottedBlob(fs, "state")
        payload, seen = await sb.load()
        assert payload is None          # not ours to parse
        assert seen == 1                # ...but it IS evidence
        assert sb._seq == 0             # garbage seq must not poison
        #                                 the alternation parity
        # the caller's migration seeding (sb._seq = legacy seq) then
        # steers the next save AWAY from the only valid old slot
        sb._seq = 3
        await sb.save(b"migrated")      # seq 4 -> slot .b
        f = fs.open("state.a")
        assert await f.read(0, f.size()) == old     # untouched
        await f.close()
        payload, _ = await SlottedBlob(fs, "state").load()
        assert payload == b"migrated"

    _run(main())


def test_coordinator_recovers_pre_helper_slot():
    """End-to-end migration: a coordinator restarting on a disk written
    by the ISSUE-12-era dual-slot code must recover its committed
    quorum state through the legacy fallback, not crash-loop on it."""
    async def main():
        from foundationdb_tpu.core.coordination import Coordinator
        from foundationdb_tpu.rpc.wire import encode, frame
        from foundationdb_tpu.runtime.knobs import Knobs
        fs = SimFileSystem()
        old = frame(encode({"seq": 3, "r": [1, 1], "w": [2, 2],
                            "v": b"quorum-state", "m": None}))
        f = fs.open("coord.a")
        await f.write(0, old)
        await f.sync()
        await f.close()
        co = await Coordinator.open(Knobs(), fs, "coord")
        assert co.value == b"quorum-state"
        assert co.write_gen == (2, 2)
        assert co.max_read_gen == (1, 1)

    _run(main())
