"""Feed-native backup (ISSUE 8): whole-database change feeds, packed
snapshot containers, and point-in-time restore-to-version.

Coverage: the BackupContainer's crc-framed packed layout (round trips,
torn-frame detection, newest-snapshot-at-or-below selection), the
rewritten agent's resume-token discipline (a killed agent resumes
exactly-once from the logs.manifest ``through`` frontier — no proxy-side
backup tag), crashed-restore resumability through the progress fence,
the database-level start_backup/stop_backup/restore API with the
cluster.backup status rollup, and — at the bottom — the acceptance sim:
under buggify + attrition (including killing and restarting the backup
agent mid-stream), a restored FRESH cluster's user keyspace is
sha256-byte-identical to the source's at the target version, with the
.mlog files holding every acked mutation exactly once.
"""

from __future__ import annotations

import asyncio

import pytest

from foundationdb_tpu.backup.agent import BackupAgent, RestoreError
from foundationdb_tpu.backup.container import (BackupContainer,
                                               ContainerError,
                                               keyspace_digest, pack_rows,
                                               unpack_rows)
from foundationdb_tpu.client.database import Database
from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
from foundationdb_tpu.core.data import SYSTEM_PREFIX, Mutation, MutationBatch
from foundationdb_tpu.runtime.files import SimFileSystem
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


# THE byte-identity check of the acceptance criterion — one definition,
# shared with the bench stage and the perf smoke (backup/container.py)
digest = keyspace_digest


async def read_user_keyspace(db, at_version=None):
    tr = db.create_transaction()
    while True:
        try:
            if at_version is not None:
                tr.set_read_version(at_version)
            return await tr.get_range(b"", SYSTEM_PREFIX, limit=0,
                                      snapshot=True)
        except Exception as e:   # noqa: BLE001 — retry loop
            await tr.on_error(e)


async def commit_kv(db, key: bytes, val: bytes) -> int:
    tr = db.create_transaction()
    while True:
        try:
            tr.set(key, val)
            return await tr.commit()
        except BaseException as e:
            await tr.on_error(e)


# --- container layout ---

def test_pack_rows_roundtrip():
    rows = [(b"a", b"1"), (b"b", b""), (b"c" * 40, b"v" * 300), (b"d", b"x")]
    assert unpack_rows(*pack_rows(rows)) == rows
    assert unpack_rows(*pack_rows([])) == []


def test_container_snapshot_and_log_roundtrip():
    async def main():
        fs = SimFileSystem()
        c = BackupContainer(fs, "bk")
        await c.init()
        await c.init()          # idempotent
        rows = [(b"k%03d" % i, b"v%d" % i) for i in range(50)]
        name, n = await c.write_snapshot_page(700, 0, rows)
        assert n > 0
        v, got = await c.read_snapshot_page(name)
        assert v == 700 and got == rows
        await c.finish_snapshot(700, [name], 50, n)
        # a second, later snapshot joins the container
        name2, n2 = await c.write_snapshot_page(900, 0, rows[:10])
        await c.finish_snapshot(900, [name2], 10, n2)
        snaps = await c.list_snapshots()
        assert [m["version"] for m in snaps] == [700, 900]
        assert (await c.latest_snapshot_at_or_below(899))["version"] == 700
        assert (await c.latest_snapshot_at_or_below(900))["version"] == 900
        assert await c.latest_snapshot_at_or_below(699) is None

        # mutation-log files carry the packed MutationBatch columns
        mb = MutationBatch.from_mutations([
            Mutation.set(b"x", b"1"), Mutation.clear_range(b"y", b"z")])
        lname, _ = await c.write_log_file(701, 710, 0, [(701, mb), (710, mb)])
        entries = await c.read_log_file(lname)
        assert [v for v, _b in entries] == [701, 710]
        assert entries[0][1].types == mb.types
        assert entries[0][1].blob == mb.blob
        await c.save_log_manifest({"feed": b"f", "begin": 700,
                                   "through": 710,
                                   "files": [[701, 710, lname]],
                                   "bytes": 10, "stopped": False})
        meta = await c.load_log_manifest()
        assert meta["through"] == 710 and not meta["stopped"]
        d = await c.describe()
        assert d["log_through"] == 710 and len(d["snapshots"]) == 2
    asyncio.run(main())


def test_container_detects_torn_frame():
    async def main():
        fs = SimFileSystem()
        c = BackupContainer(fs, "bk2")
        rows = [(b"k", b"v" * 64)]
        name, _ = await c.write_snapshot_page(5, 0, rows)
        path = "bk2/" + name
        # flip one payload byte on "disk": the crc must catch it
        fs.disks[path][20] ^= 0xFF
        with pytest.raises(ContainerError):
            await c.read_snapshot_page(name)
        # truncate to a torn header
        del fs.disks[path][4:]
        with pytest.raises(ContainerError):
            await c.read_snapshot_page(name)
    asyncio.run(main())


# --- the resume token discipline (agent killed + restarted) ---

def test_agent_kill_resume_exactly_once():
    """Kill the tailing agent mid-stream (task cancelled + unsynced file
    bytes dropped — the SimFile crash model), resume a FRESH agent from
    the container alone, and prove the .mlog set holds every acked
    mutation exactly once at its exact commit version."""
    async def main():
        k = Knobs().override(BACKUP_LOG_FLUSH_INTERVAL=0.05)
        fs = SimFileSystem()
        async with Cluster(ClusterConfig(storage_servers=2), k) as cluster:
            db = Database(cluster)
            agent = BackupAgent(db, fs, "bk-resume")
            await agent.start_continuous()
            committed: list[tuple[bytes, int]] = []
            for i in range(8):
                committed.append((b"ra%02d" % i,
                                  await commit_kv(db, b"ra%02d" % i, b"A")))
            # drain phase A into the container, then CRASH the agent
            deadline = asyncio.get_running_loop().time() + 60
            while agent.log_through < committed[-1][1]:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)
            agent._pull_task.cancel()
            try:
                await agent._pull_task
            except asyncio.CancelledError:
                pass
            fs.kill_unsynced()
            # writes keep flowing while no agent is alive — the FEED
            # retains them (that is the whole point: no TLog tag, no
            # proxy state, just the cursor's begin_version)
            for i in range(8):
                committed.append((b"rb%02d" % i,
                                  await commit_kv(db, b"rb%02d" % i, b"B")))
            agent2 = BackupAgent(db, fs, "bk-resume")
            resumed_at = await agent2.resume_continuous()
            assert resumed_at >= agent.log_through
            for i in range(4):
                committed.append((b"rc%02d" % i,
                                  await commit_kv(db, b"rc%02d" % i, b"C")))
            await agent2.stop_continuous()

            # exactly-once: every acked (key, version) appears in the
            # manifest-listed .mlog files exactly once
            meta = await agent2.container.load_log_manifest()
            assert meta["stopped"]
            seen: dict[bytes, list[int]] = {}
            for _f, _l, name in meta["files"]:
                for v, mb in await agent2.container.read_log_file(str(name)):
                    for t, p1, _p2 in mb.iter_ops():
                        if t == 0:
                            seen.setdefault(p1, []).append(v)
            for key, ver in committed:
                assert seen.get(key) == [ver], \
                    f"{key!r}: logged {seen.get(key)} vs committed {ver}"
            # version windows of the manifest files never overlap (the
            # zero-duplicate structural check)
            spans = sorted((f, l) for f, l, _n in meta["files"])
            for (f1, l1), (f2, _l2) in zip(spans, spans[1:]):
                assert l1 < f2, f"overlapping log files: {spans}"
    run_simulation(main())


# --- crashed-restore resumability (the progress fence) ---

def test_restore_resumes_after_crash():
    async def main():
        k = Knobs()
        fs = SimFileSystem()
        async with Cluster(ClusterConfig(), k) as cluster:
            db = Database(cluster)
            agent = BackupAgent(db, fs, "bk-crash", rows_per_file=200)

            async def fill(tr):
                for i in range(1200):
                    tr.set(b"cr%05d" % i, b"v%05d" % i)
            await db.run(fill)
            await agent.backup()
            expected = await read_user_keyspace(db)

        async with Cluster(ClusterConfig(), k) as c2:
            db2 = Database(c2)
            await db2.set(b"junk", b"pre-restore")
            agent2 = BackupAgent(db2, fs, "bk-crash")
            # crash the first restore attempt mid-plan
            task = asyncio.ensure_future(agent2.restore())
            await asyncio.sleep(0.4)
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            # resume: fenced chunks already committed are skipped, the
            # wipe is NOT re-run, and the result is byte-identical
            await agent2.restore(resume=True)
            got = await read_user_keyspace(db2)
            assert digest(got) == digest(expected)
            # the fence key is cleaned up
            assert await db2.get(
                b"\xff/backup/restore_progress") is None
    run_simulation(main())


def test_restore_to_version_picks_snapshot_at_or_below():
    """Two snapshots in one container: a restore targeting a version
    between them must stream the OLDER snapshot and replay the log gap —
    and refuse a target below the earliest snapshot."""
    async def main():
        k = Knobs()
        fs = SimFileSystem()
        async with Cluster(ClusterConfig(), k) as cluster:
            db = Database(cluster)
            agent = BackupAgent(db, fs, "bk-two")
            await agent.start_continuous()
            await db.set(b"s1", b"one")
            m1 = await agent.backup()
            vt = await commit_kv(db, b"between", b"yes")
            await db.set(b"s2", b"two")
            m2 = await agent.backup()
            assert m2.version > m1.version >= 0
            expected = await read_user_keyspace(db, at_version=vt)
            await db.set(b"after", b"no")
            await agent.stop_continuous()

        async with Cluster(ClusterConfig(), k) as c2:
            db2 = Database(c2)
            agent2 = BackupAgent(db2, fs, "bk-two")
            assert m1.version <= vt < m2.version
            await agent2.restore(to_version=vt)
            got = await read_user_keyspace(db2)
            assert digest(got) == digest(expected)
            assert dict(got).get(b"between") == b"yes"
            assert b"s2" not in dict(got) and b"after" not in dict(got)
            with pytest.raises(RestoreError):
                await agent2.restore(to_version=max(0, m1.version - 10))
    run_simulation(main())


# --- database-level API + status rollup ---

def test_database_backup_api_and_status_rollup():
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.core.status import cluster_status
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        knobs = Knobs().override(BACKUP_PROGRESS_INTERVAL=0.25)
        sim = SimulatedCluster(knobs, n_machines=4,
                               spec=ClusterConfigSpec(min_workers=4))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()
        agent = await db.start_backup(SimFileSystem(), "bk-api")
        for i in range(6):
            await commit_kv(db, b"api%02d" % i, b"v%d" % i)
        # progress publishes reach the system keyspace and the status
        # aggregator's cluster.backup rollup
        deadline = asyncio.get_running_loop().time() + 60
        while True:
            ct = sim.client_transport()
            doc = await cluster_status(sim.knobs, ct,
                                       sim.coordinator_stubs(ct))
            bk = doc["cluster"]["backup"]
            if bk["active"] >= 1:
                break
            assert asyncio.get_running_loop().time() < deadline, bk
            await asyncio.sleep(0.5)
        a = [x for x in bk["agents"] if x["name"] == "bk-api"][0]
        assert not a["stopped"]
        assert a["snapshot_version"] is not None
        assert a["log_through"] > 0
        assert a["lag_versions"] >= 0
        vt = await commit_kv(db, b"api-marker", b"end")
        expected = await read_user_keyspace(db, at_version=vt)
        through = await db.stop_backup("bk-api")
        assert through >= vt

        # restore-to-version into a FRESH cluster via the db-level API
        async with Cluster(ClusterConfig(), Knobs()) as c2:
            db2 = Database(c2)
            await db2.restore(agent.fs, "bk-api", to_version=vt)
            got = await read_user_keyspace(db2)
            assert digest(got) == digest(expected)
        await sim.stop()

    run_simulation(main(), seed=11)


# --- the acceptance sim (ISSUE 8) ---

def test_sim_restore_to_version_byte_identical_under_chaos():
    """The acceptance criterion verbatim: under buggify + attrition —
    a storage machine killed and rebooted mid-stream AND the backup
    agent killed and restarted mid-stream — the restored fresh
    cluster's user keyspace is sha256-byte-identical to the source's at
    the target version, and the .mlog set holds zero duplicate and zero
    lost mutations (the exactly-once cursor discipline extended to the
    backup path)."""
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.runtime.buggify import enable_buggify
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    knobs = Knobs().override(BUGGIFY_ENABLED=True,
                             BACKUP_LOG_FLUSH_INTERVAL=0.1,
                             BACKUP_PROGRESS_INTERVAL=0.5)
    enable_buggify(True)

    async def main():
        sim = SimulatedCluster(knobs, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6,
                                                      replication=2),
                               durable_storage=True)
        await sim.start()
        state = await sim.wait_epoch(1)
        db = await sim.database()
        fs = SimFileSystem()
        loop = asyncio.get_running_loop()

        committed: list[tuple[bytes, int]] = []
        unknown: set[bytes] = set()

        async def write(key: bytes, val: bytes) -> None:
            from foundationdb_tpu.runtime.errors import CommitUnknownResult
            tr = db.create_transaction()
            while True:
                try:
                    tr.set(key, val)
                    committed.append((key, await tr.commit()))
                    return
                except BaseException as e:
                    if isinstance(e, CommitUnknownResult):
                        unknown.add(key)      # unique key; never retried
                        return
                    await tr.on_error(e)

        # phase A, then arm the backup (snapshot + whole-db feed tail)
        for i in range(8):
            await write(b"cha%03d" % i, b"A%d" % i)
        agent = await db.start_backup(fs, "bk-chaos")

        # phase B under chaos: kill a feed-replica machine, keep
        # writing, kill the AGENT, reboot the machine, resume the agent
        for i in range(8):
            await write(b"chb%03d" % i, b"B%d" % i)
        coord_ips = {a.ip for a in sim.coord_addrs}
        replica_ips = [s["worker"][0] for s in state["storage"]
                       if s["begin"] <= b"chb" < s["end"]]
        victims = [ip for ip in replica_ips if ip not in coord_ips] \
            or replica_ips
        machine = next(m for m in sim.machines if m.ip == victims[0])
        await machine.kill()
        for i in range(8):
            await write(b"chc%03d" % i, b"C%d" % i)
        # the agent "crashes": task killed, unsynced container bytes lost
        agent._pull_task.cancel()
        try:
            await agent._pull_task
        except asyncio.CancelledError:
            pass
        fs.kill_unsynced()
        await machine.reboot()
        for i in range(8):
            await write(b"chd%03d" % i, b"D%d" % i)
        agent2 = BackupAgent(db, fs, "bk-chaos")
        await agent2.resume_continuous()

        # the restore target: a marker commit mid-stream; phase E after
        # it must NOT appear in the restored keyspace
        await write(b"ch-marker", b"at-target")
        tip = max(v for _k, v in committed)
        expected = await read_user_keyspace(db, at_version=tip)
        vt = tip
        for i in range(6):
            await write(b"che%03d" % i, b"E%d" % i)

        # drain + stop through the feed path, then restore into a
        # FRESH cluster
        deadline = loop.time() + 240
        while agent2.log_through < max(v for _k, v in committed):
            assert loop.time() < deadline, "backup tail stalled"
            await asyncio.sleep(0.25)
        await agent2.stop_continuous(drain_timeout=60.0)

        # zero duplicate / zero lost: every acked key logged exactly
        # once at its exact commit version; strays are maybe-committed
        meta = await agent2.container.load_log_manifest()
        logged: dict[bytes, list[int]] = {}
        for _f, _l, name in meta["files"]:
            for v, mb in await agent2.container.read_log_file(str(name)):
                for t, p1, _p2 in mb.iter_ops():
                    if t == 0:
                        logged.setdefault(p1, []).append(v)
        by_key = dict(committed)
        acked = set(by_key)
        for key in acked:
            if by_key[key] > meta["begin"]:
                # committed after the feed registration: in the log
                # exactly once, at the exact commit version
                assert logged.get(key) == [by_key[key]], (
                    f"{key!r}: logged {logged.get(key)} vs "
                    f"committed {by_key[key]}")
            else:
                # phase A predates the feed: covered by the snapshot,
                # never by the log (capture is strictly above begin)
                assert logged.get(key) is None, \
                    f"pre-registration key {key!r} leaked into the log"
        for key, vs in logged.items():
            assert key in acked or (key in unknown and len(vs) == 1), \
                f"stray logged key {key!r} x{len(vs)}"

        async with Cluster(ClusterConfig(), Knobs()) as fresh:
            fdb = Database(fresh)
            await fdb.restore(fs, "bk-chaos", to_version=vt)
            got = await read_user_keyspace(fdb)
            assert digest(got) == digest(expected), (
                f"restore-to-version diverged: {len(got)} restored rows "
                f"vs {len(expected)} expected")
            rows = dict(got)
            assert rows.get(b"ch-marker") == b"at-target"
            assert not any(k.startswith(b"che") for k in rows)
        await sim.stop()

    try:
        run_simulation(main(), seed=67)
    finally:
        enable_buggify(False)
