"""CoW B+tree engine: splits/compaction/recovery behind IKeyValueStore.

Reference: REF:fdbserver/VersionedBTree.actor.cpp (Redwood) — crash
semantics proven with the lossy sim filesystem, correctness with a
randomized differential test against a model map (the reference's
VersionedBTree unit tests run the same shape of randomized op stream).
"""

from __future__ import annotations

import random

import foundationdb_tpu.storage.btree as bt_mod
from foundationdb_tpu.client import Database
from foundationdb_tpu.core.cluster import Cluster, ClusterConfig
from foundationdb_tpu.runtime.files import SimFileSystem
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.storage.btree import BTreeKVStore
from foundationdb_tpu.storage.kv_store import OP_CLEAR, OP_SET


def test_btree_basic_and_recovery(monkeypatch):
    monkeypatch.setattr(bt_mod, "_LEAF_BYTES", 256)
    monkeypatch.setattr(bt_mod, "_FANOUT", 4)

    async def main():
        fs = SimFileSystem()
        kv = await BTreeKVStore.open(fs, "db/bt")
        for round_ in range(8):
            ops = [(OP_SET, b"k%03d" % i, b"r%d-%03d" % (round_, i))
                   for i in range(40)]
            await kv.commit(ops, {"durable_version": round_})
        assert kv.get(b"k005") == b"r7-005"
        assert kv.get(b"nope") is None
        assert len(kv) == 40
        await kv.commit([(OP_CLEAR, b"k010", b"k020")], {"durable_version": 9})
        assert kv.get(b"k015") is None
        assert len(kv) == 30
        rows = list(kv.range(b"k000", b"k999"))
        assert [k for k, _ in rows] == [b"k%03d" % i for i in range(40)
                                        if not (10 <= i < 20)]
        assert all(v == b"r7-%03d" % int(k[1:]) for k, v in rows)
        rrows = list(kv.range(b"k000", b"k999", reverse=True))
        assert rrows == list(reversed(rows))
        # sub-range + boundaries
        assert list(kv.range(b"k005", b"k012")) == rows[5:10]
        await kv.close()

        kv2 = await BTreeKVStore.open(fs, "db/bt")
        assert kv2.meta == {"durable_version": 9}
        assert kv2.get(b"k015") is None
        assert len(kv2) == 30
        assert list(kv2.range(b"k000", b"k999")) == rows
        await kv2.close()
    run_simulation(main())


def test_btree_crash_recovers_last_commit(monkeypatch):
    monkeypatch.setattr(bt_mod, "_LEAF_BYTES", 256)

    async def main():
        fs = SimFileSystem()
        kv = await BTreeKVStore.open(fs, "db/crash")
        await kv.commit([(OP_SET, b"a", b"1")], {"durable_version": 1})
        # stage tree writes for a second commit but DIE before the header
        # fsync: the data write below is unsynced, so the machine kill
        # models a torn commit at the worst point
        await kv._f.write(kv._end, b"\x00garbage-torn-node-bytes")
        fs.kill_unsynced()
        kv2 = await BTreeKVStore.open(fs, "db/crash")
        assert kv2.get(b"a") == b"1"
        assert kv2.meta == {"durable_version": 1}
        # and the engine keeps working past the torn tail
        await kv2.commit([(OP_SET, b"b", b"2")], {"durable_version": 2})
        assert kv2.get(b"b") == b"2"
        await kv2.close()

        kv3 = await BTreeKVStore.open(fs, "db/crash")
        assert kv3.get(b"a") == b"1" and kv3.get(b"b") == b"2"
        await kv3.close()
    run_simulation(main())


def test_btree_compaction_bounds_file(monkeypatch):
    monkeypatch.setattr(bt_mod, "_LEAF_BYTES", 256)
    monkeypatch.setattr(bt_mod, "_FANOUT", 4)
    monkeypatch.setattr(bt_mod, "_COMPACT_MIN", 4096)
    monkeypatch.setattr(bt_mod, "_COMPACT_FACTOR", 3)

    async def main():
        fs = SimFileSystem()
        kv = await BTreeKVStore.open(fs, "db/comp")
        # overwrite the same keys many times: dead nodes pile up, then
        # compaction rewrites into a fresh file
        for round_ in range(60):
            ops = [(OP_SET, b"k%02d" % i, b"%04d" % round_)
                   for i in range(20)]
            await kv.commit(ops, {"durable_version": round_})
        assert kv._fileno > 0, "compaction never ran"
        files = [p for p in fs.listdir("db/comp.bt.")]
        assert files == [kv._file_path(kv._fileno)], "old files not GCd"
        assert kv._end <= 64 * 1024
        assert list(kv.range(b"", b"\xff")) == \
            [(b"k%02d" % i, b"0059") for i in range(20)]
        await kv.close()
        kv2 = await BTreeKVStore.open(fs, "db/comp")
        assert list(kv2.range(b"", b"\xff")) == \
            [(b"k%02d" % i, b"0059") for i in range(20)]
        await kv2.close()
    run_simulation(main())


def test_btree_randomized_vs_model(monkeypatch):
    """Differential test: random op batches (sets, clears, overwrites,
    empty + meta-only commits, reopens) against a model dict."""
    monkeypatch.setattr(bt_mod, "_LEAF_BYTES", 200)
    monkeypatch.setattr(bt_mod, "_FANOUT", 3)
    monkeypatch.setattr(bt_mod, "_COMPACT_MIN", 2048)
    monkeypatch.setattr(bt_mod, "_COMPACT_FACTOR", 2)

    async def main():
        rng = random.Random(20260731)
        fs = SimFileSystem()
        kv = await BTreeKVStore.open(fs, "db/rand")
        model: dict[bytes, bytes] = {}

        def rkey():
            return b"%04d" % rng.randrange(300)

        for step in range(120):
            ops = []
            for _ in range(rng.randrange(1, 12)):
                if rng.random() < 0.25:
                    a, b = sorted((rkey(), rkey()))
                    ops.append((OP_CLEAR, a, b))
                    for k in [k for k in model if a <= k < b]:
                        del model[k]
                else:
                    k, v = rkey(), bytes([rng.randrange(256)]) * \
                        rng.randrange(1, 60)
                    ops.append((OP_SET, k, v))
                    model[k] = v
            await kv.commit(ops, {"durable_version": step})
            if rng.random() < 0.1:
                await kv.close()
                kv = await BTreeKVStore.open(fs, "db/rand")
            if rng.random() < 0.2:
                a, b = sorted((rkey(), rkey()))
                got = list(kv.range(a, b))
                want = sorted((k, v) for k, v in model.items() if a <= k < b)
                assert got == want, f"step {step}: range mismatch"
                assert list(kv.range(a, b, reverse=True)) == \
                    list(reversed(want))
        assert len(kv) == len(model)
        assert sorted(model.items()) == list(kv.range(b"", b"\xff\xff"))
        for k in (b"0000", b"0123", b"0299", b"zzzz"):
            assert kv.get(k) == model.get(k)
        await kv.close()
    run_simulation(main())


def test_cluster_restart_resume_on_btree_engine():
    """The durable-cluster restart test, on the B-tree engine."""
    async def main():
        fs = SimFileSystem()
        k = Knobs().override(STORAGE_ENGINE="btree")
        cluster = await Cluster.create(ClusterConfig(), k, fs=fs,
                                       data_dir="btclu")
        async with cluster:
            db = Database(cluster)
            for i in range(30):
                await db.set(b"p%02d" % i, b"v%02d" % i)
        cluster2 = await Cluster.create(ClusterConfig(), k, fs=fs,
                                        data_dir="btclu")
        async with cluster2:
            db2 = Database(cluster2)
            for i in range(30):
                assert await db2.get(b"p%02d" % i) == b"v%02d" % i
            rows = await db2.get_range(b"p", b"q", limit=0)
            assert len(rows) == 30
    run_simulation(main())
