"""The restarting/upgrade test tier (REF:tests/restarting/) driven by
spec files: a durable cluster stops mid-life, restarts as a "new
binary" (bumped protocol version), and must prove continuity — data
byte-for-byte, invariants green, multi-version client re-resolving
across the upgrade while pinned clients get cluster_version_changed.
"""

from __future__ import annotations

import os

import pytest

from foundationdb_tpu.client import multiversion as mv
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.spec import load_spec, run_spec

SPECS = os.path.join(os.path.dirname(__file__), "specs")


@pytest.fixture(autouse=True)
def _fresh_api_version():
    mv._reset_api_version_for_tests()
    yield
    mv._reset_api_version_for_tests()


def test_cycle_restart_upgrade_spec():
    spec = load_spec(os.path.join(SPECS, "cycle_restart.toml"))

    async def main():
        return await run_spec(spec, seed=11)

    r = run_simulation(main(), seed=11)
    assert r["restart"]["rows"] > 10
    assert r["restart"]["new_protocol"] == r["restart"]["old_protocol"] + 1
    assert r["restart"]["mv_client_switched"]
    assert "phase1" in r and "phase2" in r


def test_chaos_spec_runs():
    spec = load_spec(os.path.join(SPECS, "attrition_cycle.toml"))

    async def main():
        return await run_spec(spec, seed=3)

    r = run_simulation(main(), seed=3)
    assert "phase1" in r and "restart" not in r


def test_restart_without_protocol_bump():
    """Plain whole-cluster restart (same binary): old clients keep
    working, no version-changed error."""
    spec = {
        "config": {"machines": 4, "replication": 2,
                   "durableStorage": True, "buggify": False},
        "test": [{"testName": "Cycle", "nodeCount": 6,
                  "transactionsPerClient": 10}],
        "restart": {"protocolBump": False},
    }

    async def main():
        return await run_spec(spec, seed=7)

    r = run_simulation(main(), seed=7)
    assert r["restart"]["rows"] > 5
    assert r["restart"]["new_protocol"] == r["restart"]["old_protocol"]
    assert "mv_client_switched" not in r["restart"]
