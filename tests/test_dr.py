"""DR (cluster-to-cluster replication) + database lock.

Reference test model: REF:fdbclient/DatabaseBackupAgent.actor.cpp
(`fdbdr start/status/switch`) — a secondary cluster converges on the
primary's state, switchover is loss-free, and the database lock fences
the primary from non-lock-aware commits.
"""

from __future__ import annotations

import asyncio

from foundationdb_tpu.backup.dr import DRAgent, DrError
from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
from foundationdb_tpu.core.data import SYSTEM_PREFIX
from foundationdb_tpu.core.management import lock_database, unlock_database
from foundationdb_tpu.runtime.errors import DatabaseLocked
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation
from foundationdb_tpu.sim.cluster_sim import SimulatedCluster


async def _read_all(db, at_version=None):
    tr = db.create_transaction()
    tr.lock_aware = True
    while True:
        try:
            if at_version is not None:
                tr.set_read_version(at_version)
            rows = await tr.get_range(b"", SYSTEM_PREFIX, limit=0,
                                      snapshot=True)
            return dict(rows)
        except Exception as e:   # noqa: BLE001 — retry loop
            await tr.on_error(e)


async def _two_clusters(n_src=4, n_dest=4):
    src_sim = SimulatedCluster(Knobs(), n_machines=n_src,
                               spec=ClusterConfigSpec(min_workers=n_src))
    dest_sim = SimulatedCluster(Knobs(), n_machines=n_dest,
                                spec=ClusterConfigSpec(min_workers=n_dest))
    await src_sim.start()
    await dest_sim.start()
    await src_sim.wait_epoch(1)
    await dest_sim.wait_epoch(1)
    return src_sim, dest_sim, await src_sim.database(), \
        await dest_sim.database()


def test_dr_replicates_sets_clears_atomics():
    """Writes before AND after start() all converge on dest, including
    pre-snapshot state, clears, and order-sensitive atomic adds."""
    async def main():
        src_sim, dest_sim, src, dest = await _two_clusters()

        async def seed(tr):
            for i in range(30):
                tr.set(b"pre%03d" % i, b"S%d" % i)
            tr.add(b"counter", (7).to_bytes(8, "little"))
        await src.run(seed)

        dr = DRAgent(src, dest)
        await dr.start()

        for j in range(6):
            async def live(tr, j=j):
                tr.set(b"live%03d" % j, b"L%d" % j)
                tr.clear(b"pre%03d" % (j * 3))
                tr.add(b"counter", (3).to_bytes(8, "little"))
            await src.run(live)

        vd = await dr.drain()
        expected = await _read_all(src, at_version=vd)
        got = await _read_all(dest)
        got.pop(b"\xff/dr/applied", None)
        assert expected[b"counter"] == (25).to_bytes(8, "little")
        assert got == expected, (
            f"missing={sorted(set(expected) - set(got))[:4]} "
            f"extra={sorted(set(got) - set(expected))[:4]}")
        await dr.abort()
        await src_sim.stop()
        await dest_sim.stop()
    run_simulation(main())


def test_dr_switchover_is_loss_free_and_locks_source():
    """switchover(): every acked source commit is on dest; the source
    then refuses non-lock-aware commits; dest accepts writes."""
    async def main():
        src_sim, dest_sim, src, dest = await _two_clusters()
        dr = DRAgent(src, dest)
        await dr.start()

        for j in range(5):
            async def w(tr, j=j):
                tr.set(b"k%03d" % j, b"v%d" % j)
            await src.run(w)

        vd = await dr.switchover()
        expected = await _read_all(src, at_version=vd)
        got = await _read_all(dest)
        got.pop(b"\xff/dr/applied", None)
        assert got == expected

        # the source is fenced
        tr = src.create_transaction()
        tr.set(b"after", b"must-not-land")
        try:
            await tr.commit()
            raise AssertionError("locked source accepted a commit")
        except DatabaseLocked:
            pass

        # the destination is live and writable
        async def wd(tr):
            tr.set(b"dest-write", b"ok")
        await dest.run(wd)
        got2 = await _read_all(dest)
        assert got2[b"dest-write"] == b"ok"
        await src_sim.stop()
        await dest_sim.stop()
    run_simulation(main())


def test_dr_survives_source_recovery():
    """A source-side recovery mid-stream must not lose or duplicate
    mutations on dest (the tag re-arms from the \\xff read and the
    stream's cursor rolls generations)."""
    async def main():
        src_sim, dest_sim, src, dest = await _two_clusters(n_src=6)
        dr = DRAgent(src, dest)
        await dr.start()

        async def w(tr, tag, n):
            for i in range(n):
                tr.set(b"r%s%03d" % (tag, i), b"v-" + tag)
            tr.add(b"rc", (1).to_bytes(8, "little"))
        await src.run(lambda tr: w(tr, b"pre", 15))

        state1 = await src_sim.wait_epoch(1)
        victims = await src_sim.txn_only_machines()
        assert victims
        await victims[0].kill()
        await src_sim.wait_epoch(state1["epoch"] + 1)

        while True:
            tr = src.create_transaction()
            try:
                await w(tr, b"post", 15)
                await tr.commit()
                break
            except Exception as e:   # noqa: BLE001 — retry through recovery
                await tr.on_error(e)

        vd = await dr.drain(timeout=60.0)
        expected = await _read_all(src, at_version=vd)
        got = await _read_all(dest)
        got.pop(b"\xff/dr/applied", None)
        assert expected[b"rc"] == (2).to_bytes(8, "little")
        assert got == expected, (
            f"missing={sorted(set(expected) - set(got))[:4]} "
            f"extra={sorted(set(got) - set(expected))[:4]}")
        await dr.abort()
        await src_sim.stop()
        await dest_sim.stop()
    run_simulation(main())


def test_database_lock_semantics():
    """lock blocks plain commits (database_locked, non-retryable), spares
    lock-aware ones, refuses a mismatched unlock, and unlock restores
    service."""
    from foundationdb_tpu.core.management import DatabaseLockedByOther

    async def main():
        sim = SimulatedCluster(Knobs(), n_machines=4,
                               spec=ClusterConfigSpec(min_workers=4))
        await sim.start()
        await sim.wait_epoch(1)
        db = await sim.database()

        await lock_database(db, b"uid-1")

        tr = db.create_transaction()
        tr.set(b"x", b"1")
        try:
            await tr.commit()
            raise AssertionError("locked db accepted a plain commit")
        except DatabaseLocked:
            pass

        tr = db.create_transaction()
        tr.lock_aware = True
        tr.set(b"x", b"locked-write")
        await tr.commit()

        # a non-lock-aware STATE transaction is fenced BEFORE resolution:
        # its \xff mutations must never reach the proxies' metadata
        tr = db.create_transaction()
        tr.set(b"\xff/conf/resolvers", b"7")
        try:
            await tr.commit()
            raise AssertionError("locked db accepted a state txn")
        except DatabaseLocked:
            pass

        # relock under the same uid is idempotent; other uid refused
        await lock_database(db, b"uid-1")
        try:
            await lock_database(db, b"uid-2")
            raise AssertionError("second uid stole the lock")
        except DatabaseLockedByOther:
            pass
        try:
            await unlock_database(db, b"uid-2")
            raise AssertionError("mismatched unlock succeeded")
        except DatabaseLockedByOther:
            pass

        await unlock_database(db, b"uid-1")
        # a non-lock-aware STATE txn right after unlock: a proxy whose
        # local lock view is stale-locked must refresh (empty batch)
        # instead of spuriously rejecting with the non-retryable 1038
        from foundationdb_tpu.core.management import configure
        await configure(db, resolvers=1)
        async def w(tr):
            tr.set(b"y", b"after-unlock")
        await db.run(w)
        got = await _read_all(db)
        assert got[b"x"] == b"locked-write" and got[b"y"] == b"after-unlock"
        await sim.stop()
    run_simulation(main())


def test_lock_survives_recovery():
    """A lock committed moments before a crash must still fence the
    recovered cluster: recovery's metadata read waits for the storage
    replica to catch up to the recovery version (a lagging snapshot
    would silently recover unlocked — an unfenced primary after DR
    switchover)."""
    async def main():
        sim = SimulatedCluster(Knobs(), n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        db = await sim.database()

        await lock_database(db, b"uid-r")
        victims = await sim.txn_only_machines()
        assert victims
        await victims[0].kill()
        await sim.wait_epoch(state1["epoch"] + 1)

        # still fenced after recovery — commits AND reads
        while True:
            tr = db.create_transaction()
            tr.set(b"x", b"1")
            try:
                await tr.commit()
                raise AssertionError("recovered cluster dropped the lock")
            except DatabaseLocked:
                break
            except Exception as e:   # noqa: BLE001 — retry through recovery
                await tr.on_error(e)

        # lock-aware service still works, and unlock restores everything
        await unlock_database(db, b"uid-r")
        async def w(tr):
            tr.set(b"y", b"ok")
        await db.run(w)
        assert (await _read_all(db))[b"y"] == b"ok"
        await sim.stop()
    run_simulation(main())


def test_dest_locked_during_dr():
    """The destination refuses third-party writes while DR runs (the
    reference locks the secondary for exactly this), and opens up at
    switchover."""
    async def main():
        src_sim, dest_sim, src, dest = await _two_clusters()
        dr = DRAgent(src, dest)
        await dr.start()

        tr = dest.create_transaction()
        tr.set(b"intruder", b"x")
        try:
            await tr.commit()
            raise AssertionError("dest accepted a third-party write")
        except DatabaseLocked:
            pass

        async def w(tr):
            tr.set(b"k", b"v")
        await src.run(w)
        await dr.switchover()

        # dest is primary now: unlocked
        async def wd(tr):
            tr.set(b"after", b"ok")
        await dest.run(wd)
        got = await _read_all(dest)
        assert got[b"after"] == b"ok" and got[b"k"] == b"v"
        assert b"intruder" not in got
        await src_sim.stop()
        await dest_sim.stop()
    run_simulation(main())


def test_backup_and_dr_tags_coexist():
    """A named DR tag and the legacy file-backup tag stream concurrently:
    disarming one leaves the other armed (the proxy's named-slot map)."""
    from foundationdb_tpu.backup.agent import BackupAgent
    from foundationdb_tpu.runtime.files import SimFileSystem

    async def main():
        src_sim, dest_sim, src, dest = await _two_clusters()
        bk = BackupAgent(src, SimFileSystem(), "bk-dr")
        dr = DRAgent(src, dest)
        await bk.start_continuous()
        await bk.backup()
        await dr.start()

        for j in range(4):
            async def w(tr, j=j):
                tr.set(b"both%03d" % j, b"B%d" % j)
            await src.run(w)

        # disarm DR; backup keeps streaming
        vd = await dr.drain()
        await dr.abort()

        async def after(tr):
            tr.set(b"after-dr-abort", b"bk-only")
        await src.run(after)
        tr = src.create_transaction()
        while True:
            try:
                tr.set(b"marker", b"end")
                vt = await tr.commit()
                break
            except Exception as e:   # noqa: BLE001
                await tr.on_error(e)
        expected_src = await _read_all(src, at_version=vt)
        await bk.stop_continuous()

        # dest has the DR prefix
        got = await _read_all(dest)
        got.pop(b"\xff/dr/applied", None)
        assert got == await _read_all(src, at_version=vd)

        # the file backup restores the FULL stream incl. post-abort writes
        async def wipe(tr):
            tr.clear_range(b"", SYSTEM_PREFIX)
        await src.run(wipe)
        await bk.restore(to_version=vt)
        assert await _read_all(src) == expected_src
        await src_sim.stop()
        await dest_sim.stop()
    run_simulation(main())
