"""Split-phase (non-blocking) resolver: the TPU backend must not stall the
event loop, and consecutive batches must pipeline (batch N+1 submits to the
device while batch N's verdicts are still syncing back).

VERDICT r1 weak #3 / SURVEY §7 hard part 3: the resolver sits on the commit
critical path; a synchronous device sync per batch would stall every
coroutine in the process.  These tests run the ``tpu`` backend on the CPU
device stand-in under a *real* asyncio loop (executor threads are the
production path; the virtual-time simulator syncs inline instead).
"""

import asyncio

import pytest

from foundationdb_tpu.core.resolver import ResolveBatchRequest, Resolver
from foundationdb_tpu.ops.batch import TxnRequest
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


def _knobs(backend, fuse=True):
    return Knobs().override(RESOLVER_CONFLICT_BACKEND=backend,
                            CONFLICT_RING_CAPACITY=4096,
                            RESOLVER_GROUP_FUSION=fuse)


def _batches(n_batches, txns_per_batch):
    """Deterministic batch stream with genuine conflicts."""
    out = []
    ver = 0
    for b in range(n_batches):
        txns = []
        for t in range(txns_per_batch):
            key = b"k%03d" % ((b + t) % 10)
            txns.append(TxnRequest(
                read_ranges=[(key, key + b"\x00")],
                write_ranges=[(key, key + b"\x00")],
                read_snapshot=max(0, ver - 2_000_000)))
        prev, ver = ver, ver + 1_000_000
        out.append(ResolveBatchRequest(prev_version=prev, version=ver, txns=txns))
    return out


def _run_real_loop(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_tpu_backend_parity_on_real_loop():
    """Same verdicts from the split-phase tpu path and the sync numpy twin."""
    reqs = _batches(6, 8)

    async def run(backend):
        r = Resolver(_knobs(backend))
        return [(await r.resolve(req)).verdicts for req in reqs]

    got_tpu = _run_real_loop(run("tpu"))
    got_np = _run_real_loop(run("numpy"))
    assert got_tpu == got_np
    # batches genuinely contain conflicts, or this test proves nothing
    assert any(v != 0 for batch in got_np for v in batch)


def test_event_loop_live_during_resolve():
    """Other coroutines make progress while a batch resolves on device."""
    reqs = _batches(4, 16)

    async def run():
        r = Resolver(_knobs("tpu"))
        ticks = 0
        stop = False

        async def ticker():
            nonlocal ticks
            while not stop:
                ticks += 1
                await asyncio.sleep(0)

        t = asyncio.ensure_future(ticker())
        await asyncio.sleep(0)          # let the ticker start
        before = ticks
        for req in reqs:
            await r.resolve(req)
        during = ticks - before
        stop = True
        await t
        return during

    # every resolve awaits the executor sync, yielding the loop at least
    # once per batch — a blocking resolver would leave the ticker frozen
    assert _run_real_loop(run()) >= len(reqs)


def test_batches_pipeline_submit_before_prior_finish():
    """Batch N+1 must be submitted before batch N's verdict sync returns
    (the split-phase path; the fused path is covered separately)."""
    reqs = _batches(3, 8)
    events = []

    async def run():
        r = Resolver(_knobs("tpu", fuse=False))
        orig_begin = r.backend.resolve_begin

        def logged_begin(txns, version):
            events.append(("submit", version))
            fin = orig_begin(txns, version)

            async def wrapped():
                out = await fin
                events.append(("finish", version))
                return out

            return wrapped()

        r.backend.resolve_begin = logged_begin
        await asyncio.gather(*(r.resolve(req) for req in reqs))

    _run_real_loop(run())
    order = {e: i for i, e in enumerate(events)}
    v1, v2, v3 = (r.version for r in reqs)
    # submits happen in version order (serial history contract)...
    assert order[("submit", v1)] < order[("submit", v2)] < order[("submit", v3)]
    # ...and each later submit precedes the earlier batch's host sync
    assert order[("submit", v2)] < order[("finish", v1)]
    assert order[("submit", v3)] < order[("finish", v2)]


def test_resolver_fail_stops_after_sync_failure():
    """If verdict sync fails after the chain advanced, the resolver must
    fail-stop — its history may hold the failed batch's writes, so serving
    more verdicts would be unsound."""
    from foundationdb_tpu.runtime.errors import ResolverFailed

    reqs = _batches(3, 4)

    async def run():
        r = Resolver(_knobs("tpu", fuse=False))
        await r.resolve(reqs[0])

        async def boom():
            raise RuntimeError("device lost")

        orig = r.backend.resolve_begin
        r.backend.resolve_begin = lambda txns, v: boom()
        with pytest.raises(RuntimeError):
            await r.resolve(reqs[1])
        r.backend.resolve_begin = orig
        with pytest.raises(ResolverFailed):
            await r.resolve(reqs[2])

    _run_real_loop(run())


def test_fused_group_parity_and_pipelining():
    """The r5 group-fusion path: concurrent batches fuse into grouped
    dispatches, verdicts match the serial split-phase path bit for bit,
    and at least one dispatch carries more than one batch."""
    reqs = _batches(8, 8)

    async def run(fuse):
        r = Resolver(_knobs("tpu", fuse=fuse))
        outs = await asyncio.gather(*(r.resolve(req) for req in reqs))
        return [o.verdicts for o in outs], list(r.group_sizes)

    fused, sizes = _run_real_loop(run(True))
    serial, _ = _run_real_loop(run(False))
    assert fused == serial
    # all batches went through fused dispatches
    assert sum(sizes) == len(reqs)


def test_fused_fail_stop_poisons_queue():
    """A group sync failure must fail-stop the resolver and fail queued
    batches instead of hanging them."""
    from foundationdb_tpu.runtime.errors import ResolverFailed

    reqs = _batches(4, 4)

    async def run():
        r = Resolver(_knobs("tpu", fuse=True))
        await r.resolve(reqs[0])

        def boom(batches, versions):
            raise RuntimeError("device lost")

        r.backend.resolve_group_begin = boom
        results = await asyncio.gather(
            *(r.resolve(req) for req in reqs[1:3]), return_exceptions=True)
        assert all(isinstance(x, (ResolverFailed, RuntimeError))
                   for x in results), results
        with pytest.raises(ResolverFailed):
            await r.resolve(reqs[3])

    _run_real_loop(run())


def test_split_phase_under_simulation():
    """The sim loop forbids executors; the split-phase path must sync inline
    and stay deterministic."""
    reqs = _batches(5, 8)

    async def main():
        r = Resolver(_knobs("tpu"))
        return [(await r.resolve(req)).verdicts for req in reqs]

    a = run_simulation(main(), seed=7)
    b = run_simulation(main(), seed=7)
    assert a == b

    async def main_np():
        r = Resolver(_knobs("numpy"))
        return [(await r.resolve(req)).verdicts for req in reqs]

    assert run_simulation(main_np(), seed=7) == a
