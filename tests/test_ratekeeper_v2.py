"""Ratekeeper v2: per-tag throttling + priority admission lanes.

Reference: REF:fdbserver/Ratekeeper.actor.cpp + TagThrottler.actor.cpp —
when one transaction tag dominates demand while the cluster is limited,
that tag alone is clamped; batch-priority work yields the leftover
budget; immediate (system) work is never throttled.
"""

from __future__ import annotations

import asyncio

from foundationdb_tpu.core.grv_proxy import GrvProxy
from foundationdb_tpu.core.ratekeeper import Ratekeeper
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation


class OverloadedSS:
    """durable engine with its queue right at the target (worst = 1.0)."""
    tag = 0
    engine = object()
    bytes_input = 10_000
    bytes_durable = 0
    version = 0
    durable_version = 0


def _knobs():
    return Knobs().override(TARGET_STORAGE_QUEUE_BYTES=10_000,
                            RATEKEEPER_MAX_TPS=1000.0,
                            RATEKEEPER_MIN_TPS=5.0)


def test_hot_tag_throttled_cold_unaffected():
    async def main():
        rk = Ratekeeper(_knobs(), [OverloadedSS()], [])
        # build smoothed demand: the "hot" tag dominates the default lane
        for _ in range(8):
            await rk.admit(90, tags={"hot": 90})
            await rk.admit(10)                      # untagged cold work
            await rk._recompute()
        assert "hot" in rk.tag_rates, rk.limiting_reason
        assert rk.tag_rates["hot"] == 5.0           # clamped to the floor
        # the GLOBAL lane stays open: cold tenants don't pay
        assert rk.rate_tps == 1000.0
        assert "tag_throttle_hot" in rk.limiting_reason
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await rk.admit(50)                          # cold, untagged
        assert loop.time() - t0 < 1.0, "cold work was throttled"
        t0 = loop.time()
        await rk.admit(50, tags={"hot": 50})        # hot tag queues
        assert loop.time() - t0 >= 40 / 5.0
        # recovery: queue drains -> throttle lifts
        rk.storage_servers[0].bytes_durable = 10_000
        await rk._recompute()
        assert rk.tag_rates == {}
        t0 = loop.time()
        await rk.admit(50, tags={"hot": 50})
        assert loop.time() - t0 < 1.0
    run_simulation(main())


def test_cold_admit_not_blocked_by_draining_hot_tag():
    """A clamped hot tag sleeping on its own bucket must not hold the
    admission lock against concurrent cold work."""
    async def main():
        rk = Ratekeeper(_knobs(), [OverloadedSS()], [])
        for _ in range(8):
            await rk.admit(90, tags={"hot": 90})
            await rk._recompute()
        assert rk.tag_rates.get("hot") == 5.0
        loop = asyncio.get_running_loop()
        hot = asyncio.ensure_future(rk.admit(100, tags={"hot": 100}))
        await asyncio.sleep(0.1)        # hot is now draining its clamp
        t0 = loop.time()
        await rk.admit(20)              # cold, untagged
        assert loop.time() - t0 < 0.5, "cold blocked behind hot drain"
        assert not hot.done()
        hot.cancel()
        try:
            await hot
        except asyncio.CancelledError:
            pass
    run_simulation(main())


def test_idle_tag_demand_decays():
    """A tag that bursts and goes idle must not hijack a later overload:
    its smoothed demand decays, so the global throttle engages and the
    actual (untagged) offender is the one slowed."""
    async def main():
        rk = Ratekeeper(_knobs(), [OverloadedSS()], [])
        for _ in range(8):              # the burst
            await rk.admit(90, tags={"burst": 90})
            await rk._recompute()
        assert "burst" in rk.tag_rates
        for _ in range(12):             # tag idle; untagged load dominates
            await rk.admit(90)
            await rk._recompute()
        assert rk.tag_rates == {}, rk.tag_rates
        assert rk.rate_tps == 5.0       # global throttle does the work
        assert "storage_queue" in rk.limiting_reason
        assert rk._tag_tokens == {}     # bucket state pruned with it
    run_simulation(main())


def test_no_dominant_tag_falls_back_to_global_throttle():
    async def main():
        rk = Ratekeeper(_knobs(), [OverloadedSS()], [])
        for _ in range(8):
            # three tags at ~33% each: none crosses the 50% share bar
            await rk.admit(90, tags={"a": 30, "b": 30, "c": 30})
            await rk._recompute()
        assert rk.tag_rates == {}
        assert rk.rate_tps == 5.0
        assert "storage_queue" in rk.limiting_reason
    run_simulation(main())


def test_priority_lanes():
    async def main():
        k = _knobs()
        rk = Ratekeeper(k, [OverloadedSS()], [])
        # default demand ~ the whole budget: batch gets only the floor
        for _ in range(8):
            await rk.admit(int(1000 * k.RATEKEEPER_UPDATE_INTERVAL))
            await rk._recompute()
        assert rk.batch_rate_tps <= 2 * k.RATEKEEPER_MIN_TPS
        loop = asyncio.get_running_loop()
        # immediate: never throttled, even at the floor rate
        t0 = loop.time()
        await rk.admit(10_000, priority="immediate")
        assert loop.time() - t0 < 0.01
        # batch: crawls at the leftover rate
        t0 = loop.time()
        await rk.admit(30, priority="batch")
        assert loop.time() - t0 >= 20 / (2 * k.RATEKEEPER_MIN_TPS)
    run_simulation(main())


def test_grv_proxy_routes_lanes_and_tags():
    """The GRV proxy splits a mixed batch into lanes and forwards per-tag
    counts; immediate requests are served without admission delay even
    while the default lane is throttled hard."""
    class FakeSequencer:
        async def get_live_committed_version(self):
            return 42, None

    class RecordingRk(Ratekeeper):
        def __init__(self):
            super().__init__(_knobs(), [], [])
            self.calls = []

        async def admit(self, n, priority="default", tags=None):
            self.calls.append((priority, n, tags))
            if priority == "default":
                await asyncio.sleep(1.0)    # simulated throttle delay

    async def main():
        k = Knobs()
        rk = RecordingRk()
        proxy = GrvProxy(k, FakeSequencer(), rk)
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        futs = [asyncio.ensure_future(c) for c in (
            proxy.get_read_version(),
            proxy.get_read_version(False, "default", "hot"),
            proxy.get_read_version(False, "immediate"),
            proxy.get_read_version(False, "batch"))]
        # NO priority inversion: the immediate (and batch) lanes resolve
        # while the default lane is still sleeping in admission
        assert await asyncio.wait_for(asyncio.shield(futs[2]), 0.5) == 42
        assert loop.time() - t0 < 0.5
        results = await asyncio.gather(*futs)
        assert all(v == 42 for v in results)
        # lanes are per (priority, tag): the tagged default request is
        # admitted separately from the untagged one
        calls = sorted(((p, n, tags) for p, n, tags in rk.calls),
                       key=repr)
        assert calls == sorted([("default", 1, None),
                                ("default", 1, {"hot": 1}),
                                ("immediate", 1, None),
                                ("batch", 1, None)], key=repr), calls
        assert loop.time() - t0 >= 1.0      # default lanes were admitted
    run_simulation(main())


def test_transaction_carries_priority_and_tag():
    from foundationdb_tpu.client import Database
    from foundationdb_tpu.core.cluster import Cluster, ClusterConfig

    async def main():
        async with Cluster(ClusterConfig(), Knobs()) as cluster:
            db = Database(cluster)
            tr = db.create_transaction()
            tr.priority = "batch"
            tr.throttle_tag = "analytics"
            tr.set(b"k", b"v")
            await tr.commit()
            tr2 = db.create_transaction()
            assert await tr2.get(b"k") == b"v"
    run_simulation(main())
