"""Routed resolver mesh (ISSUE 16): verdict parity, the empty-clip
fast path, and heat-driven partition rebalance.

The load-bearing invariant is the differential one: a K-resolver ROUTED
mesh (sparse sub-batches + header-only version advances + AND-join
scatter) must return bit-identical verdicts to ONE merged resolver fed
the same txn stream — routing is a performance transform, never a
semantic one.  The harness replays randomized streams (boundary-
straddling ranges, state-txn singleton batches, header-only partitions)
through both shapes across seeds.
"""

from __future__ import annotations

import asyncio

import pytest

from foundationdb_tpu.core.data import KeyRange, Mutation
from foundationdb_tpu.core.resolver import (ResolveBatchRequest, Resolver,
                                            clip_txn_to_range)
from foundationdb_tpu.core.shard_load import rebalance_resolver_boundaries
from foundationdb_tpu.core.shard_map import ShardMap
from foundationdb_tpu.ops.batch import TxnRequest
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.simloop import run_simulation

# sim-scale conflict shapes (cluster_sim.py's rationale): production
# shapes scan seconds per batch on a CPU host
MESH_KNOBS = dict(RESOLVER_CONFLICT_BACKEND="numpy",
                  RESOLVER_BATCH_TXNS=16, RESOLVER_RANGES_PER_TXN=4,
                  CONFLICT_RING_CAPACITY=1 << 12, KEY_ENCODE_BYTES=16)


# --- the boundary-rebalance math (pure) ---

def _samples(weights: dict[bytes, float]) -> list[tuple[bytes, float]]:
    return sorted(weights.items())


def test_rebalance_balanced_mesh_is_left_alone():
    s = _samples({bytes([b]) + b"k": 1.0 for b in range(0, 240, 10)})
    assert rebalance_resolver_boundaries(s, [b"\x80"]) is None


def test_rebalance_moves_single_boundary_into_hot_half():
    # all heat below 0x80: the 2-partition boundary must move INTO the
    # hot half (split at its heat midpoint, merge the only pair)
    s = _samples({bytes([b]) + b"k": 10.0 for b in range(0, 0x40, 2)})
    new = rebalance_resolver_boundaries(s, [b"\x80"])
    assert new is not None and len(new) == 1
    assert b"" < new[0] < b"\x80"


def test_rebalance_preserves_partition_count():
    # 4 partitions, heat concentrated in the first: N must stay 4 —
    # hot splits, coldest adjacent pair merges
    hot = {bytes([b]) + b"h": 50.0 for b in range(0, 0x40, 4)}
    cold = {bytes([b]) + b"c": 1.0 for b in range(0x40, 0xF0, 8)}
    bounds = [b"\x40", b"\x80", b"\xc0"]
    new = rebalance_resolver_boundaries(_samples(hot | cold), bounds)
    assert new is not None and len(new) == 3
    assert new != bounds
    assert any(b < b"\x40" for b in new), "no split inside the hot range"


def test_rebalance_thin_or_single_key_signal_declines():
    # fewer than 4 in-partition samples, or one key with half the
    # weight: weighted_split_key has no honest midpoint — do nothing
    assert rebalance_resolver_boundaries(
        _samples({b"\x01a": 99.0}), [b"\x80"]) is None
    s = _samples({b"\x01a": 90.0, b"\x02b": 1.0, b"\x03c": 1.0,
                  b"\x04d": 1.0, b"\x05e": 1.0})
    assert rebalance_resolver_boundaries(s, [b"\x80"]) is None


# --- the empty-clip fast path ---

def test_empty_clip_fast_path_skips_backend():
    """A header-only request (no txns, no state txns) advances the
    version chain and returns instantly — conflict backend untouched,
    nothing dispatched — and the next REAL batch chains off it."""
    async def main():
        k = Knobs().override(RESOLVER_MESH_ROUTING=True, **MESH_KNOBS)
        r = Resolver(k, KeyRange(b"\x80", b"\xff\xff\xff"))
        t = TxnRequest([], [(b"\x90a", b"\x90b")], 90)
        rep = await r.resolve(ResolveBatchRequest(0, 100, [t]))
        assert rep.verdicts == [0]
        # header-only: the proxy's batch clipped empty on this partition
        rep = await r.resolve(ResolveBatchRequest(100, 200, []))
        assert rep.verdicts == []
        assert r.total_header_batches == 1
        assert r.total_batches == 1, "fast path must not touch the backend"
        assert r.version == 200, "the version chain must still advance"
        # the chain is intact: a real batch chained off the header-only
        # version resolves (a wedged chain would hang here)
        rep = await asyncio.wait_for(
            r.resolve(ResolveBatchRequest(200, 300, [t])), timeout=5.0)
        assert len(rep.verdicts) == 1
    run_simulation(main())


def test_fast_path_disabled_with_routing_off():
    """Broadcast twin: with the knob off an empty batch walks the normal
    path (keepalives did this forever) — the counter stays zero."""
    async def main():
        k = Knobs().override(RESOLVER_MESH_ROUTING=False, **MESH_KNOBS)
        r = Resolver(k, KeyRange(b"", b"\xff\xff\xff"))
        rep = await r.resolve(ResolveBatchRequest(0, 100, []))
        assert rep.verdicts == []
        assert r.total_header_batches == 0
        assert r.version == 100
    run_simulation(main())


# --- the verdict-parity harness (the differential twins) ---
#
# Two twins, two invariants:
#
# 1. routed mesh == BROADCAST mesh, bit-identical, on fully random
#    streams (boundary-straddling ranges, state singletons, header-only
#    partitions).  This is THE invariant of the routing transform: the
#    sparse sub-batch + empty-clip fast path + scatter must be
#    observationally equal to clipping-and-broadcasting.
#
# 2. routed mesh == one MERGED resolver, bit-identical, on
#    partition-coherent streams (each batch's conflict ranges inside one
#    partition — the range-partitioned workload the routed mesh is built
#    for, and the live A/B's shape).  On streams where a txn straddles
#    partitions AND fails on only one of them, ANY mesh — broadcast or
#    routed, here and in the reference — is strictly MORE conservative
#    than a merged resolver: the passing partition applies the txn's
#    writes to its window (it cannot know the other partition's verdict
#    without a cross-resolver round), so a later overlapping txn in the
#    window can see an extra conflict.  That corner is one-sided —
#    asserted below as containment: the mesh never COMMITS a txn the
#    merged resolver aborts.


def _random_txn(rng, version: int, band: int | None = None) -> TxnRequest:
    """Conflict ranges over a byte-prefixed keyspace.  ``band=None``
    draws boundary-straddling and point ranges anywhere; a concrete band
    keeps every range inside one ShardMap.even(2/4) partition.
    Snapshots stay inside the write-life window so the too-old floors
    never fire (TOO_OLD is version-arithmetic, not range-clipping)."""
    def rand_range():
        if band is None:
            b0 = rng.randrange(0, 240)
            b = bytes([b0]) + bytes([rng.randrange(97, 123)])
            if rng.random() < 0.3:  # boundary-straddling wide range
                hi = min(240, b0 + rng.randrange(1, 60))
                e = bytes([hi]) + bytes([rng.randrange(97, 123)])
            else:                   # point-ish range
                e = b + b"\x01"
        else:
            b = bytes([band]) + bytes([rng.randrange(97, 123)])
            e = b + b"\x01"
        return (min(b, e), max(b, e) + b"\x00")
    reads = [rand_range() for _ in range(rng.randrange(0, 3))]
    writes = [rand_range() for _ in range(rng.randrange(1, 3))]
    return TxnRequest(reads, writes, max(0, version - rng.randrange(0, 400)))


async def _ask_routed(mesh, prev, version, txns):
    """The proxy's routed send, distilled: sparse sub-batch per
    partition (header-only when it clips empty), verdicts scattered
    through the index map into the AND-join."""
    final = [0] * len(txns)

    async def ask(r: Resolver):
        sub, idx = [], []
        for i, t in enumerate(txns):
            ct = clip_txn_to_range(t, r.key_range)
            if ct.read_ranges or ct.write_ranges:
                sub.append(ct)
                idx.append(i)
        rep = await r.resolve(ResolveBatchRequest(prev, version, sub))
        return rep, idx
    for rep, idx in await asyncio.gather(*(ask(r) for r in mesh)):
        assert len(rep.verdicts) == len(idx)
        for j, v in zip(idx, rep.verdicts):
            final[j] = max(final[j], v)
    return final


async def _ask_broadcast(mesh, prev, version, txns):
    """The broadcast twin's send: every resolver gets ALL txns, clipped
    (empty-range rows ride along as padding)."""
    async def ask(r: Resolver):
        sent = [clip_txn_to_range(t, r.key_range) for t in txns]
        return await r.resolve(ResolveBatchRequest(prev, version, sent))
    final = [0] * len(txns)
    for rep in await asyncio.gather(*(ask(r) for r in mesh)):
        for i, v in enumerate(rep.verdicts):
            final[i] = max(final[i], v)
    return final


async def _ask_state(resolvers, prev, version, txns, state):
    """State-txn singleton batch: unclipped, alone, to every resolver;
    all verdicts must agree (the verdict-agreement invariant that keeps
    every resolver's committed-state stream identical)."""
    replies = await asyncio.gather(*(
        r.resolve(ResolveBatchRequest(prev, version, txns, state))
        for r in resolvers))
    assert len({rep.verdicts[0] for rep in replies}) == 1, \
        "state-txn verdict must agree across the whole mesh"
    return [replies[0].verdicts[0]]


async def _drive_parity(seed: int, K: int, coherent: bool,
                        n_batches: int = 40) -> None:
    import random
    rng = random.Random(seed)
    k = Knobs().override(RESOLVER_MESH_ROUTING=True, **MESH_KNOBS)
    res_map = ShardMap.even(K)
    routed = [Resolver(k, res_map.shard_range(i)) for i in range(K)]
    bcast = [Resolver(k, res_map.shard_range(i)) for i in range(K)]
    merged = Resolver(k, KeyRange(b"", b"\xff\xff\xff"))

    version = 0
    for bi in range(n_batches):
        prev, version = version, version + rng.randrange(50, 200)
        band = rng.randrange(0, 240) if coherent else None
        if rng.random() < 0.1:
            txns = [_random_txn(rng, version, band)]
            state = [(0, [Mutation.set(b"\xff/parity/%d" % bi, b"v")])]
            vr = await _ask_state(routed, prev, version, txns, state)
            vb = await _ask_state(bcast, prev, version, txns, state)
            vm = (await merged.resolve(
                ResolveBatchRequest(prev, version, txns, state))).verdicts
        else:
            txns = [_random_txn(rng, version, band)
                    for _ in range(rng.randrange(1, 8))]
            vr = await _ask_routed(routed, prev, version, txns)
            vb = await _ask_broadcast(bcast, prev, version, txns)
            vm = (await merged.resolve(
                ResolveBatchRequest(prev, version, txns))).verdicts
        assert vr == vb, (
            f"seed={seed} K={K} batch={bi}: routed {vr} != broadcast {vb}")
        if coherent:
            assert vr == vm, (
                f"seed={seed} K={K} batch={bi}: routed {vr} "
                f"!= merged {vm}")
        else:
            # straddling streams: the mesh may be strictly MORE
            # conservative than merged, never less — a mesh COMMIT is
            # always a merged COMMIT
            for i, (a, b) in enumerate(zip(vr, vm)):
                assert not (a == 0 and b != 0), (
                    f"seed={seed} K={K} batch={bi} txn={i}: the mesh "
                    f"committed ({a}) what merged aborted ({b})")


@pytest.mark.parametrize("seed", [1, 7, 23, 1234])
@pytest.mark.parametrize("K", [2, 4])
def test_routed_mesh_verdict_parity_coherent(seed: int, K: int):
    """Range-partitioned streams (the routed mesh's target workload):
    routed == broadcast == merged, bit-identical, across seeds/widths."""
    run_simulation(_drive_parity(seed, K, coherent=True))


@pytest.mark.parametrize("seed", [2, 11, 47, 4321])
@pytest.mark.parametrize("K", [2, 4])
def test_routed_mesh_verdict_parity_straddling(seed: int, K: int):
    """Adversarial streams (boundary-straddling ranges): routed ==
    broadcast bit-identical, and the mesh is one-sided-safe vs merged."""
    run_simulation(_drive_parity(seed, K, coherent=False))


def test_routed_mesh_parity_three_way_split():
    # odd K: uneven byte-prefix boundaries exercise clip edges the
    # power-of-two maps never produce
    run_simulation(_drive_parity(99, 3, coherent=True, n_batches=25))


# --- heat-driven rebalance, end to end in the sim ---

def test_heat_rebalance_moves_resolver_boundary():
    """Sustained one-sided load on a 2-resolver mesh: DD's rollup must
    write a desired boundary INSIDE the hot half, and the next epoch's
    recruitment must apply it (the state-txn remap; windows rebuild from
    the tlogs like any recovery)."""
    from foundationdb_tpu.core.cluster_controller import ClusterConfigSpec
    from foundationdb_tpu.core.system_data import RESOLVER_BOUNDARIES_KEY
    from foundationdb_tpu.rpc.wire import decode
    from foundationdb_tpu.sim.cluster_sim import SimulatedCluster

    async def main():
        k = Knobs().override(
            DD_ENABLED=True, DD_INTERVAL=0.5,
            DD_SHARD_SPLIT_BYTES=1 << 24,        # size policy silent
            RESOLVER_REBALANCE=True,
            RESOLVER_REBALANCE_RATIO=1.5,
            RESOLVER_REBALANCE_SUSTAIN_ROUNDS=2,
            DD_HEAT_COOLDOWN_S=5.0,
            SHARD_HEAT_HALFLIFE=3.0)
        sim = SimulatedCluster(k, n_machines=6,
                               spec=ClusterConfigSpec(min_workers=6,
                                                      resolvers=2))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        assert [bytes(r["begin"]) for r in state1["resolvers"]] \
            == [b"", b"\x80"]
        db = await sim.database()

        stop = asyncio.Event()

        async def writer(wid: int) -> None:
            i = 0
            while not stop.is_set():
                i += 1

                async def do(tr, i=i):
                    # every write lands BELOW 0x80: partition 0 carries
                    # all the routed load, partition 1 only headers
                    for j in range(5):
                        tr.set(bytes([(i * 5 + j) % 0x60]) +
                               b"hot%03d" % wid, b"v" * 20)
                await db.run(do)
                await asyncio.sleep(0.03)

        tasks = [asyncio.ensure_future(writer(w)) for w in range(3)]

        async def desired_written():
            while True:
                raw = await db.get(RESOLVER_BOUNDARIES_KEY)
                if raw:
                    return [bytes(b) for b in decode(raw)]
                await asyncio.sleep(0.5)
        desired = await asyncio.wait_for(desired_written(), timeout=60.0)
        stop.set()
        await asyncio.gather(*tasks)
        assert len(desired) == 1 and b"" < desired[0] < b"\x80", desired
        dd = sim.leader_dd()
        assert dd is not None and dd.resolver_rebalances >= 1

        # the remap applies at the next epoch boundary: kill the machine
        # hosting a resolver so recovery recruits on the new ranges
        res_ip = state1["resolvers"][0]["addr"][0]
        victim = next(m for m in sim.machines if m.ip == res_ip)
        await victim.kill()
        state2 = await asyncio.wait_for(
            sim.wait_epoch(state1["epoch"] + 1), timeout=60.0)
        bounds2 = sorted(bytes(r["begin"]) for r in state2["resolvers"]
                         if bytes(r["begin"]))
        assert bounds2 == desired, (bounds2, desired)
        await sim.stop()

    run_simulation(main())
