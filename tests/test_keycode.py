"""Property tests for the order-preserving fixed-width key encoding."""

import numpy as np
import pytest

from foundationdb_tpu.ops import keycode
from foundationdb_tpu.runtime import DeterministicRandom

W = 16  # smaller width in tests to hit truncation paths often


def rand_key(rng, maxlen=24, alphabet=4):
    n = rng.random_int(0, maxlen + 1)
    # tiny alphabet maximizes shared prefixes / ties
    return bytes(rng.random_int(0, alphabet) for _ in range(n))


def test_encode_exact_order_short_keys():
    rng = DeterministicRandom(1)
    keys = [rand_key(rng, maxlen=W) for _ in range(300)] + [b"", b"\x00", b"\x00" * W]
    enc = keycode.encode_keys(keys, W)
    for i in range(0, len(keys), 7):
        for j in range(len(keys)):
            a, b = keys[i], keys[j]
            lt = keycode.lex_lt(enc[i], enc[j])
            eq = keycode.lex_eq(enc[i], enc[j])
            assert bool(lt) == (a < b), (a, b)
            assert bool(eq) == (a == b), (a, b)


def test_encode_monotone_long_keys():
    rng = DeterministicRandom(2)
    keys = sorted(rand_key(rng, maxlen=40) for _ in range(300))
    enc = keycode.encode_keys(keys, W)
    for i in range(len(keys) - 1):
        # a <= b  =>  enc(a) <= enc(b):  never enc(b) < enc(a)
        assert not bool(keycode.lex_lt(enc[i + 1], enc[i])), (keys[i], keys[i + 1])


def test_possibly_lt_conservative():
    """true a<b implies possibly_lt; exact when not both-truncated."""
    rng = DeterministicRandom(3)
    keys = [rand_key(rng, maxlen=40) for _ in range(200)]
    enc = keycode.encode_keys(keys, W)
    for i in range(0, len(keys), 5):
        for j in range(len(keys)):
            a, b = keys[i], keys[j]
            plt = bool(keycode.possibly_lt(enc[i], enc[j], W))
            if a < b:
                assert plt, (a, b)           # no false negatives, ever
            both_trunc = len(a) > W and len(b) > W and a[:W] == b[:W]
            if not both_trunc:
                assert plt == (a < b), (a, b)  # exact outside the ambiguous case


def test_encode_key_matches_batch_encode():
    rng = DeterministicRandom(4)
    keys = [rand_key(rng, maxlen=40, alphabet=256) for _ in range(100)]
    batch = keycode.encode_keys(keys, W)
    for i, k in enumerate(keys):
        np.testing.assert_array_equal(batch[i], keycode.encode_key(k, W))


def test_sentinel_above_everything():
    rng = DeterministicRandom(5)
    S = keycode.sentinel(W)
    for _ in range(100):
        k = keycode.encode_key(rand_key(rng, maxlen=40, alphabet=256), W)
        assert bool(keycode.lex_lt(k, S))
        assert not bool(keycode.possibly_lt(S, k, W))
